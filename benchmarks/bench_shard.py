#!/usr/bin/env python
"""Pod-scale embedding-sharding benchmark: row-sharded all-to-all
lookups vs replicated tables vs table-dim sharding.

Measures, on the attached mesh (CPU-virtual or real accelerator):

- ``steps_per_s_{replicated,row_sharded,table_sharded}`` — steady-state
  training rate of the same DLRM under the three table placements:
  pure data-parallel (every device holds every table), PARAM-axis row
  sharding (each device holds rows/N of every table, lookups routed by
  explicit all-to-all — the ZionEX/DLRM-Terabyte shape), and classic
  table-dim sharding (each device holds whole tables);
- ``row_vs_replicated`` — the headline ratio (the paper's bar: >= 1.5x
  pure DP on tables that fit no single device);
- ``a2a_bytes_per_step`` — all-to-all bytes one device exchanges per
  step under the balanced exchange model (ids out, rows back, gradient
  rows out);
- ``sim_pod_sweep`` — cost-model step times for replicated vs
  row-sharded plans on simulated pod topologies (flat ICI 8, 2 slices
  x 4 over DCN, 8 slices x 8 = v5e-64), where the replicated plan goes
  INFEASIBLE once the tables exceed per-chip HBM.

Prints ONE JSON line (the BENCH_*.json convention); `measure()` is also
imported by bench.py when BENCH_SHARD=1.

Usage: python benchmarks/bench_shard.py [--steps N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# big enough that the table working set dwarfs caches and the sparse
# update dominates; small enough that N replicated copies fit host RAM
ROWS = int(os.environ.get("BENCH_SHARD_ROWS", "131072"))
TABLES = 8
DIM = 64


def _build(ndev, batch, mode, bag=1):
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig

    dcfg = DLRMConfig(embedding_size=[ROWS] * TABLES,
                      sparse_feature_size=DIM,
                      embedding_bag_size=bag,
                      mlp_bot=[DIM, 128, DIM],
                      mlp_top=[DIM * (TABLES + 1), 128, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0))
    build_dlrm(model, dcfg)
    strat = {}
    row_kw = {
        "row_sharded": {},
        "dedup": {"exchange": "dedup"},
        "hybrid": {"exchange": "dedup", "hot_fraction": 1.0 / 64},
    }
    for op in model.ops:
        tn = type(op).__name__
        nd = op.outputs[0].num_dims if op.outputs else 0
        if tn == "EmbeddingBagStacked":
            if mode in row_kw:
                strat[op.name] = ParallelConfig((ndev, 1, 1),
                                                param_degree=ndev,
                                                **row_kw[mode])
            elif mode == "table_sharded":
                dt = next(d for d in range(min(ndev, TABLES), 0, -1)
                          if TABLES % d == 0 and ndev % d == 0)
                strat[op.name] = ParallelConfig((1, dt, 1))
            else:
                strat[op.name] = ParallelConfig.data_parallel(nd, ndev)
        elif nd:
            strat[op.name] = ParallelConfig.data_parallel(nd, ndev)
    model.compile(ff.SGDOptimizer(lr=0.05), "mean_squared_error",
                  ["mse"], mesh=make_mesh(devices=jax.devices()[:ndev]),
                  strategies=strat)
    model.init_layers()
    return model, dcfg


def _steps_per_s(model, batches, steps):
    model.train_batch_device(batches[0])          # warm/compile
    t0 = time.perf_counter()
    mets = None
    for s in range(steps):
        mets = model.train_batch_device(batches[s % len(batches)])
    float(mets["loss"])                           # true completion
    return steps / (time.perf_counter() - t0)


def _sim_pod_sweep(ndev):
    """Cost-model pricing of replicated vs row-sharded plans across pod
    topologies, with an HBM cap the replicated tables exceed."""
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
    from dlrm_flexflow_tpu.search.cost_model import CostModel, TPUSpec
    from dlrm_flexflow_tpu.search.mcmc import default_strategy
    from dlrm_flexflow_tpu.search.simulator import Simulator

    dcfg = DLRMConfig.random_benchmark()          # 8 x 1M x 64 (2 GB)
    out = {}
    for label, topo, n in [
        ("ici8", [("ici", 8)], 8),
        ("dcn2xici4", [("dcn", 2), ("ici", 4)], 8),
        ("dcn8xici8_v5e64", [("dcn", 8), ("ici", 8)], 64),
    ]:
        model = ff.FFModel(ff.FFConfig(batch_size=256 * n))
        build_dlrm(model, dcfg)
        model.optimizer = ff.SGDOptimizer(lr=0.1)
        emb = next(op for op in model.ops
                   if type(op).__name__ == "EmbeddingBagStacked")
        dp = default_strategy(model, n)
        row = dict(dp)
        row[emb.name] = ParallelConfig((n, 1, 1), param_degree=n)
        # 1 GB "HBM": the 2 GB replicated tables cannot fit, the row
        # shards can — the memory-feasibility half of the row-shard case
        sim_cap = Simulator(model, CostModel(
            spec=TPUSpec(hbm_capacity_bytes=1e9)), topology=topo)
        sim = Simulator(model, CostModel(), topology=topo)
        t_dp, t_row = sim.simulate(dp, n), sim.simulate(row, n)
        out[label] = {
            "sim_step_ms_replicated": round(1e3 * t_dp, 4),
            "sim_step_ms_row_sharded": round(1e3 * t_row, 4),
            "row_vs_replicated_sim": round(t_dp / t_row, 3),
            "replicated_feasible_at_1gb_hbm":
                sim_cap.simulate(dp, n) != float("inf"),
            "row_sharded_feasible_at_1gb_hbm":
                sim_cap.simulate(row, n) != float("inf"),
        }
    return out


def _skew_sweep(ndev, steps):
    """Skew sweep (ISSUE 11): alpha in {0 (uniform), 0.8, 1.0, 1.2}
    comparing the dense vs dedup'd vs hybrid exchange on the CPU mesh —
    steps/s plus the MEASURED balanced exchange bytes, computed from
    the actual per-device DISTINCT id counts of the benchmark batches
    (the dedup'd exchange's valid traffic scales with these, not with
    batch size; the hybrid's cold stream excludes hot hits on top)."""
    import jax
    import numpy as np

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.parallel.alltoall import \
        exchange_bytes_per_step

    batch = 64 * ndev
    bag = 4           # multi-hot bags are where duplicates concentrate
    out = {}
    for alpha in (0.0, 0.8, 1.0, 1.2):
        entry = {}
        batches_np = []
        for i in range(4):
            x, y = synthetic_batch(
                _bench_dcfg(bag), batch, seed=i, zipf_alpha=alpha)
            x["label"] = y
            batches_np.append(x)
        for mode in ("row_sharded", "dedup", "hybrid"):
            model, dcfg = _build(ndev, batch, mode, bag=bag)
            emb = next(op for op in model.ops
                       if type(op).__name__ == "EmbeddingBagStacked")
            plan = emb._row_plan
            if mode == "dedup":
                # measured distinct cold ids per device per step
                per_dev = batch // ndev
                dcounts = []
                for x in batches_np:
                    flat = emb.flat_lookup_ids(x["sparse"]).reshape(
                        batch, -1)
                    for d in range(ndev):
                        dcounts.append(len(np.unique(
                            flat[d * per_dev:(d + 1) * per_dev])))
                entry["measured_distinct_per_dev"] = round(
                    float(np.mean(dcounts)), 1)
                entry["a2a_bytes_dedup"] = exchange_bytes_per_step(
                    plan, batch * TABLES * bag, DIM,
                    distinct_per_device=float(np.mean(dcounts)))
                entry["a2a_bytes_dense"] = exchange_bytes_per_step(
                    plan, batch * TABLES * bag, DIM)
            staged = [model._device_batch(dict(x)) for x in batches_np]
            jax.block_until_ready(staged)
            entry[f"steps_per_s_{mode}"] = round(
                _steps_per_s(model, staged, steps), 3)
            del model, staged
        out[f"alpha_{alpha:g}"] = entry
    return out


def _bench_dcfg(bag):
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    return DLRMConfig(embedding_size=[ROWS] * TABLES,
                      sparse_feature_size=DIM, embedding_bag_size=bag,
                      mlp_bot=[DIM, 128, DIM],
                      mlp_top=[DIM * (TABLES + 1), 128, 1])


def _sim_skew_dcn():
    """The ISSUE 11 perf bar: >= 2x simulated step time vs the dense
    exchange at zipf(1.0) on the DCN topology — a production-scale
    step (multi-hot bag 32, 2048 samples/device, fused supersteps)
    where the exchange + touched-rows scatter dominate, priced from an
    observed zipf(1.0) histogram."""
    import numpy as np

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.data.dataloader import zipf_indices
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
    from dlrm_flexflow_tpu.search.cost_model import CostModel
    from dlrm_flexflow_tpu.search.mcmc import default_strategy
    from dlrm_flexflow_tpu.search.simulator import Simulator
    from dlrm_flexflow_tpu.utils.histogram import IdFrequencySketch

    n = 8
    dcfg = DLRMConfig(embedding_size=[1000000] * 8,
                      embedding_bag_size=32, sparse_feature_size=64,
                      mlp_bot=[64, 512, 512, 64],
                      mlp_top=[576, 1024, 1024, 1024, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=2048 * n, superstep=8))
    build_dlrm(model, dcfg)
    model.optimizer = ff.SGDOptimizer(lr=0.1)
    emb = next(op for op in model.ops
               if type(op).__name__ == "EmbeddingBagStacked")
    rng = np.random.RandomState(0)
    sk = IdFrequencySketch(8 * 1000000)
    for t in range(8):
        sk.observe(zipf_indices(rng, 1000000, 400000, 1.0)
                   + t * 1000000)
    model.attach_id_histograms({emb.name: sk})
    dp = default_strategy(model, n)

    def plan(**kw):
        s = dict(dp)
        s[emb.name] = ParallelConfig((n, 1, 1), param_degree=n, **kw)
        return s

    sim = Simulator(model, CostModel(), topology=[("dcn", 8)])
    t_dense = sim.simulate(plan(), n)
    t_dedup = sim.simulate(plan(exchange="dedup"), n)
    t_hyb = sim.simulate(plan(exchange="dedup", hot_fraction=1 / 64), n)
    return {
        "sim_step_ms_dense": round(1e3 * t_dense, 3),
        "sim_step_ms_dedup": round(1e3 * t_dedup, 3),
        "sim_step_ms_hybrid": round(1e3 * t_hyb, 3),
        "dedup_vs_dense_sim": round(t_dense / t_dedup, 3),
        "hybrid_vs_dense_sim": round(t_dense / t_hyb, 3),
    }


def _sim_overlap_dcn():
    """The ISSUE 19 perf bar: >= 1.5x simulated step time from the
    pipelined exchange on the DCN topology — a multi-hot production
    shape (4 x 1M x 384-d tables, bag 64, 2048 samples/device) where
    the row-shard all-to-all dwarfs the dense window, so decomposing it
    into ppermute rounds that ride under the gather/scatter is the
    whole step. Also runs a short MCMC walk from scratch to show the
    search picks the pipelined plan unforced."""
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
    from dlrm_flexflow_tpu.search.cost_model import CostModel
    from dlrm_flexflow_tpu.search.mcmc import default_strategy, optimize
    from dlrm_flexflow_tpu.search.simulator import Simulator

    n, T, d = 8, 4, 384
    dcfg = DLRMConfig(embedding_size=[1000000] * T,
                      embedding_bag_size=64, sparse_feature_size=d,
                      mlp_bot=[64, 512, 512, d],
                      mlp_top=[d * (T + 1), 512, 512, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=2048 * n))
    build_dlrm(model, dcfg)
    model.optimizer = ff.SGDOptimizer(lr=0.1)
    emb = next(op for op in model.ops
               if type(op).__name__ == "EmbeddingBagStacked")
    dp = default_strategy(model, n)
    sim = Simulator(model, CostModel(), topology=[("dcn", 8)])

    def t(**kw):
        s = dict(dp)
        s[emb.name] = ParallelConfig((n, 1, 1), param_degree=n, **kw)
        return sim.simulate(s, n)

    t_ser, t_ovl = t(), t(overlap=True)
    best = optimize(model, budget=400, ndev=n, seed=3,
                    topology=[("dcn", 8)])
    best_pc = best[emb.name]
    return {
        "sim_step_ms_serial": round(1e3 * t_ser, 3),
        "sim_step_ms_overlap": round(1e3 * t_ovl, 3),
        "overlap_vs_serial_sim": round(t_ser / t_ovl, 3),
        "mcmc_picked_overlap":
            bool(getattr(best_pc, "overlap", False))
            and getattr(best_pc, "param_degree", 1) > 1,
        "sim_step_ms_mcmc_best": round(1e3 * sim.simulate(best, n), 3),
    }


def measure(steps: int = 12):
    import jax

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.parallel.alltoall import \
        exchange_bytes_per_step

    ndev = len(jax.devices())
    batch = 64 * ndev
    out = {"ndev": ndev, "rows": ROWS, "tables": TABLES, "dim": DIM,
           "batch": batch}

    modes = ["replicated", "row_sharded"]
    if ndev > 1 and TABLES % 2 == 0:
        modes.append("table_sharded")
    dcfg = None
    for mode in modes:
        model, dcfg = _build(ndev, batch, mode)
        if mode == "row_sharded":
            emb = next(op for op in model.ops
                       if type(op).__name__ == "EmbeddingBagStacked")
            plan = getattr(emb, "_row_plan", None)
            out["row_plan_active"] = plan is not None
            if plan is not None:
                lookups = batch * TABLES * dcfg.embedding_bag_size
                out["a2a_bytes_per_step"] = exchange_bytes_per_step(
                    plan, lookups, DIM)
        batches = []
        for i in range(4):
            x, y = synthetic_batch(dcfg, batch, seed=i)
            x["label"] = y
            batches.append(model._device_batch(x))
        jax.block_until_ready(batches)
        out[f"steps_per_s_{mode}"] = round(
            _steps_per_s(model, batches, steps), 3)
        del model, batches

    if "steps_per_s_row_sharded" in out and \
            out.get("steps_per_s_replicated"):
        out["row_vs_replicated"] = round(
            out["steps_per_s_row_sharded"]
            / out["steps_per_s_replicated"], 3)

    # quantized-storage exchange payload (ISSUE 14): the row-sharded
    # all-to-all's ROW payload under the int8 policy vs fp32 — ids
    # route unchanged, rows ship as codes + one fp32 scale each
    if dcfg is not None:
        from dlrm_flexflow_tpu.quant.policy import QuantPolicy
        lookups_dev = batch * TABLES * dcfg.embedding_bag_size / ndev
        fp32_rows = lookups_dev * DIM * 4.0
        int8_rows = lookups_dev * QuantPolicy("int8").row_bytes(DIM)
        out["quant_exchange"] = {
            "rows_payload_fp32_kb": round(fp32_rows / 1e3, 1),
            "rows_payload_int8_kb": round(int8_rows / 1e3, 1),
            "ratio": round(fp32_rows / int8_rows, 2),
        }

    out["sim_pod_sweep"] = _sim_pod_sweep(ndev)
    out["skew_sweep"] = _skew_sweep(ndev, steps)
    out["sim_skew_dcn"] = _sim_skew_dcn()
    out["sim_overlap_dcn"] = _sim_overlap_dcn()
    return out


def main(argv):
    steps = 12
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    print(json.dumps({"metric": "embedding_sharding", **measure(steps)}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
