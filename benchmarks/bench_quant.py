#!/usr/bin/env python
"""Quantized embedding storage benchmark (ISSUE 14).

Measures, on one host, what the int8 row policy buys against fp32
across every byte surface it touches, plus what it costs in ranking
quality:

- ``footprint``: per-table HBM bytes (``hbm_footprint_report``) under
  fp32 vs int8 — acceptance bar >= 3.5x;
- ``exchange``: per-device all-to-all row-payload bytes of the
  row-sharded lookup under fp32 vs int8 policy (the DCN term the cost
  model prices) — bar >= 3.5x;
- ``delta``: measured on-disk delta-publish bytes (a DeltaPublisher
  pair over identical training) — row payloads bar >= 3.5x;
- ``cache``: EmbeddingCache rows-per-MB fp32 vs int8;
- ``auc``: ROC-AUC on a dlrm_kaggle-shaped model over synthetic
  learnable click data — fp32 vs int8 master_weight (structurally
  identical: delta == 0) and vs int8 stochastic_rounding (the
  measured quantized-training cost) — bar: delta <= 0.002.

Prints ONE JSON line; ``measure()`` is imported by bench.py when
BENCH_QUANT=1. Usage: python benchmarks/bench_quant.py
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _kaggle_small():
    """dlrm_kaggle SHAPE (26 tables x 16-d, the run_criteo_kaggle.sh
    geometry) at CPU-bench row counts."""
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    # 500-row tables so the 4k-sample train set revisits each id ~enough
    # for the embeddings to learn the planted logistic signal
    return DLRMConfig(embedding_size=[500] * 26, sparse_feature_size=16,
                      embedding_bag_size=1,
                      mlp_bot=[13, 64, 16], mlp_top=[432, 64, 1])


def _build(dcfg, batch=128, seed=3, **cfg_kw):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import build_dlrm
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=seed, **cfg_kw))
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.05), "mean_squared_error", ["mse"])
    model.init_layers()
    return model


def _click_data(dcfg, n, seed=0):
    """Synthetic LEARNABLE click data: labels from a sparse logistic
    ground truth over the categorical ids, so AUC moves off 0.5 and a
    quantization-induced quality drop is measurable."""
    import numpy as np
    rng = np.random.RandomState(seed)
    T = len(dcfg.embedding_size)
    bag = dcfg.embedding_bag_size
    dense = rng.rand(n, dcfg.mlp_bot[0]).astype(np.float32)
    sparse = np.stack(
        [rng.randint(0, rows, size=(n, bag))
         for rows in dcfg.embedding_size], axis=1).astype(np.int64)
    w = {t: rng.randn(dcfg.embedding_size[t]).astype(np.float32) * 2.0
         for t in range(T)}
    logits = sum(w[t][sparse[:, t, :]].sum(axis=1) for t in range(T))
    logits = logits / np.sqrt(T) + dense.sum(axis=1) - \
        dense.shape[1] / 2.0
    p = 1.0 / (1.0 + np.exp(-logits))
    y = (rng.rand(n) < p).astype(np.float32)[:, None]
    return {"dense": dense, "sparse": sparse}, y


def _auc(scores, labels):
    import numpy as np
    s = np.asarray(scores).reshape(-1)
    y = np.asarray(labels).reshape(-1)
    order = np.argsort(s)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(s) + 1)
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if not n_pos or not n_neg:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2)
                 / (n_pos * n_neg))


def _train_and_auc(dcfg, xtr, ytr, xte, yte, epochs, **cfg_kw):
    import numpy as np
    model = _build(dcfg, **cfg_kw)
    model.fit(xtr, ytr, epochs=epochs, verbose=False)
    scores = np.asarray(model.forward_batch(xte))
    return model, _auc(scores, yte)


def _measure_footprint():
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    from dlrm_flexflow_tpu.search.cost_model import CostModel
    from dlrm_flexflow_tpu.search.simulator import hbm_footprint_report
    dcfg = DLRMConfig(embedding_size=[200_000] * 4,
                      sparse_feature_size=64,
                      mlp_bot=[4, 16, 64], mlp_top=[320, 16, 1])
    m32 = _build(dcfg, batch=32)
    m8 = _build(dcfg, batch=32, emb_dtype="int8")
    cost = CostModel()
    r32 = hbm_footprint_report(m32, cost, m32.strategies, 1)
    r8 = hbm_footprint_report(m8, cost, m8.strategies, 1)
    name = max((k for k in r32 if k in r8), key=lambda k: r32[k])
    return {"table_fp32_mb": round(r32[name] / 1e6, 2),
            "table_int8_mb": round(r8[name] / 1e6, 2),
            "ratio": round(r32[name] / r8[name], 2)}, m32, m8, name


def _measure_exchange(m32, m8, name):
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
    pc = ParallelConfig((8, 1, 1), param_degree=8)
    op32 = next(o for o in m32.ops if o.name == name)
    op8 = next(o for o in m8.ops if o.name == name)
    _, rows32, _ = op32.alltoall_payload_bytes(8, 4, pc=pc)
    _, rows8, _ = op8.alltoall_payload_bytes(8, 4, pc=pc)
    return {"rows_fp32_kb": round(rows32 / 1e3, 1),
            "rows_int8_kb": round(rows8 / 1e3, 1),
            "ratio": round(rows32 / rows8, 2)}


def _measure_delta(steps=8):
    import numpy as np

    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, synthetic_batch
    from dlrm_flexflow_tpu.utils.delta import DeltaPublisher
    dcfg = DLRMConfig(embedding_size=[20_000] * 4,
                      sparse_feature_size=64,
                      mlp_bot=[4, 16, 64], mlp_top=[320, 16, 1])
    out = {}
    for tag, kw in (("fp32", {}), ("int8", {"emb_dtype": "int8"})):
        model = _build(dcfg, batch=64, **kw)
        with tempfile.TemporaryDirectory() as tmp:
            pub = DeltaPublisher(model, tmp, keep_last=2)
            pub.publish_full()
            x, y = synthetic_batch(dcfg, 64 * steps, seed=0)
            model.fit(x, y, epochs=1, verbose=False)
            entry = pub.publish()
            out[tag] = int(entry["bytes"])
            # the ROW payload alone (the term the policy shrinks; the
            # total is diluted by the dense fulls both modes ship)
            data = np.load(os.path.join(tmp, entry["file"]))
            out[f"{tag}_row_payload"] = int(sum(
                data[k].nbytes for k in data.files
                if k.split("/")[0] in ("rows", "scl")))
            out[f"{tag}_rows"] = int(np.sum(
                [v for v in entry["touched_rows"].values()]))
    out["ratio"] = round(out["fp32"] / max(out["int8"], 1), 2)
    out["ratio_rows"] = round(out["fp32_row_payload"]
                              / max(out["int8_row_payload"], 1), 2)
    return out


def _measure_cache():
    import numpy as np

    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, synthetic_batch
    from dlrm_flexflow_tpu.serve.cache import EmbeddingCache
    dcfg = DLRMConfig(embedding_size=[4096] * 4, sparse_feature_size=64,
                      mlp_bot=[4, 16, 64], mlp_top=[320, 16, 1])
    model = _build(dcfg, batch=64, host_resident_tables=True,
                   host_tables_async=False)
    op = next(o for o in model.ops if hasattr(o, "host_lookup"))
    x, _ = synthetic_batch(dcfg, 256, seed=1)
    idx = np.ascontiguousarray(x["sparse"], np.int32)
    c32 = EmbeddingCache(4096)
    c8 = EmbeddingCache(4096, quant={op.name: "int8"})
    c32.lookup(op, model.host_params[op.name], idx)
    c8.lookup(op, model.host_params[op.name], idx)
    rows32 = len(c32) / max(c32.stored_bytes() / 1e6, 1e-9)
    rows8 = len(c8) / max(c8.stored_bytes() / 1e6, 1e-9)
    return {"rows_per_mb_fp32": round(rows32),
            "rows_per_mb_int8": round(rows8),
            "ratio": round(rows8 / rows32, 2)}


def _measure_auc(train_n=4096, test_n=4096, epochs=2):
    dcfg = _kaggle_small()
    xtr, ytr = _click_data(dcfg, train_n, seed=0)
    xte, yte = _click_data(dcfg, test_n, seed=1)
    _, auc32 = _train_and_auc(dcfg, xtr, ytr, xte, yte, epochs)
    _, auc8m = _train_and_auc(dcfg, xtr, ytr, xte, yte, epochs,
                              emb_dtype="int8")
    _, auc8s = _train_and_auc(dcfg, xtr, ytr, xte, yte, epochs,
                              emb_dtype="int8",
                              emb_update_rule="stochastic_rounding")
    return {"fp32": round(auc32, 4),
            "int8_master": round(auc8m, 4),
            "int8_sr": round(auc8s, 4),
            # master_weight trains the exact fp32 master — the delta is
            # structurally zero (bit-identical params); SR is the
            # measured quantized-training cost
            "auc_delta_master": round(abs(auc8m - auc32), 5),
            "auc_delta_sr": round(abs(auc8s - auc32), 5)}


def measure(auc_epochs=2):
    footprint, m32, m8, name = _measure_footprint()
    return {
        "footprint": footprint,
        "exchange": _measure_exchange(m32, m8, name),
        "delta": _measure_delta(),
        "cache": _measure_cache(),
        "auc": _measure_auc(epochs=auc_epochs),
    }


def main():
    out = measure()
    print(json.dumps({"quant": out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
