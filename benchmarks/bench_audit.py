#!/usr/bin/env python
"""Lowered-HLO collective audit of the bench_shard plans: the
predicted-vs-lowered collective-bytes drift report.

For the PR 8 row-sharded bench plan (and the replicated baseline it
beats), AOT-lowers the train step on the attached mesh and reports:

- ``collective_counts`` / ``measured_bytes`` — collectives GSPMD
  actually inserted, per kind, at their per-device buffer sizes;
- ``predicted_bytes`` — what `search/cost_model.py` + the dense
  all-to-all exchange geometry predict for the same plan
  (``all-to-all-balanced`` is the ragged/production exchange the
  simulator prices — the dense/balanced gap is the padding factor);
- ``drift`` — relative measured-vs-predicted disagreement per kind
  (the FLX513 gate fails above ``tolerance``);
- ``high_findings`` — rendered FLX51x findings (the replicated plan's
  table-scale gradient all-reduce shows up here; the row-sharded plan
  must be clean).

Prints ONE JSON line; `measure()` is imported by bench.py when
BENCH_AUDIT=1. Usage: python benchmarks/bench_audit.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def measure(tolerance: float = 0.25):
    import jax

    from bench_shard import _build
    from dlrm_flexflow_tpu.analysis.hlo_audit import audit_model

    ndev = len(jax.devices())
    batch = 64 * ndev
    out = {"ndev": ndev, "batch": batch, "tolerance": tolerance}
    for mode in ("row_sharded", "replicated"):
        model, _dcfg = _build(ndev, batch, mode)
        findings, report = audit_model(model, tolerance=tolerance)
        report["high_findings"] = [f.render() for f in findings
                                   if f.severity == "high"]
        report["findings"] = len(findings)
        out[mode] = report
        del model
    row = out.get("row_sharded", {})
    drift = (row.get("drift") or {}).get("all-to-all")
    out["row_a2a_within_tolerance"] = (drift is not None
                                       and drift != "inf"
                                       and float(drift) <= tolerance)
    return out


def main(argv):
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # standalone CPU smoke: virtualize the 8-device mesh like the
        # test fixture does (must run before jax initializes); on the
        # real accelerator bench.py's devices are used as-is
        from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices
        ensure_cpu_devices(
            int(os.environ.get("BENCH_AUDIT_CPU_DEVICES", "8")))
    tol = 0.25
    if "--tolerance" in argv:
        tol = float(argv[argv.index("--tolerance") + 1])
    print(json.dumps({"metric": "hlo_collective_audit", **measure(tol)}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
