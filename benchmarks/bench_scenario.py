"""Closed-loop online-learning smoke for bench.py (BENCH_SCENARIO=1).

Runs the compressed drifting-zipf scenario — trace replay with a
mid-day hot-set churn, feedback-spool training, delta publication,
and the live hot/cold re-placement trigger — and reports the budget
metrics as one JSON-able dict:

    auc            serving-edge AUC over the second half of the day
    p99_ms         client-observed request p99
    fleet_max      peak replica count (autoscaler cap compliance)
    freshness_lag  publisher tip step - slowest replica's version
    replacements   online re-placements fired (the churn should cost 1)
    failed         client requests that raised (the bar is 0)
    passed         every budget held, chaos included

Chaos (a finite replica outage, one torn delta, lossy feedback) stays
ON: the point of the scenario is that the budgets hold through it.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def measure(steps: int = 48, replicas: int = 2,
            seed: int = 0) -> Dict[str, Any]:
    from dlrm_flexflow_tpu.scenarios import run_scenario

    verdict = run_scenario("drifting_zipf", steps=steps, fast=True,
                           replicas=replicas, seed=seed)
    m = verdict["metrics"]
    return {
        "scenario": verdict["scenario"],
        "steps": verdict["steps"],
        "auc": round(m["auc"], 4),
        "p99_ms": (round(m["p99_ms"], 3)
                   if m["p99_ms"] is not None else None),
        "fleet_max": m["fleet_max"],
        "freshness_lag": m["freshness_lag"],
        "spool_lag": m["spool_lag"],
        "replacements": m["replacements"],
        "failed": m["failed"],
        "step_time_ratio": (round(m["step_time_ratio"], 3)
                            if m["step_time_ratio"] is not None
                            else None),
        "wall_s": round(m["wall_s"], 2),
        "passed": verdict["passed"],
        "failures": verdict["failures"],
    }


if __name__ == "__main__":
    import json
    print(json.dumps(measure(), indent=2))
