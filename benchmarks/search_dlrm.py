#!/usr/bin/env python
"""MEASURED-MODE SOAP search for the DLRM configs (VERDICT r4 #3).

The reference's whole point is measured-search-found strategies: the
simulator times real kernels on the device and MCMC searches against
those timings (reference: src/runtime/simulator.cc:235-273 feeding
FFModel::optimize, model.cc:1093-1144). This script closes the same loop
on the real chip for the two tracked DLRM configs:

- kaggle   : run_criteo_kaggle.sh shape (26 tables 4..3.1M rows x 16-d),
             8-device target.
- terabyte : Criteo-TB shape (26 tables, the large ones tens of millions
             of rows, x 64-d — run_summit_large.sh territory), 64-device
             target on the 8-slice x 8 hybrid DCN+ICI topology, searched
             under the activation-aware capacity model (pure DP cannot
             fit: replicated tables are ~24 GB/chip).

With --measure (run ON the TPU) per-op costs come from timing each op's
compiled subgraph at its candidate shard shape (CostModel measure=True,
the r5-fixed path that rotates lookup indices per iteration); without it
the calibrated roofline prices ops. Exports the winner as a
reference-format .pb and prints one JSON line with the simulated
DP-vs-searched comparison.

  python benchmarks/search_dlrm.py --config kaggle --measure
  python benchmarks/search_dlrm.py --config terabyte --measure
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# public Criteo-Kaggle cardinalities (run_criteo_kaggle.sh)
KAGGLE_TABLES = [1396, 550, 2700000, 2160000, 301, 22, 11878, 619, 3,
                 64889, 5236, 2567820, 3136, 26, 12607, 471917, 11, 4970,
                 2159, 4, 2586596, 7043, 61, 4, 930, 14]
# public Criteo-Terabyte cardinalities (mlperf DLRM counts)
TB_TABLES = [39884406, 39043, 17289, 7420, 20263, 3, 7120, 1543, 63,
             38532951, 2953546, 403346, 10, 2208, 11938, 155, 4, 976, 14,
             39979771, 25641295, 39664984, 585935, 12972, 108, 36]


def build_config(name, batch):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm

    if name == "kaggle":
        dcfg = DLRMConfig(embedding_size=KAGGLE_TABLES,
                          sparse_feature_size=16,
                          mlp_bot=[13, 512, 256, 64, 16],
                          mlp_top=[432, 512, 256, 1])
    elif name == "terabyte":
        dcfg = DLRMConfig(embedding_size=TB_TABLES,
                          sparse_feature_size=64,
                          mlp_bot=[13, 512, 256, 64],
                          mlp_top=[64 * 27, 512, 512, 256, 1])
    else:
        raise ValueError(name)
    model = ff.FFModel(ff.FFConfig(batch_size=batch,
                                   compute_dtype="bfloat16"))
    build_dlrm(model, dcfg)
    return model


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=["kaggle", "terabyte"],
                    default="kaggle")
    ap.add_argument("--budget", type=int, default=600)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--measure", action="store_true",
                    help="measured-mode per-op costs on the attached "
                         "chip (reference simulator.cc:235-273); default "
                         "is the calibrated roofline")
    args = ap.parse_args(argv)

    if not args.measure:
        from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices
        ensure_cpu_devices(8)

    from dlrm_flexflow_tpu.search.cost_model import CostModel
    from dlrm_flexflow_tpu.search.mcmc import default_strategy, optimize
    from dlrm_flexflow_tpu.search.simulator import Simulator
    from dlrm_flexflow_tpu.parallel.strategy_io import save_strategies_pb

    if args.config == "kaggle":
        # flat single-slice ICI (DP sync cheap — an honest search may
        # confirm DP) AND a 2-host slice pair whose DP all-reduce rides
        # DCN (the reference's searched-beats-DP territory: weak
        # inter-node links, README.md:64-68)
        ndev = 8
        topos = [("ici_flat", None),
                 ("dcn_2host", [("dcn", 2), ("ici", 4)])]
    else:
        ndev = 64
        topos = [("dcn8x8", [("dcn", 8), ("ici", 8)])]
    batch = 256 * ndev

    model = build_config(args.config, batch)
    cm = CostModel(measure=args.measure,
                   compute_dtype=model.config.jnp_compute_dtype)
    mode = "measured" if args.measure else "roofline"
    dp = default_strategy(model, ndev)
    results = []
    for topo_label, topo in topos:
        sim = Simulator(model, cost_model=cm, topology=topo)
        t_dp = sim.simulate(dp, ndev)
        found = optimize(model, budget=args.budget, alpha=1.2, ndev=ndev,
                         cost_model=cm, seed=args.seed, start=dp,
                         topology=topo, verbose=True)
        t_found = sim.simulate(found, ndev)
        path = os.path.join(
            REPO, "strategies",
            f"dlrm_{args.config}_{ndev}dev_{topo_label}_{mode}.pb")
        save_strategies_pb(path, found)
        emb_pcs = {k: str(pc) for k, pc in sorted(found.items())
                   if "emb" in k or "table" in k}
        results.append({
            "topology": topo_label,
            "sim_dp_ms": (None if t_dp == float("inf")
                          else round(t_dp * 1e3, 3)),
            "dp_feasible": t_dp != float("inf"),
            # None (never Infinity — nonstandard JSON) when the budget
            # found no capacity-feasible strategy
            "search_feasible": t_found != float("inf"),
            "sim_searched_ms": (None if t_found == float("inf")
                                else round(t_found * 1e3, 3)),
            "speedup_vs_dp": (
                None if t_dp == float("inf") or t_found == float("inf")
                else round(t_dp / t_found, 4)),
            "ops_changed_from_dp": sum(
                1 for k, pc in found.items()
                if pc.degrees != dp[k].degrees
                or pc.memory_types != dp[k].memory_types),
            "embedding_placements": emb_pcs,
            "strategy_file": os.path.relpath(path, REPO),
        })
    print(json.dumps({
        "metric": f"dlrm_{args.config}_searched_vs_dp_simulated",
        "mode": mode,
        "ndev": ndev,
        "budget": args.budget,
        "results": results,
    }))


if __name__ == "__main__":
    main()
