#!/usr/bin/env python
"""Continual-learning freshness benchmark: train-step → servable latency.

The continual loop's whole point is that publishing only the TOUCHED
embedding rows (plus the small dense params) shrinks the trainer→server
hand-off from checkpoint-sized to touched-rows-sized. This bench runs a
combined train+serve loop on one host — a DLRM whose tables dominate the
snapshot (the production shape) — and measures, for each publish, the
time from the trained state existing (just before ``publish()``) to the
serving engine having APPLIED that version, under two publication modes:

- ``delta``: :class:`~dlrm_flexflow_tpu.utils.delta.DeltaPublisher`
  chain — atomic delta files, incremental ``apply_delta`` installs;
- ``full``: a full checkpoint per publish (the pre-ISSUE-10 path:
  write the whole npz, watcher reloads all params).

Acceptance bar (ISSUE 10): delta p99 <= 0.25 x full p99.

Prints ONE JSON line; ``measure()`` is imported by bench.py when
BENCH_FRESHNESS=1. Usage: python benchmarks/bench_freshness.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _build(seed=3, rows=120_000):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    # tables dominate: 4 x rows x 16-d fp32 ≈ 30 MB of a ~31 MB snapshot
    dcfg = DLRMConfig(embedding_size=[rows] * 4, sparse_feature_size=16,
                      mlp_bot=[8, 32, 16], mlp_top=[80, 32, 1])
    cfg = ff.FFConfig(batch_size=64, seed=seed)
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    return model, dcfg


def _pct(sorted_vals, p):
    from dlrm_flexflow_tpu.serve.engine import percentile
    return percentile(sorted_vals, p)


def _run_mode(mode, publishes, steps_per_publish, tmp, poll_s):
    import numpy as np

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.serve import InferenceEngine, ServeConfig
    from dlrm_flexflow_tpu.serve.watcher import SnapshotWatcher
    from dlrm_flexflow_tpu.utils.delta import DeltaPublisher

    trainer, dcfg = _build(seed=3)
    x, y = synthetic_batch(dcfg, 64, seed=0)
    xb = dict(x)
    xb["label"] = y
    d = os.path.join(tmp, mode)
    os.makedirs(d, exist_ok=True)
    pub = DeltaPublisher(trainer, d, keep_last=2, compact_frac=1e9)

    def train_step():
        # observe-then-train, exactly like fit_stream's staging hook:
        # the tracker's touched-row candidates keep the publish-time
        # diff touched-rows-sized instead of table-sized
        pub.observe_batch(xb)
        trainer.train_batch(xb)

    train_step()                     # base step >= 1: a fresh engine
    base = pub.publish_full({})      # (version 0) must reload it

    server, _ = _build(seed=9)
    eng = InferenceEngine(server, ServeConfig(max_batch=64, warmup=False))
    eng.start()
    watcher = SnapshotWatcher(eng, d, poll_s=poll_s)
    watcher.start()
    lat_s = []
    bytes_published = 0
    try:
        # let the engine pick up the base before timing
        deadline = time.time() + 120
        while (eng._applied_version < base["step"]
               and time.time() < deadline):
            time.sleep(0.01)
        if eng._applied_version < base["step"]:
            raise RuntimeError("engine never loaded the base snapshot")
        # one untimed publish cycle: the first delta apply compiles its
        # row-scatter executables; freshness is the steady-state number
        train_step()
        warm = (pub.publish_delta({}) if mode == "delta"
                else pub.publish_full({}))
        deadline = time.time() + 120
        while (eng._applied_version < int(trainer._step)
               and time.time() < deadline):
            time.sleep(poll_s / 4)
        for _ in range(publishes):
            for _ in range(steps_per_publish):
                train_step()
            step = int(trainer._step)
            t0 = time.perf_counter()
            entry = (pub.publish_delta({}) if mode == "delta"
                     else pub.publish_full({}))
            deadline = time.time() + 120
            while eng._applied_version < step and time.time() < deadline:
                time.sleep(poll_s / 4)
            if eng._applied_version < step:
                raise RuntimeError(
                    f"engine never reached version {step} "
                    f"(at {eng._applied_version})")
            lat_s.append(time.perf_counter() - t0)
            if entry is not None:
                f = os.path.join(d, entry["file"])
                bytes_published += (os.path.getsize(f)
                                    if os.path.isfile(f) else 0)
        # sanity: the served scores match the trainer's, bit for bit
        got = np.asarray(eng.model.forward_bucket(
            {k: v[:4] for k, v in x.items()}))
        want = np.asarray(trainer.forward_bucket(
            {k: v[:4] for k, v in x.items()}))
        if not np.array_equal(got, want):
            raise RuntimeError("served state diverged from the trainer")
    finally:
        watcher.stop()
        eng.close()
    lat_ms = sorted(1e3 * v for v in lat_s)
    return {
        "p50_ms": round(_pct(lat_ms, 50), 2),
        "p99_ms": round(_pct(lat_ms, 99), 2),
        "mean_ms": round(sum(lat_ms) / len(lat_ms), 2),
        "publishes": len(lat_ms),
        "bytes_per_publish": int(bytes_published / max(len(lat_ms), 1)),
    }


def measure(publishes=12, steps_per_publish=4, poll_s=0.005):
    """Both modes on the same shapes; returns the comparison dict."""
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench_freshness_")
    delta = _run_mode("delta", publishes, steps_per_publish, tmp, poll_s)
    full = _run_mode("full", publishes, steps_per_publish, tmp, poll_s)
    ratio = (delta["p99_ms"] / full["p99_ms"]
             if full["p99_ms"] else float("inf"))
    return {
        "delta": delta,
        "full": full,
        "p99_ratio_delta_vs_full": round(ratio, 4),
        "bar": "delta p99 <= 0.25 x full p99",
        "pass": bool(ratio <= 0.25),
        "quant_publish": _quant_publish_bytes(),
    }


def _quant_publish_bytes(steps=8):
    """ISSUE 14 rider: measured on-disk delta-publish bytes under the
    int8 row policy vs fp32, identical training on the tables-dominated
    shape — the publish-bytes half of the quantized-storage bar (the
    row payload is the term the policy shrinks; the total is diluted by
    the dense fulls both modes ship)."""
    import tempfile as _tf

    import numpy as np

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.utils.delta import DeltaPublisher
    out = {}
    for tag, kw in (("fp32", {}), ("int8", {"emb_dtype": "int8"})):
        import dlrm_flexflow_tpu as ff
        from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
        dcfg = DLRMConfig(embedding_size=[120_000] * 4,
                          sparse_feature_size=64,
                          mlp_bot=[8, 32, 64], mlp_top=[320, 32, 1])
        model = ff.FFModel(ff.FFConfig(batch_size=64, seed=3, **kw))
        build_dlrm(model, dcfg)
        model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error",
                      ["mse"])
        model.init_layers()
        with _tf.TemporaryDirectory() as tmp2:
            pub = DeltaPublisher(model, tmp2, keep_last=2)
            pub.publish_full()
            x, y = synthetic_batch(dcfg, 64 * steps, seed=0)
            model.fit(x, y, epochs=1, verbose=False)
            entry = pub.publish()
            out[f"bytes_{tag}"] = int(entry["bytes"])
            data = np.load(os.path.join(tmp2, entry["file"]))
            out[f"row_payload_{tag}"] = int(sum(
                data[k].nbytes for k in data.files
                if k.split("/")[0] in ("rows", "scl")))
    out["ratio"] = round(out["bytes_fp32"] / max(out["bytes_int8"], 1), 2)
    out["ratio_rows"] = round(
        out["row_payload_fp32"] / max(out["row_payload_int8"], 1), 2)
    return out


if __name__ == "__main__":
    publishes = int(os.environ.get("BENCH_FRESHNESS_PUBLISHES", "12"))
    print(json.dumps(measure(publishes=publishes)))
