#!/usr/bin/env python
"""Host-resident tables smoke/benchmark on the REAL chip: a DLRM whose
embedding tables EXCEED the chip's HBM trains on one chip with the tables
in host RAM (the reference hetero-strategy capability,
embedding_avx2.cc + dlrm_strategy_hetero.cc:28-49 — what makes
DLRM-Terabyte runnable on few devices).

Default config: 8 tables x 10M rows x 64-d fp32 = 20.5 GB of tables vs
16 GB of v5e HBM. Prints one JSON line.

  python benchmarks/bench_host_tables.py [--rows 10000000] [--steps 50]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--tables", type=int, default=8)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               synthetic_batch)

    table_gb = args.tables * args.rows * 64 * 4 / 1e9
    cfg = ff.FFConfig(batch_size=args.batch, compute_dtype="bfloat16",
                      host_resident_tables=True)
    dcfg = DLRMConfig(
        embedding_size=[args.rows] * args.tables,
        sparse_feature_size=64,
        mlp_bot=[64, 512, 512, 64],
        mlp_top=[64 * (args.tables + 1), 1024, 1024, 1024, 1])
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error", ["mse"])
    model.init_layers()
    emb = next(iter(model.host_params))
    host_gb = sum(v.nbytes for v in model.host_params[emb].values()) / 1e9

    batches = []
    for i in range(4):
        x, y = synthetic_batch(dcfg, args.batch, seed=i)
        x["label"] = y
        batches.append(model._device_batch(x))

    model.train_batch_device(batches[0])   # warm/compile

    def window():
        t0 = time.time()
        mets = None
        for s in range(args.steps):
            mets = model.train_batch_device(batches[s % 4])
        loss = float(mets["loss"])
        model._host_drain()
        return args.steps * args.batch / (time.time() - t0), loss

    tput_sync, loss = window()
    # pipelined mode: previous step's cotangent readback + host scatter
    # overlap the next step's gather/H2D (bounded one-step staleness)
    model.config.host_tables_async = True
    tput_async, loss_a = window()
    print(json.dumps({
        "metric": "dlrm_host_resident_tables_throughput_per_chip",
        "value": round(tput_sync, 2),
        "async_value": round(tput_async, 2),
        "unit": "samples/s/chip",
        "table_gb": round(table_gb, 1),
        "host_resident_gb": round(host_gb, 1),
        "hbm_gb": 16,
        "loss": round(loss, 5)}))


if __name__ == "__main__":
    main()
