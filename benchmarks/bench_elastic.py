#!/usr/bin/env python
"""Elastic-recovery smoke benchmark: what does surviving device loss cost?

Measures, on a small DLRM (CPU or attached accelerator):

- ``detect_ms`` — collective-watchdog detection latency: wall time from a
  stalled mesh probe to the typed ``MeshDegraded``, with a 0.2s deadline
  (the number should sit just above the configured deadline — detection
  is deadline-bound, not stall-bound);
- ``replan_ms`` — strategy re-search time for a half-fleet shrink
  (MCMC constrained to the survivors, seeded from the clamped old plan)
  and ``replan_greedy_ms`` for the zero-budget greedy clamp;
- ``reshard_ms`` — full in-place recovery: gather state to host,
  recompile on the shrunken mesh, re-split params/opt state;
- ``steps_per_s_before`` / ``steps_per_s_after`` — steady-state training
  rate on the full mesh vs the shrunken one (the capacity actually lost,
  as opposed to the whole job, which is what a non-elastic run loses);
- ``expand_*`` — scale-UP: detect (consume the return signal) → replan →
  reshard → FIRST post-expansion step, the end-to-end time from capacity
  coming back to the grown mesh training on it;
- ``warm_vs_cold`` — the persistent-cache story (ISSUE 12): the same
  recover-and-first-step cycle with an empty warm cache (cold: MCMC
  search + XLA compile) vs a populated one (warm: plan-cache hit + AOT
  executable deserialize), plus a corrupt-cache run proving the
  degradation path re-compiles instead of failing. The acceptance bar is
  warm recovery dropping from seconds to milliseconds
  (``warm_speedup`` >> 1, warm total in single-digit ms territory on
  this tiny model; real models amortize far more compile time).

Prints ONE JSON line (the BENCH_*.json convention); `measure()` is also
imported by bench.py when BENCH_ELASTIC=1 so recovery-cost regressions
show up next to the headline throughput.

Usage: python benchmarks/bench_elastic.py [--steps N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _build(ndev, batch, **cfg_kw):
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               dlrm_strategy)
    from dlrm_flexflow_tpu.parallel.mesh import make_mesh

    dcfg = DLRMConfig(embedding_size=[1024] * 8, sparse_feature_size=16,
                      mlp_bot=[13, 64, 16], mlp_top=[144, 64, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0, **cfg_kw))
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"],
                  mesh=make_mesh(devices=jax.devices()[:ndev]),
                  strategies=dlrm_strategy(model, dcfg, ndev))
    model.init_layers()
    return model, dcfg


def _steps_per_s(model, batches, steps):
    model.train_batch_device(batches[0])         # warm/compile
    t0 = time.perf_counter()
    mets = None
    for s in range(steps):
        mets = model.train_batch_device(batches[s % len(batches)])
    float(mets["loss"])                          # true completion
    return steps / (time.perf_counter() - t0)


def measure(steps=30, batch=128, search_budget=50):
    import jax

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.parallel.distributed import (MeshDegraded,
                                                        probe_mesh)
    from dlrm_flexflow_tpu.parallel.elastic import recover
    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    from dlrm_flexflow_tpu.search.replan import replan_strategies
    from dlrm_flexflow_tpu.utils import faults

    ndev = len(jax.devices())
    half = max(ndev // 2, 1)

    def staged(model, dcfg, n=4):
        out = []
        for i in range(n):
            x, y = synthetic_batch(dcfg, batch, seed=i)
            x["label"] = y
            out.append(model._device_batch(x))
        return out

    # --- detection latency (collective-deadline watchdog) --------------
    mesh = make_mesh(devices=jax.devices()[:half])
    probe_mesh(mesh, deadline_s=30.0)   # warm the probe jit
    deadline = 0.2
    with faults.active_plan(faults.FaultPlan(stall_s={"collective": 60.0})):
        t0 = time.perf_counter()
        try:
            probe_mesh(mesh, deadline_s=deadline)
            raise RuntimeError("stalled probe did not trip the watchdog")
        except MeshDegraded:
            detect_ms = 1e3 * (time.perf_counter() - t0)

    # --- re-search time ------------------------------------------------
    model, dcfg = _build(ndev, batch, elastic="inplace",
                         elastic_search_budget=search_budget)
    t0 = time.perf_counter()
    _, info = replan_strategies(model, half, budget=search_budget)
    replan_ms = 1e3 * (time.perf_counter() - t0)
    t0 = time.perf_counter()
    replan_strategies(model, half, budget=0)
    replan_greedy_ms = 1e3 * (time.perf_counter() - t0)

    # --- steps/s before, reshard, steps/s after ------------------------
    before = _steps_per_s(model, staged(model, dcfg), steps)
    devs = list(model.mesh.devices.flat)
    t0 = time.perf_counter()
    report = recover(model, lost=devs[half:], mode="inplace",
                     budget=search_budget)
    reshard_ms = 1e3 * report.reshard_s
    recover_total_ms = 1e3 * (time.perf_counter() - t0)
    after = _steps_per_s(model, staged(model, dcfg), steps)

    # --- scale-UP: detect -> replan -> reshard -> first step -----------
    from dlrm_flexflow_tpu.parallel.elastic import expand
    from dlrm_flexflow_tpu.parallel.distributed import MeshReturned
    model.config.elastic_expand = True
    returned = [d for d in jax.devices()
                if d.id not in {dd.id for dd in model.mesh.devices.flat}]
    b0 = staged(model, dcfg, n=1)[0]
    with faults.active_plan(faults.FaultPlan(
            return_device_steps={int(model._step): len(returned)})):
        t0 = time.perf_counter()
        try:
            model.train_batch_device(b0)          # detection point
            raise RuntimeError("return-device fault did not fire")
        except MeshReturned as exc:
            detect_expand_ms = 1e3 * (time.perf_counter() - t0)
            erep = expand(model, returned=exc.returned, mode="inplace",
                          budget=search_budget)
    t0 = time.perf_counter()
    b1 = staged(model, dcfg, n=1)[0]              # restage on new mesh
    float(model.train_batch_device(b1)["loss"])   # first grown step
    expand_first_step_ms = 1e3 * (time.perf_counter() - t0)

    # --- warm vs cold recovery (persistent plan + compile caches) ------
    import shutil
    import tempfile

    def _recover_cycle(cache_dir, corrupt=False):
        m, dc = _build(ndev, batch, elastic="inplace",
                       elastic_search_budget=search_budget)
        if cache_dir:
            m.attach_plan_cache(cache_dir)
            m.attach_compile_cache(cache_dir)
        bts = staged(m, dc, n=1)
        float(m.train_batch_device(bts[0])["loss"])   # pre-shrink warm
        plan = (faults.FaultPlan(corrupt_cache_entries=10 ** 6)
                if corrupt else faults.FaultPlan())
        with faults.active_plan(plan):
            t0 = time.perf_counter()
            rep = recover(m, lost=list(m.mesh.devices.flat)[half:],
                          mode="inplace", budget=search_budget)
            bt = staged(m, dc, n=1)[0]
            float(m.train_batch_device(bt)["loss"])   # first step
            total_ms = 1e3 * (time.perf_counter() - t0)
        return total_ms, rep

    cache_dir = tempfile.mkdtemp(prefix="ff-warmcache-")
    try:
        cold_ms, cold_rep = _recover_cycle(cache_dir)      # fills cache
        warm_ms, warm_rep = _recover_cycle(cache_dir)      # hits cache
        corrupt_ms, corrupt_rep = _recover_cycle(cache_dir,
                                                 corrupt=True)
        nocache_ms, _ = _recover_cycle(None)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    return {
        "devices": ndev,
        "devices_after": report.surviving,
        "detect_ms": round(detect_ms, 2),
        "detect_deadline_ms": round(1e3 * deadline, 2),
        "replan_ms": round(replan_ms, 2),
        "replan_greedy_ms": round(replan_greedy_ms, 2),
        "replan_searched": bool(info.get("searched")),
        "reshard_ms": round(reshard_ms, 2),
        "recover_total_ms": round(recover_total_ms, 2),
        "steps_per_s_before": round(before, 2),
        "steps_per_s_after": round(after, 2),
        "shrink_throughput_ratio": round(after / before, 4)
        if before > 0 else None,
        # scale-UP: capacity back -> grown mesh training on it
        "expand_detect_ms": round(detect_expand_ms, 2),
        "expand_replan_ms": round(1e3 * erep.replan_s, 2),
        "expand_reshard_ms": round(1e3 * erep.reshard_s, 2),
        "expand_first_step_ms": round(expand_first_step_ms, 2),
        "expand_devices": erep.surviving,
        # warm vs cold recovery (recover + first post-reshard step)
        "warm_vs_cold": {
            "no_cache_ms": round(nocache_ms, 2),
            "cold_ms": round(cold_ms, 2),
            "warm_ms": round(warm_ms, 2),
            "warm_speedup": round(nocache_ms / warm_ms, 2)
            if warm_ms > 0 else None,
            "warm_plan_cache_hit": bool(warm_rep.plan_cache_hit),
            "cold_plan_cache_hit": bool(cold_rep.plan_cache_hit),
            # corrupt entries must degrade to a fresh compile (cold
            # speed, zero failures), never to a dead recovery
            "corrupt_cache_ms": round(corrupt_ms, 2),
            "corrupt_degraded_ok": bool(
                not corrupt_rep.plan_cache_hit
                and corrupt_rep.surviving == half),
        },
    }


def main():
    steps = 30
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    out = {"metric": "elastic_smoke", "unit": "ms / steps_per_s"}
    out.update(measure(steps=steps))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
