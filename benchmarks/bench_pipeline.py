#!/usr/bin/env python
"""Input-pipeline benchmark: what does staging cost, and how much of it
does the prefetch ring hide?

Measures, on a small DLRM (CPU or attached accelerator):

- ``steps_per_s_staged`` — everything pre-staged on device (the
  all-in-HBM fast path fit() uses when the dataset fits);
- ``steps_per_s_streamed`` — slice + ``device_put`` synchronously inside
  the hot loop (the old streaming fallback);
- ``steps_per_s_prefetched`` — the same staging work done by the
  data/prefetch.py ring (depth = FFConfig.prefetch_depth) while the
  device trains, plus ``overlap_fraction`` = share of staging time the
  ring hid under compute. The acceptance bar: prefetched within 10% of
  pre-staged (``prefetched_vs_staged`` >= 0.9);
- ``steps_per_s_host_sync`` / ``steps_per_s_host_async`` — host-resident
  tables with exact-ordered inline gather/scatter vs the double-buffered
  worker (scatter + chained next-step gather overlapping device
  compute); ``host_async_speedup`` is their ratio.

Prints ONE JSON line (the BENCH_*.json convention); `measure()` is also
imported by bench.py when BENCH_PIPELINE=1 so input-pipeline regressions
show up next to the headline throughput.

Usage: python benchmarks/bench_pipeline.py [--steps N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _build(batch, **cfg_kw):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm

    # the reference run_random.sh shapes scaled to a CPU-friendly size —
    # a realistic compute/staging ratio (per-step input bytes are small
    # next to the MLP FLOPs, as in the real configs), not a toy MLP whose
    # step time is all dispatch
    dcfg = DLRMConfig(embedding_size=[16384] * 8, sparse_feature_size=64,
                      mlp_bot=[64, 256, 256, 64],
                      mlp_top=[576, 512, 256, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0, **cfg_kw))
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    return model, dcfg


def _host_batches(dcfg, batch, n=8):
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    out = []
    for i in range(n):
        x, y = synthetic_batch(dcfg, batch, seed=i)
        x["label"] = y
        out.append(x)
    return out


def measure(steps=60, batch=128):
    from dlrm_flexflow_tpu.data.prefetch import PrefetchPipeline

    model, dcfg = _build(batch)
    depth = max(getattr(model.config, "prefetch_depth", 2), 1)
    batches = _host_batches(dcfg, batch)
    nb = len(batches)

    staged = [model._stage_step(b) for b in batches]
    model.train_batch_staged(staged[0])          # warm/compile

    def timed(run):
        t0 = time.perf_counter()
        mets = run()
        float(mets["loss"])                      # true completion
        return steps / (time.perf_counter() - t0)

    def run_staged():
        mets = None
        for s in range(steps):
            mets = model.train_batch_staged(staged[s % nb])
        return mets

    def run_streamed():
        mets = None
        for s in range(steps):
            mets = model.train_batch_staged(
                model._stage_step(batches[s % nb]))
        return mets

    sps_staged = timed(run_staged)
    sps_streamed = timed(run_streamed)

    pipe = PrefetchPipeline(
        lambda k: model._stage_step(batches[k % nb]),
        depth=depth, num_items=steps, name="bench")
    try:
        def run_prefetched():
            mets = None
            for _ in range(steps):
                mets = model.train_batch_staged(pipe.get())
            return mets

        sps_prefetched = timed(run_prefetched)
        overlap = pipe.stats()["overlap_fraction"]
    finally:
        pipe.close()

    # host-resident tables: exact inline ordering vs the double-buffered
    # worker (scatter + chained next-step gather). Both are numerically
    # exact; the async mode just overlaps the host work with the device.
    def run_host(m, chained):
        hstaged = [m._stage_step(b) for b in batches]
        m.train_batch_staged(hstaged[0])         # warm/compile
        t0 = time.perf_counter()
        mets = None
        for s in range(steps):
            nh = hstaged[(s + 1) % nb].host_idx if chained else None
            mets = m.train_batch_staged(hstaged[s % nb], next_host_idx=nh)
        float(mets["loss"])
        m._host_drain()
        return steps / (time.perf_counter() - t0)

    h_sync, _ = _build(batch, host_resident_tables=True,
                       host_tables_async=False)
    sps_host_sync = run_host(h_sync, chained=False)
    h_async, _ = _build(batch, host_resident_tables=True)  # async default
    sps_host_async = run_host(h_async, chained=True)

    return {
        "steps_per_s_staged": round(sps_staged, 2),
        "steps_per_s_streamed": round(sps_streamed, 2),
        "steps_per_s_prefetched": round(sps_prefetched, 2),
        "streamed_vs_staged": round(sps_streamed / sps_staged, 4),
        "prefetched_vs_staged": round(sps_prefetched / sps_staged, 4),
        "overlap_fraction": round(overlap, 4),
        "steps_per_s_host_sync": round(sps_host_sync, 2),
        "steps_per_s_host_async": round(sps_host_async, 2),
        "host_async_speedup": round(sps_host_async / sps_host_sync, 4),
    }


def main():
    steps = 60
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    out = {"metric": "input_pipeline_smoke", "unit": "steps/s / ratio"}
    out.update(measure(steps=steps))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
