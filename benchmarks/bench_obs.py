#!/usr/bin/env python
"""Observability-overhead benchmark: what does ``--obs on`` cost?

The whole point of the obs layer is that it is cheap enough to leave on
in production; this bench holds it to that (the ISSUE-15 bar: <= 2%
overhead on BOTH train steps/s and serve p99). Measures, on a small
DLRM (CPU or attached accelerator):

- ``train_steps_per_s_off`` / ``train_steps_per_s_on`` — a 200-step
  pre-staged training loop with obs off vs on (spans on every dispatch,
  the drift monitor observing every step); ``train_overhead_frac`` is
  the relative slowdown and ``train_overhead_ok`` the <= 2% verdict.
- ``serve_p99_ms_off`` / ``serve_p99_ms_on`` — the serving engine's
  request p99 under a closed-loop client with obs off vs on (enqueue/
  batch-form/dispatch spans, latency reservoir registered as a scrape
  histogram, the stats collector live); ``serve_overhead_frac`` +
  ``serve_overhead_ok`` likewise.
- ``trace_export`` — size and wall time of one Chrome-trace export of
  the 200-step run's ring (the "one trace away" promise has to stay
  cheap too).

Both measurements repeat ``repeats`` times and keep the BEST throughput
/ LOWEST p99 per mode — CPU wall-clock noise at the 2% scale demands
best-of-N, the same discipline bench.py's headline windows use.

Prints ONE JSON line; ``measure()`` is imported by bench.py when
BENCH_OBS=1 so obs-overhead regressions show up next to the headline
throughput. Results recorded in BENCHMARKS.md round 15.

Usage: python benchmarks/bench_obs.py [--steps N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

OVERHEAD_BAR = 0.02


def _build(batch, **cfg_kw):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm

    dcfg = DLRMConfig(embedding_size=[16384] * 8, sparse_feature_size=64,
                      mlp_bot=[64, 256, 256, 64],
                      mlp_top=[576, 512, 256, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0, **cfg_kw))
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    return model, dcfg


def _train_overhead(steps, batch, repeats):
    """(best off steps/s, best on steps/s), INTERLEAVED windows over one
    model: the span/drift hooks check the global obs switch at call
    time, so flipping it per window compares the two modes under the
    same thermal/GC conditions — at the 2% scale, back-to-back blocks
    measure the machine's drift, not the instrumentation."""
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.obs import metrics, trace
    from dlrm_flexflow_tpu.obs.drift import DriftMonitor

    model, dcfg = _build(batch)
    x, y = synthetic_batch(dcfg, batch, seed=0)
    x["label"] = y
    staged = model._stage_step(x)
    model.train_batch_staged(staged)            # warm/compile

    def window(mon):
        t0 = time.perf_counter()
        mets = None
        for _s in range(steps):
            t_step = time.perf_counter() if mon is not None else 0.0
            mets = model.train_batch_staged(staged)
            if mon is not None:
                mon.observe_step(time.perf_counter() - t_step)
        float(mets["loss"])                     # true completion
        return steps / (time.perf_counter() - t0)

    best_off = best_on = 0.0
    for _ in range(repeats):
        with metrics.override(False), trace.override(False):
            best_off = max(best_off, window(None))
        with metrics.override(True), trace.override(True):
            best_on = max(best_on,
                          window(DriftMonitor(name="bench")))
            trace.clear()
    return best_off, best_on


def _serve_overhead(requests, batch, repeats):
    """(off p99, on p99) over ONE engine: MEDIAN of `repeats`
    interleaved windows per mode (4 closed-loop client threads against
    the continuous batcher). Median-of-windows because a CPU closed
    loop's p99 is scheduler-coupled — any single window can eat a 10 ms
    GIL/timeslice outlier that has nothing to do with the
    instrumentation being measured."""
    import statistics
    import threading

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.obs import metrics, trace
    from dlrm_flexflow_tpu.obs.metrics import percentile
    from dlrm_flexflow_tpu.serve import InferenceEngine, ServeConfig

    model, dcfg = _build(batch)
    eng = InferenceEngine(model, ServeConfig(max_batch=batch,
                                             queue_capacity=4096))
    windows = {False: [], True: []}
    with eng:
        feats, _ = synthetic_batch(dcfg, 1, seed=1)
        eng.predict(feats)                      # warm

        def window():
            lat = []
            lock = threading.Lock()
            n_threads = 4
            n_per = max(requests // n_threads, 1)

            def client(n):
                f, _ = synthetic_batch(dcfg, 1, seed=n)
                for _i in range(n_per):
                    t0 = time.perf_counter()
                    eng.predict(f)
                    ms = 1e3 * (time.perf_counter() - t0)
                    with lock:
                        lat.append(ms)

            threads = [threading.Thread(target=client, args=(i,),
                                        daemon=True)
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return percentile(sorted(lat), 99)

        window()                                # settle the batcher
        for _ in range(repeats):
            for on in (False, True):
                with metrics.override(on), trace.override(on):
                    windows[on].append(window())
                    if on:
                        trace.clear()
    return (statistics.median(windows[False]),
            statistics.median(windows[True]))


def _trace_export(steps, batch, tmpdir):
    """Size + latency of exporting a 200-step run's span ring."""
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.obs import metrics, trace

    with metrics.override(True), trace.override(True,
                                                trace_dir=tmpdir):
        model, dcfg = _build(batch)
        x, y = synthetic_batch(dcfg, batch, seed=0)
        x["label"] = y
        staged = model._stage_step(x)
        model.train_batch_staged(staged)
        for _ in range(steps):
            model.train_batch_staged(staged)
        t0 = time.perf_counter()
        path = trace.export_to_dir()
        export_s = time.perf_counter() - t0
        out = {
            "events": len(trace.events()),
            "dropped": trace.dropped(),
            "export_ms": round(1e3 * export_s, 2),
            "file_bytes": os.path.getsize(path),
        }
        trace.clear()
    return out


def measure(steps=200, batch=128, requests=384, repeats=3):
    import tempfile

    train_off, train_on = _train_overhead(steps, batch, repeats)
    serve_off, serve_on = _serve_overhead(requests, batch, repeats + 4)
    with tempfile.TemporaryDirectory() as d:
        export = _trace_export(steps, batch, d)

    train_frac = (train_off - train_on) / train_off if train_off else 0.0
    serve_frac = ((serve_on - serve_off) / serve_off
                  if serve_off else 0.0)
    return {
        "train_steps_per_s_off": round(train_off, 2),
        "train_steps_per_s_on": round(train_on, 2),
        "train_overhead_frac": round(train_frac, 4),
        "train_overhead_ok": bool(train_frac <= OVERHEAD_BAR),
        "serve_p99_ms_off": round(serve_off, 3),
        "serve_p99_ms_on": round(serve_on, 3),
        "serve_overhead_frac": round(serve_frac, 4),
        "serve_overhead_ok": bool(serve_frac <= OVERHEAD_BAR),
        "overhead_bar": OVERHEAD_BAR,
        "trace_export": export,
    }


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    steps = 200
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    out = {"bench": "obs_overhead", **measure(steps=steps)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
