#!/usr/bin/env python
"""Retrieval benchmark: what does the sharded MIPS index cost and buy?

Three questions, matching the ISSUE-20 acceptance bar:

- **Recall**: recall@k of the int8-quantized sharded top-k against an
  fp32 exact scan over the SAME item embeddings (bar: >= 0.95 at
  k=100) — the price of storing the index as ``QuantTable`` codes +
  per-row scales instead of dense fp32. The merged sharded answer is
  also checked bitwise against the single-machine exact scan over the
  same codes (that one is a correctness invariant, not a trade).
- **Per-shard scoring throughput**: rows scored per second through the
  full quantize-once → per-shard local top-k → exact heap-merge path
  for shard counts {1, 2, 4} (merge included — the ranker pays it).
- **Cascade QPS at a p99 SLO**: open-loop Poisson arrivals through
  ``CascadeEngine.predict`` (retrieve → expand → DLRM ranker →
  re-rank) reusing bench_serve_fleet's ``_poisson_drive``/
  ``_qps_at_slo`` harness (open loop for the same reason: a slow
  cascade must not slow the arrival process and flatter its own tail).
  Plus a chaos phase killing one index shard under load (bar: ZERO
  failed requests — answers come back degraded-flagged with the dead
  shard's candidates dropped, never errors).

The cascade's user encoder here is a fixed projection of the request's
dense features — the bench prices the retrieve+rank pipeline, not user-
tower compute (serve_dlrm's cascade runs the compiled two-tower head).

Prints ONE JSON line; `measure()` is imported by bench.py when
BENCH_RETRIEVE=1. Usage:
  python benchmarks/bench_retrieve.py [--requests N] [--slo-ms MS]
"""

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from bench_serve_fleet import _poisson_drive, _qps_at_slo   # noqa: E402


def _index(n_items, dim, nshards, seed=0):
    import numpy as np
    from dlrm_flexflow_tpu.retrieve.index import ShardedMIPSIndex
    rng = np.random.default_rng(seed)
    items = rng.standard_normal((n_items, dim)).astype(np.float32)
    sset = ShardedMIPSIndex.standalone_set(nshards)
    return ShardedMIPSIndex.build(sset, items), items, sset


def _measure_recall(n_items=20000, dim=128, k=100, queries=64):
    """recall@k of int8 sharded topk vs the fp32 exact scan, plus the
    bitwise merge-vs-exact-scan check over the same codes."""
    import numpy as np
    idx, items, sset = _index(n_items, dim, nshards=4)
    try:
        rng = np.random.default_rng(1)
        users = rng.standard_normal((queries, dim)).astype(np.float32)
        # generous per-shard deadline: the bench measures recall, not
        # tail latency, and a first-call import stall must not eject
        # shards and hollow out the answer
        r = idx.topk(users, k, deadline_s=30.0)
        ref_s, ref_i = idx.exact_scan_fp32(users, items, k)
        hits = sum(len(np.intersect1d(r.ids[b], ref_i[b]))
                   for b in range(queries))
        recall = hits / float(queries * k)
        oracle_s, oracle_i = idx.exact_scan(users, k)
        exact = (np.array_equal(r.ids, oracle_i)
                 and np.array_equal(r.scores, oracle_s))
        return {"n_items": n_items, "dim": dim, "k": k,
                "recall_at_k": round(recall, 4),
                "recall_pass": recall >= 0.95,
                "merge_bitwise_exact": bool(exact)}
    finally:
        sset.close()


def _measure_throughput(n_items=20000, dim=128, k=100, queries=32,
                        iters=8):
    """Rows scored per second through the full sharded query path for
    shard counts {1, 2, 4}."""
    import numpy as np
    rng = np.random.default_rng(2)
    users = rng.standard_normal((queries, dim)).astype(np.float32)
    out = {}
    for ns in (1, 2, 4):
        idx, _, sset = _index(n_items, dim, nshards=ns)
        try:
            idx.topk(users, k, deadline_s=30.0)             # warm
            t0 = time.perf_counter()
            for _ in range(iters):
                idx.topk(users, k, deadline_s=30.0)
            dt = time.perf_counter() - t0
            rows = n_items * queries * iters
            out[f"shards_{ns}"] = {
                "rows_per_s": round(rows / dt),
                "query_ms": round(1e3 * dt / (iters * queries), 3)}
        finally:
            sset.close()
    return out


def _cascade(k, nshards, n_items, dim):
    """A real cascade: fixed-projection user encoder, sharded int8
    index, DLRM ranker behind an InferenceEngine."""
    import numpy as np
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.retrieve import (CascadeConfig, CascadeEngine,
                                            dlrm_candidate_features)
    dcfg = DLRMConfig(embedding_size=[n_items] * 8,
                      sparse_feature_size=16, mlp_bot=[16, 64, 16],
                      mlp_top=[144, 64, 1])
    cfg = ff.FFConfig(batch_size=64, seed=3, serve_max_batch=64)
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    eng = ff.InferenceEngine(model, ff.ServeConfig(
        max_batch=64, queue_capacity=4096))
    idx, _, sset = _index(n_items, dim, nshards=nshards)
    rng = np.random.default_rng(5)
    W = rng.standard_normal((dcfg.mlp_bot[0], dim)).astype(np.float32)

    def encode(feats):
        return np.asarray(feats["dense"], np.float32) @ W

    cascade = CascadeEngine(
        idx, encode, eng,
        dlrm_candidate_features(8, dcfg.embedding_size),
        CascadeConfig(k=k, retrieve_deadline_ms=1000.0))
    return cascade, eng, sset, dcfg


def _measure_cascade(requests=128, slo_ms=150.0, k=32, nshards=2,
                     n_items=8192, dim=32):
    """Attained cascade QPS at the p99 SLO under open-loop Poisson
    load, then the one-shard-dead chaos phase at half that rate."""
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.serve import percentile
    from dlrm_flexflow_tpu.utils import faults

    cascade, eng, sset, dcfg = _cascade(k, nshards, n_items, dim)
    x, _ = synthetic_batch(dcfg, requests, seed=0)
    reqs = [{kk: v[i:i + 1] for kk, v in x.items()}
            for i in range(requests)]
    pool = ThreadPoolExecutor(max_workers=32,
                              thread_name_prefix="ff-bench-cascade")

    def submit(req):
        return pool.submit(cascade.predict, req)

    out = {"k": k, "nshards": nshards, "slo_ms": slo_ms}
    try:
        with eng:
            best, detail = _qps_at_slo(submit, reqs, slo_ms,
                                       rates=[4, 8, 16, 32, 64, 128])
            out["qps_at_slo"] = best
            out["detail"] = detail

            # chaos: shard 1's retrieval surface dead for the whole
            # phase (-1 = until the plan clears); the bar is zero
            # failed requests — degraded-flagged answers only
            rate = max(best / 2.0, 4.0)
            d0 = cascade.degraded_requests
            with faults.active_plan(faults.FaultPlan(
                    topk_drop={1: -1})):
                lat, failed, _ = _poisson_drive(submit, reqs, rate)
            out["chaos"] = {
                "offered_qps": round(rate, 1),
                "failed": failed,
                "zero_failed": failed == 0,
                "degraded_requests": cascade.degraded_requests - d0,
                "p99_ms": round(percentile(lat, 99), 2) if lat else None}
            out["stats"] = cascade.stats()
    finally:
        pool.shutdown(wait=False)
        sset.close()
    return out


def measure(requests=128, slo_ms=150.0):
    return {
        "recall": _measure_recall(),
        "throughput": _measure_throughput(),
        "cascade": _measure_cascade(requests=requests, slo_ms=slo_ms),
    }


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    requests, slo_ms = 128, 150.0
    while args:
        a = args.pop(0)
        if a == "--requests":
            requests = int(args.pop(0))
        elif a == "--slo-ms":
            slo_ms = float(args.pop(0))
        else:
            raise SystemExit(f"unknown arg {a!r}")
    out = measure(requests=requests, slo_ms=slo_ms)
    print(json.dumps({"retrieve": out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
