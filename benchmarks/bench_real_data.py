#!/usr/bin/env python
"""End-to-end REAL-data-path benchmark: Criteo-Kaggle-format data through
preprocess_hdf.py → .ffbin → FFBinDataLoader → train loop.

The reference's Criteo path is dlrm.cc:266-484 (HDF5 X_int/X_cat/y probed,
loaded whole into zero-copy memory, device-side scatter per batch) fed by
its preprocess_hdf.py. This benchmark drives the same chain here with
generated-but-format-faithful data, so the number includes the native
mmap+ring-buffer loader (native/ffloader.cc), not just synthetic arrays.

Prints one JSON line with samples/s. Usage:
    python benchmarks/bench_real_data.py [--samples N] [--epochs E]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# run_criteo_kaggle.sh table sizes / MLP shapes
KAGGLE_SIZES = [1396, 550, 2700000, 2160000, 301, 22, 11878, 619, 3, 64889,
                5236, 2567820, 3136, 26, 12607, 471917, 11, 4970, 2159, 4,
                2586596, 7043, 61, 4, 930, 14]


def make_raw_npz(path: str, n: int, seed: int = 0):
    """Criteo-Kaggle raw format as the preprocessor expects it: integer
    counts X_int (pre-log), categorical ids X_cat, click labels y."""
    rng = np.random.RandomState(seed)
    x_int = rng.poisson(3.0, size=(n, 13)).astype(np.int64)
    x_cat = np.stack([rng.randint(0, s, size=n) for s in KAGGLE_SIZES],
                     axis=1).astype(np.int64)
    y = rng.randint(0, 2, size=n).astype(np.int64)
    np.savez(path, X_int=x_int, X_cat=x_cat, y=y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--samples", type=int, default=131072)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="ffbench_")
    raw = os.path.join(tmp, "raw.npz")
    h5 = os.path.join(tmp, "criteo.hdf5")
    ffbin = os.path.join(tmp, "criteo.ffbin")

    make_raw_npz(raw, args.samples)
    subprocess.check_call([sys.executable,
                           os.path.join(REPO, "examples", "native",
                                        "preprocess_hdf.py"),
                           "-i", raw, "-o", h5])

    from dlrm_flexflow_tpu.data.dataloader import (load_dlrm_hdf5,
                                                   write_ffbin)
    x, y = load_dlrm_hdf5(h5)
    write_ffbin(ffbin, x["dense"], x["sparse"], y)

    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.data.dataloader import FFBinDataLoader
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               dlrm_strategy)

    cfg = ff.FFConfig(batch_size=args.batch, compute_dtype="bfloat16")
    dcfg = DLRMConfig(embedding_size=KAGGLE_SIZES, sparse_feature_size=16,
                      mlp_bot=[13, 512, 256, 64, 16],
                      mlp_top=[432, 512, 256, 1])
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error", ["mse"],
                  strategies=dlrm_strategy(model, dcfg, 1))
    model.init_layers()

    loader = FFBinDataLoader(model, ffbin)
    # warmup/compile
    model.train_batch_device(loader.next_batch())
    jax.block_until_ready(model.params)

    steps = 0
    t0 = time.time()
    mets = None
    for _ in range(args.epochs):
        for _ in range(loader.num_batches):
            mets = model.train_batch_device(loader.next_batch())
            steps += 1
    float(mets["loss"])                      # dependent readback
    elapsed = time.time() - t0
    thr = steps * args.batch / elapsed
    print(json.dumps({
        "metric": "dlrm_criteo_kaggle_realdata_throughput_per_chip",
        "value": round(thr, 2), "unit": "samples/s/chip",
        "samples": args.samples, "epochs": args.epochs,
        "loader": "ffbin(native mmap prefetch)"}))


if __name__ == "__main__":
    main()
