#!/usr/bin/env python
"""Fused-superstep benchmark: what does one dispatch per K steps buy?

Round 5 of BENCHMARKS.md pinned a ~0.55 ms per-step dispatch floor that
dominates small-batch DLRM (`dlrm_random b256` is floor-bound at
1.65 ms/step — roughly half of every step is dispatch, not math). Fused
supersteps (`FFConfig.superstep`, core/model.py `_train_superstep`)
compile K training steps into ONE executable, so one host→device
dispatch pays the floor once per K steps.

This bench sweeps K ∈ {1, 2, 4, 8, 16} on the two floor-sensitive DLRM
configs at b256 (floor-bound) and b1024 (compute-heavier), reporting:

- ``ms_per_step`` per K — must be STRICTLY decreasing K=1→8 on a
  floor-bound config (the ISSUE-4 acceptance bar);
- ``dispatch_floor_ms`` — the measured floor, recovered as the slope of
  the least-squares line t(K) = t_device + floor/K over 1/K (the K→∞
  intercept ``t_device_ms`` is the pure device time);
- ``speedup_k8_vs_k1`` — the headline amortization win.

On a TPU the reference run_random.sh / run_criteo_kaggle.sh shapes are
used; off-TPU the same topology scales down (CPU-runnable smoke, same
code paths). Prints ONE JSON line (the BENCH_*.json convention);
`measure()` is imported by bench.py when BENCH_SUPERSTEP=1.

Usage: python benchmarks/bench_superstep.py [--steps N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

# Criteo-Kaggle table sizes (run_criteo_kaggle.sh / calibrate_sim.py)
KAGGLE_TABLES = [1396, 550, 2700000, 2160000, 301, 22, 11878, 619, 3,
                 64889, 5236, 2567820, 3136, 26, 12607, 471917, 11, 4970,
                 2159, 4, 2586596, 7043, 61, 4, 930, 14][:26]


def _configs(full):
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    if full:
        rnd = DLRMConfig.random_benchmark()
        kag = DLRMConfig(embedding_size=KAGGLE_TABLES,
                         sparse_feature_size=16,
                         mlp_bot=[13, 512, 256, 64, 16],
                         mlp_top=[432, 512, 256, 1])
    else:
        # same topology, CPU-friendly table sizes/MLP widths — the
        # dispatch-vs-math ratio stays realistic, the suite stays fast
        rnd = DLRMConfig(embedding_size=[16384] * 8,
                         sparse_feature_size=64,
                         mlp_bot=[64, 256, 256, 64],
                         mlp_top=[576, 512, 256, 1])
        kag = DLRMConfig(embedding_size=[min(s, 4096) for s in
                                         KAGGLE_TABLES],
                         sparse_feature_size=16,
                         mlp_bot=[13, 64, 32, 16],
                         mlp_top=[432, 64, 32, 1])
    return {"dlrm_random": rnd, "dlrm_kaggle": kag}


def fit_dispatch_floor(ms_per_step):
    """Recover the per-dispatch floor from a K sweep.

    Model: t(K) = t_device + floor / K — each dispatch's fixed host cost
    spreads over the K steps it trains. A least-squares line over
    (1/K, ms_per_step) gives slope = floor (ms) and intercept = t_device
    (ms), the extrapolated K→∞ per-step time."""
    import numpy as np
    ks = sorted(ms_per_step)
    if len(ks) < 2:
        raise ValueError("need at least two K points to fit the floor")
    xs = np.array([1.0 / k for k in ks], dtype=np.float64)
    ys = np.array([ms_per_step[k] for k in ks], dtype=np.float64)
    slope, intercept = np.polyfit(xs, ys, 1)
    return float(slope), float(intercept)


def _measure_config(model, dcfg, bs, steps, ks, synthetic_batch,
                    stack_batches):
    per_k = {}
    for k in sorted(ks):
        if k == 1:
            bats = []
            for i in range(2):
                x, y = synthetic_batch(dcfg, bs, seed=i)
                x["label"] = y
                bats.append(model._device_batch(x))
            mets = model.train_batch_device(bats[0])     # warm/compile
            float(mets["loss"])
            rounds = max(2, steps)
            t0 = time.perf_counter()
            for s in range(rounds):
                mets = model.train_batch_device(bats[s % 2])
            float(mets["loss"])                          # true completion
            per_k[1] = (time.perf_counter() - t0) / rounds * 1e3
        else:
            megas = []
            for i in range(2):
                group = []
                for j in range(k):
                    x, y = synthetic_batch(dcfg, bs, seed=i * k + j)
                    x["label"] = y
                    group.append(x)
                megas.append(model._stage_superstep(stack_batches(group)))
            mets = model.train_batch_staged(megas[0])    # warm/compile
            float(mets["loss"])
            rounds = max(1, steps // k)
            t0 = time.perf_counter()
            for r in range(rounds):
                mets = model.train_batch_staged(megas[r % 2])
            float(mets["loss"])
            per_k[k] = (time.perf_counter() - t0) / (rounds * k) * 1e3
    return per_k


def measure(steps=48, ks=(1, 2, 4, 8, 16), batch_sizes=(256, 1024),
            full=None, configs=("dlrm_random", "dlrm_kaggle")):
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.data.prefetch import stack_batches
    from dlrm_flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch

    if full is None:
        full = jax.default_backend() == "tpu"
    dtype = "bfloat16" if jax.default_backend() == "tpu" else "float32"
    out = {}
    for name, dcfg in _configs(full).items():
        if name not in configs:
            continue
        for bs in batch_sizes:
            model = ff.FFModel(ff.FFConfig(batch_size=bs,
                                           compute_dtype=dtype))
            build_dlrm(model, dcfg)
            model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error",
                          ["mse"])
            model.init_layers()
            per_k = _measure_config(model, dcfg, bs, steps, ks,
                                    synthetic_batch, stack_batches)
            floor_ms, t_dev_ms = fit_dispatch_floor(per_k)
            mono = all(per_k[a] > per_k[b]
                       for a, b in ((1, 2), (2, 4), (4, 8))
                       if a in per_k and b in per_k)
            row = {
                "ms_per_step": {str(k): round(v, 4)
                                for k, v in sorted(per_k.items())},
                "dispatch_floor_ms": round(floor_ms, 4),
                "t_device_ms": round(t_dev_ms, 4),
                "strictly_decreasing_1_to_8": mono,
            }
            if 1 in per_k and 8 in per_k:
                row["speedup_k8_vs_k1"] = round(per_k[1] / per_k[8], 3)
            out[f"{name}_b{bs}"] = row
    return out


def main():
    steps = 48
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    out = {"metric": "superstep_amortization",
           "unit": "ms/step by K / ms floor"}
    out.update(measure(steps=steps))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
