#!/usr/bin/env python
"""SOAP-search a hybrid strategy for InceptionV3 (BASELINE.md tracked
config 3: "InceptionV3 with SOAP-searched hybrid strategy").

Runs MCMC (`optimize`, the reference FFModel::optimize algorithm,
model.cc:1093-1144) over an 8-device target offline (structural mesh
factorization — no 8 chips needed, unlike the reference which searches
on the target cluster, simulator.cu:79-109), exports the best strategy
as a reference-format .pb, and reports the simulated speedup vs pure
data parallelism.

  python benchmarks/search_inception.py [--budget 400] [--ndev 8]

Writes strategies/inception_v3_{ndev}dev_{topology}.pb; the multichip dryrun
(__graft_entry__.dryrun_multichip) loads and EXECUTES this file as its
fourth config, closing the search -> export -> load -> train loop.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(batch):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.inception import build_inception_v3
    model = ff.FFModel(ff.FFConfig(batch_size=batch,
                                   compute_dtype="bfloat16"))
    build_inception_v3(model, num_classes=1000)
    return model


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=400)
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--on-tpu", action="store_true",
                    help="search against the attached accelerator instead "
                         "of a virtual CPU mesh (offline targeting is the "
                         "default: the roofline models the TPU regardless "
                         "of where the search runs)")
    args = ap.parse_args(argv)

    if not args.on_tpu:
        # env vars alone don't switch backends under the axon
        # sitecustomize; this must run before any jax computation
        from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices
        ensure_cpu_devices(min(args.ndev, 8))

    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    from dlrm_flexflow_tpu.parallel.strategy_io import save_strategies_pb
    from dlrm_flexflow_tpu.search.mcmc import default_strategy, optimize
    from dlrm_flexflow_tpu.search.simulator import Simulator

    model = build(args.batch * args.ndev)
    model.mesh = make_mesh(num_devices=min(args.ndev,
                                           _n_local_devices()))
    dp = default_strategy(model, args.ndev)
    results = []
    out = None
    # two targets: a flat single-slice ICI mesh (DP sync is cheap there —
    # an honest search may confirm DP) and a 2-host slice pair whose DP
    # all-reduce rides DCN (the reference's searched-beats-DP territory:
    # its clusters had weak inter-node links, README.md:64-68)
    for label, topo in (("ici_flat", None),
                        ("dcn_2host", [("dcn", 2),
                                       ("ici", args.ndev // 2)])):
        sim = Simulator(model, topology=topo)
        t_dp = sim.simulate(dp, args.ndev)
        found = optimize(model, budget=args.budget, alpha=1.2,
                         ndev=args.ndev, seed=args.seed, start=dp,
                         topology=topo)
        t_found = sim.simulate(found, args.ndev)
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "strategies",
            f"inception_v3_{args.ndev}dev_{label}.pb")
        save_strategies_pb(path, found)
        out = path
        results.append({
            "topology": label,
            "sim_dp_ms": round(t_dp * 1e3, 3),
            "sim_searched_ms": round(t_found * 1e3, 3),
            "speedup_vs_dp": round(t_dp / t_found, 4),
            "ops_changed_from_dp": sum(
                1 for k, pc in found.items()
                if pc.degrees != dp[k].degrees),
            "strategy_file": os.path.relpath(path),
        })
    print(json.dumps({
        "metric": "inception_v3_searched_vs_dp_simulated",
        "ndev": args.ndev,
        "budget": args.budget,
        "results": results,
    }))
    return out


def _n_local_devices():
    import jax
    return len(jax.devices())


if __name__ == "__main__":
    main()
