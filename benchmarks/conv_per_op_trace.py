#!/usr/bin/env python
"""Per-op conv-model trace + the two named conv experiments (VERDICT r4
#5 / CONV_MFU_ANALYSIS.md "highest-leverage known fixes"):

1. PER-OP TABLE: measured fwd time of every ResNet-18 / InceptionV3 op's
   compiled subgraph on the real chip (utils.profiling.profile_ops with
   the r5-fixed measurement harness), heaviest first — the per-layer
   evidence queued since round 3.
2. BN-FUSION A/B: the same conv stack with and without BatchNorm,
   whole-step marginal — if the with-BN step costs ~the BN-less step,
   XLA already folds the normalize into the conv stream and a Pallas
   fused-BN epilogue is moot (the reference's counterpart is just
   cuDNN's fused BN, batch_norm.cu:1).
3. BATCH-512: ResNet-18 throughput at b128/b256/b512 (+ jax.checkpoint
   remat on the block boundaries if b512 OOMs — it does not on v5e/16GB).

Writes benchmarks/CONV_PER_OP_r5.md.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "CONV_PER_OP_r5.md")


def build_resnet(batch, with_bn=True, hw=224):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.resnet import build_resnet

    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    model = ff.FFModel(cfg)
    if with_bn:
        build_resnet(model, num_classes=1000, image_hw=hw, depth=18)
    else:
        # same conv/pool/dense skeleton, BN ops elided
        _build_resnet_nobn(model, hw)
    model.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    return model


def _build_resnet_nobn(model, hw):
    """ResNet-18 skeleton with every BatchNorm removed (ReLU kept)."""
    t = model.create_tensor((model.config.batch_size, 3, hw, hw),
                            name="image")
    t = model.conv2d(t, 64, 7, 7, 2, 2, 3, 3, activation="relu", name="c0")
    t = model.pool2d(t, 3, 3, 2, 2, 1, 1, name="p0")
    ch = 64
    i = 0
    for stage, blocks in enumerate([2, 2, 2, 2]):
        for b in range(blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            out_ch = 64 * (2 ** stage)
            sc = t
            if stride != 1 or ch != out_ch:
                sc = model.conv2d(t, out_ch, 1, 1, stride, stride, 0, 0,
                                  name=f"sc{i}")
            t2 = model.conv2d(t, out_ch, 3, 3, stride, stride, 1, 1,
                              activation="relu", name=f"a{i}")
            t2 = model.conv2d(t2, out_ch, 3, 3, 1, 1, 1, 1, name=f"b{i}")
            t = model.relu(model.add(t2, sc, name=f"add{i}"),
                           name=f"r{i}")
            ch = out_ch
            i += 1
    t = model.pool2d(t, 7, 7, 1, 1, 0, 0, pool_type="avg", name="gap")
    t = model.flat(t, name="flat")
    model.dense(t, 1000, name="fc")


def steptime(model, batch, hw=224, steps=60, windows=3):
    import numpy as np

    import jax
    rng = np.random.RandomState(0)
    db = model._device_batch({
        "image": rng.rand(batch, 3, hw, hw).astype(np.float32),
        "label": rng.randint(0, 1000, (batch, 1)).astype(np.int32)})
    model.train_batch_device(db)
    best = float("inf")
    for _ in range(windows):
        t0 = time.time()
        m = None
        for _s in range(steps):
            m = model.train_batch_device(db)
        float(m["loss"])
        best = min(best, (time.time() - t0) / steps)
    return best


def main():
    import jax

    from dlrm_flexflow_tpu.utils.profiling import format_profile, \
        profile_ops

    lines = ["# Per-op conv trace + BN-fusion / batch-512 experiments "
             "(round 5, real v5e)", ""]

    # 1. per-op tables
    for name, build in (("ResNet-18 b128", lambda: build_resnet(128)),):
        model = build()
        rows = profile_ops(model, measure=True)
        lines += [f"## Per-op measured table: {name}", "",
                  "```", format_profile(rows[:25]), "```", ""]
        del model

    # InceptionV3's ~100 convs would cost hours of per-op measurement on
    # the tunneled chip; its question ("BN fused or not, where does the
    # small-branch-conv time go") is answered by the BN A/B below plus
    # the roofline per-op table (analytical, instant)
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.inception import build_inception_v3
    cfg = ff.FFConfig(batch_size=64, compute_dtype="bfloat16")
    inc = ff.FFModel(cfg)
    build_inception_v3(inc, num_classes=1000, image_hw=299)
    inc.compile(ff.SGDOptimizer(lr=0.01),
                "sparse_categorical_crossentropy", ["accuracy"])
    inc.init_layers()
    rows = profile_ops(inc, measure=False)
    lines += ["## Per-op roofline table: InceptionV3 b64 (top 30, "
              "analytical — see BN A/B for the measured evidence)", "",
              "```", format_profile(rows[:30]), "```", ""]
    del inc

    # 2. BN-fusion A/B
    m_bn = build_resnet(128, with_bn=True)
    t_bn = steptime(m_bn, 128)
    del m_bn
    m_nobn = build_resnet(128, with_bn=False)
    t_nobn = steptime(m_nobn, 128)
    del m_nobn
    bn_cost = (t_bn - t_nobn) / t_bn * 100
    lines += ["## BN-fusion A/B (ResNet-18 b128, whole step)", "",
              f"- with BN: {t_bn*1e3:.3f} ms/step",
              f"- without BN (same conv skeleton): {t_nobn*1e3:.3f} ms/step",
              f"- BN's share of the step: {bn_cost:.1f}%", ""]

    # 3. batch sweep
    lines += ["## ResNet-18 batch sweep", ""]
    for b in (128, 256, 512):
        m = build_resnet(b)
        t = steptime(m, b, steps=30)
        lines += [f"- b{b}: {t*1e3:.3f} ms/step = {b/t:,.0f} samples/s"]
        del m
    lines += [""]

    with open(OUT, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {OUT}")
    print("\n".join(lines[-12:]))


if __name__ == "__main__":
    main()
