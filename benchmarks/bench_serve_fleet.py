#!/usr/bin/env python
"""Serving-fleet benchmark: what does the router buy under open-loop load?

Three questions, matching the ISSUE-6 acceptance bar:

- **Scaling**: attained QPS at a p99 SLO for 1/2/4 replicas under
  open-loop Poisson arrivals (open loop so a slow server cannot slow the
  arrival process down and flatter its own tail — the coordinated-
  omission trap of closed-loop drivers). Reported as the highest offered
  rate whose measured p99 stays inside the SLO.
- **Survival**: a 2-replica fleet at a fixed offered rate with one
  replica killed mid-run (`FF_FAULT_REPLICA_DOWN`) — failed requests
  (the bar is ZERO: every request retried to success on the survivor)
  and p99 before/during the outage.
- **Autoscaling under a load spike** (ISSUE 12): a 1-replica fleet with
  the SLO autoscaler attached serves comfortably inside the SLO; the
  offered rate then DOUBLES past single-replica capacity (each dispatch
  carries an injected fixed cost so capacity is dispatch-bound, not
  host-CPU-bound — the accelerator-serving shape, and the only regime
  where in-process CPU replicas scale at all). The autoscaler must grow
  the fleet on the sustained breach and the post-growth p99 must
  RE-ENTER the SLO with zero failed requests across all three phases —
  the ISSUE-12 acceptance bar.
- **Sharded serving tier** (ISSUE 13): a host-table model whose tables
  exceed a per-replica HBM budget is REJECTED by the replicated fleet's
  admission check and served through the row-sharded lookup tier
  instead, at the measured fraction of the replicated engine's
  p99-SLO QPS on a shape that fits both (bar: >= 0.8x) — plus a chaos
  run killing one embedding shard under open-loop traffic (zero failed
  requests; degraded-flagged answers allowed; warm-cache replacement
  probed in; p99 re-enters the SLO).
- **Wire protocol** (ISSUE 16): the same sharded tier served over REAL
  OS-process + socket boundaries — attained QPS at the p99 SLO through
  ``inproc`` vs ``tcp`` transports for 1/2/4 shard processes, the
  per-seam RTT distribution the transport measured while doing it, and
  a chaos run that ``kill -9``s one shard OS process under open-loop
  traffic (zero failed requests; warm-cache replacement probes in; p99
  re-enters the SLO).
- **Continuous vs flush batching**: the same open-loop ladder through
  one engine in continuous (iteration-level) admission vs the
  pre-continuous size/deadline flush cycle. Continuous batching is
  self-clocked — the previous dispatch IS the coalescing window, so the
  batch grows adaptively with load — where flush mode caps a batch at
  whatever ``max_delay`` collects and adds that delay to every partial
  batch; attained QPS at the SLO must be >= for continuous. (A
  closed-loop drive would flatter flush mode: N threads resubmitting in
  lock-step after each batch hand it a perfectly re-formed burst to
  collect — exactly the coordination open loop exists to avoid.)

Prints ONE JSON line; `measure()` is imported by bench.py when
BENCH_SERVE_FLEET=1. Usage:
  python benchmarks/bench_serve_fleet.py [--requests N] [--slo-ms MS]
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _build(dev=None, max_batch=64):
    """One replica's model on its own single-device mesh (replicas must
    not share a mesh — concurrent dispatches would serialize, and on
    CPU can deadlock interleaved collectives)."""
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    dcfg = DLRMConfig(embedding_size=[8192] * 8, sparse_feature_size=16,
                      mlp_bot=[16, 64, 16], mlp_top=[144, 64, 1])
    cfg = ff.FFConfig(batch_size=max_batch, seed=3,
                      serve_max_batch=max_batch)
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    mesh = None
    if dev is not None:
        devs = jax.devices()
        lo = dev % len(devs)
        mesh = make_mesh(devices=devs[lo:lo + 1])
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"],
                  mesh=mesh)
    model.init_layers()
    return model, dcfg


def _requests(dcfg, n, seed=0):
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    x, _ = synthetic_batch(dcfg, n, seed=seed)
    return [{k: v[i:i + 1] for k, v in x.items()} for i in range(n)]


def _router(n, retries=3):
    import dlrm_flexflow_tpu as ff
    scfg = ff.ServeConfig(max_batch=64, queue_capacity=4096)
    fleet = ff.Fleet.build(lambda i: _build(dev=i)[0], n, scfg)
    rcfg = ff.RouterConfig(retries=retries, backoff_ms=2.0,
                           cooldown_s=0.5, health_interval_s=0.1,
                           probe_deadline_s=30.0)
    return ff.FleetRouter(fleet, rcfg)


def _poisson_drive(submit, reqs, rate_qps, n=None, seed=7):
    """Open-loop Poisson arrivals: submit request i at its scheduled
    arrival time regardless of how the server is doing, measure latency
    FROM THE SCHEDULE (late submission counts against the server).
    ``n`` requests are drawn cyclically from ``reqs``.
    Returns (latencies_ms sorted, failed_count, elapsed_s)."""
    import numpy as np
    n = len(reqs) if n is None else n
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_qps, size=n))
    lat_ms = []
    lat_lock = threading.Lock()
    failed = [0]
    futs = []
    t0 = time.perf_counter()
    for i in range(n):
        now = time.perf_counter() - t0
        wait = arrivals[i] - now
        if wait > 0:
            time.sleep(wait)
        t_sched = t0 + arrivals[i]

        def _done(f, t_sched=t_sched):
            try:
                f.result()
                with lat_lock:
                    lat_ms.append(1e3 * (time.perf_counter() - t_sched))
            except Exception:   # noqa: BLE001 — counted, not raised
                failed[0] += 1

        try:
            fut = submit(reqs[i % len(reqs)])
        except Exception:   # noqa: BLE001 — Overloaded at submit time
            failed[0] += 1  # is a failed request in an open-loop world
            continue
        fut.add_done_callback(_done)
        futs.append(fut)
    for f in futs:
        try:
            f.result(120)
        except Exception:   # noqa: BLE001 — already counted
            pass
    return sorted(lat_ms), failed[0], time.perf_counter() - t0


def _trial_n(reqs, rate_qps, min_s=0.5):
    """Requests per trial: at least the base set, and enough to SUSTAIN
    the offered rate for ``min_s`` — a burst that fits in the queue and
    drains after the last arrival would otherwise report a flawless
    tail at an unsustainable rate (p99-from-schedule of a 30 ms burst
    says nothing about steady state). The absolute cap only bounds the
    trial's memory/runtime; past the driver's own submit ceiling the
    schedule slips, which correctly counts against the server."""
    return int(min(max(len(reqs), rate_qps * min_s), 32768))


def _qps_at_slo(submit, reqs, slo_ms, rates):
    """Highest offered rate whose p99 meets the SLO with zero failures;
    rates are tried in ascending order and the sweep stops at the first
    miss (the attained-QPS knee). A short untimed Poisson pre-run
    absorbs first-dispatch jitter (lazy imports, thread spin-up)."""
    from dlrm_flexflow_tpu.serve import percentile
    _poisson_drive(submit, reqs, rates[0], n=min(64, len(reqs)))
    best = 0.0
    detail = []
    for rate in rates:
        lat, failed, _ = _poisson_drive(submit, reqs, rate,
                                        n=_trial_n(reqs, rate))
        p99 = percentile(lat, 99)
        ok = failed == 0 and p99 is not None and p99 <= slo_ms
        detail.append({"offered_qps": round(rate, 1),
                       "n": _trial_n(reqs, rate),
                       "p99_ms": round(p99, 2) if p99 else None,
                       "failed": failed, "ok": ok})
        if not ok:
            break
        best = rate
    return best, detail


def _measure_autoscale(slo_ms=150.0, dispatch_cost_s=0.02,
                       max_batch=8):
    """Load-doubling chaos: 1 replica inside the SLO -> offered rate
    doubles past its capacity -> the autoscaler grows the fleet -> p99
    re-enters the SLO with zero failed requests.

    Capacity is made dispatch-bound by injecting a fixed per-dispatch
    cost (``FF_FAULT_SERVE_DELAY`` semantics): one replica sustains
    ~max_batch/dispatch_cost rows/s, so doubling the offered rate past
    that backs its queue up — the breach signal — while a second
    replica honestly doubles capacity (pure host-CPU-bound replicas
    would NOT scale in-process; see the module-note)."""
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    from dlrm_flexflow_tpu.serve import percentile
    from dlrm_flexflow_tpu.utils import faults

    dcfg = DLRMConfig(embedding_size=[8192] * 8, sparse_feature_size=16,
                      mlp_bot=[16, 64, 16], mlp_top=[144, 64, 1])
    reqs = _requests(dcfg, 128)
    cap_qps = max_batch / dispatch_cost_s        # one replica's ceiling
    rate_lo = 0.6 * cap_qps
    rate_hi = 1.5 * cap_qps                      # the doubled+ spike

    def factory(i):
        return _build(dev=i, max_batch=max_batch)[0]

    fleet = ff.Fleet.build(factory, 1, ff.ServeConfig(
        max_batch=max_batch, queue_capacity=8192))
    router = ff.FleetRouter(fleet, ff.RouterConfig(
        retries=4, backoff_ms=2.0, cooldown_s=0.5,
        health_interval_s=0.1, probe_deadline_s=60.0))
    scaler = ff.Autoscaler(router, ff.AutoscaleConfig(
        slo_ms=slo_ms, min_replicas=1, max_replicas=3,
        interval_s=0.1, sustain=3, queue_hwm=2.0,
        idle_sustain=10 ** 6,                    # no shrink mid-bench
        cooldown_s=1.0))
    router.start()
    scaler.start()
    try:
        for r in reqs[:16]:
            router.predict(r, timeout=120)
        with faults.active_plan(faults.FaultPlan(
                serve_delay_s=dispatch_cost_s)):
            lat_before, failed_before, _ = _poisson_drive(
                router.submit, reqs, rate_lo,
                n=_trial_n(reqs, rate_lo, min_s=2.0))
            # the spike: sustained past one replica's ceiling. Drive
            # long enough for breach detection + replica build/warm.
            lat_spike, failed_spike, _ = _poisson_drive(
                router.submit, reqs, rate_hi,
                n=_trial_n(reqs, rate_hi, min_s=8.0))
            # after growth: same doubled rate, now under capacity
            lat_after, failed_after, _ = _poisson_drive(
                router.submit, reqs, rate_hi,
                n=_trial_n(reqs, rate_hi, min_s=3.0))
        sstats = scaler.stats()
        p99_before = percentile(lat_before, 99)
        p99_spike = percentile(lat_spike, 99)
        p99_after = percentile(lat_after, 99)
        return {
            "slo_ms": slo_ms,
            "single_replica_cap_qps": round(cap_qps, 1),
            "offered_qps_before": round(rate_lo, 1),
            "offered_qps_spike": round(rate_hi, 1),
            "p99_ms_before": round(p99_before or 0, 2),
            "p99_ms_during_spike": round(p99_spike or 0, 2),
            "p99_ms_after_growth": round(p99_after or 0, 2),
            "failed_total": failed_before + failed_spike + failed_after,
            "grows": sstats["grows"],
            "fleet_size_final": sstats["size"],
            "grow_reason": sstats["last_reason"],
            "p99_reenters_slo": bool(p99_after is not None
                                     and p99_after <= slo_ms),
            "zero_failed": (failed_before + failed_spike
                            + failed_after) == 0,
        }
    finally:
        scaler.close()
        router.close()


def _build_host(max_batch=64):
    """A host-resident-table DLRM (the >HBM configuration the sharded
    tier exists for): same shape as ``_build`` but with tables in host
    memory, sliceable into lookup shards."""
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    dcfg = DLRMConfig(embedding_size=[8192] * 8, sparse_feature_size=16,
                      mlp_bot=[16, 64, 16], mlp_top=[144, 64, 1])
    cfg = ff.FFConfig(batch_size=max_batch, seed=3,
                      serve_max_batch=max_batch,
                      host_resident_tables=True,
                      host_tables_async=False)
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    return model, dcfg


def _measure_shardtier(slo_ms=50.0, nshards=4, requests=256):
    """ISSUE-13 acceptance measurements for the sharded serving tier:

    - **feasibility** — a model whose tables exceed the per-replica HBM
      budget is REJECTED by the replicated fleet's admission check and
      admitted by the sharded tier (tables stored once, divided);
    - **throughput tax** — attained QPS at the p99 SLO through the
      sharded tier vs the replicated (tables-resident) engine on a
      model that FITS both; the bar is >= 0.8x;
    - **chaos** — one embedding shard killed under open-loop traffic:
      zero failed requests (degraded-flagged answers allowed), the
      replacement shard boots from the warm cache and is probed in, and
      p99 re-enters the SLO afterwards.
    """
    import tempfile

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.serve import percentile
    from dlrm_flexflow_tpu.serve.shardtier import (EmbeddingShardSet,
                                                   ShardTierConfig,
                                                   check_serving_feasible,
                                                   serving_footprint)
    from dlrm_flexflow_tpu.utils import faults
    out = {"nshards": nshards}

    # --- (a) tables-exceed-one-host feasibility sweep -------------------
    model, dcfg = _build_host()
    fp = serving_footprint(model, replicas=2)
    budget = fp["dense_bytes"] + fp["table_bytes"] // 2
    replicated = check_serving_feasible(model, 2, budget, nshards=0)
    sharded = check_serving_feasible(model, 2, budget, nshards=nshards)
    out["feasibility"] = {
        "budget_mb": round(budget / 1e6, 2),
        "table_mb": round(fp["table_bytes"] / 1e6, 2),
        "replicated_feasible": replicated["feasible"],
        "replicated_reason": replicated["reason"],
        "sharded_feasible": sharded["feasible"],
        "sharded_ranker_mb": round(sharded["ranker_bytes"] / 1e6, 3),
        "sharded_shard_mb": round(sharded["shard_bytes"] / 1e6, 3),
    }

    reqs = _requests(dcfg, requests)

    def _qps(engine):
        for r in reqs[:16]:
            engine.predict(r, timeout=60)               # warm
        t0 = time.perf_counter()
        for r in reqs[:64]:
            engine.predict(r, timeout=60)
        base = 64 / (time.perf_counter() - t0)
        rates = [base * f for f in (0.5, 1.0, 2.0, 4.0, 8.0)]
        return _qps_at_slo(engine.submit, reqs, slo_ms, rates)

    # --- (b) replicated (tables-resident) engine baseline ---------------
    eng = ff.InferenceEngine(model, ff.ServeConfig(
        max_batch=64, queue_capacity=4096)).start()
    try:
        best_rep, sweep_rep = _qps(eng)
    finally:
        eng.close()
    out["replicated_qps_at_slo"] = round(best_rep, 1)

    # --- (c) sharded tier on the same shape -----------------------------
    cache_dir = tempfile.mkdtemp(prefix="ff-shard-cache-")
    m2, _ = _build_host()
    tier = ShardTierConfig(nshards=nshards, lookup_deadline_ms=1000.0,
                           cooldown_s=0.0, replace_after=2,
                           eject_after=2)
    sset = EmbeddingShardSet.build(m2, nshards, config=tier,
                                   cache_dir=cache_dir)
    EmbeddingShardSet.release_ranker_tables(m2)
    # cache deliberately smaller than the request pool: the chaos run
    # must keep CONSULTING the shard tier (a pool-sized cache would
    # ride out the outage on hits alone and measure nothing)
    eng = ff.InferenceEngine(m2, ff.ServeConfig(
        max_batch=64, queue_capacity=4096, cache_rows=32),
        shard_set=sset).start()
    try:
        best_shd, sweep_shd = _qps(eng)
        out["sharded_qps_at_slo"] = round(best_shd, 1)
        out["sharded_vs_replicated"] = (
            round(best_shd / best_rep, 3) if best_rep > 0 else None)

        # --- (d) chaos: kill one shard under open-loop traffic ----------
        rate = max(best_shd * 0.5, 8.0)
        half = len(reqs) // 2
        lat_before, failed_before, _ = _poisson_drive(
            eng.submit, reqs[:half], rate)
        stop = threading.Event()

        def _health_loop():
            while not stop.is_set():
                try:
                    sset.health_tick()
                except Exception:   # noqa: BLE001 — keep ticking
                    pass
                time.sleep(0.05)

        ht = threading.Thread(target=_health_loop, daemon=True,
                              name="ff-bench-shard-health")
        ht.start()
        plan = faults.FaultPlan()
        plan.shard_down[0] = -1
        with faults.active_plan(plan):
            lat_during, failed_during, _ = _poisson_drive(
                eng.submit, reqs[half:], rate)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and any(
                    r.state != "healthy" for r in sset.shards):
                time.sleep(0.05)
        lat_after, failed_after, _ = _poisson_drive(
            eng.submit, reqs[:half], rate)
        stop.set()
        ht.join(2.0)
        st = eng.stats()
        p99_after = percentile(lat_after, 99)
        out["chaos"] = {
            "offered_qps": round(rate, 1),
            "failed_before": failed_before,
            "failed_during_kill": failed_during,
            "failed_after": failed_after,
            "p99_ms_before": round(percentile(lat_before, 99) or 0, 2),
            "p99_ms_during_kill": round(percentile(lat_during, 99)
                                        or 0, 2),
            "p99_ms_after": round(p99_after or 0, 2),
            "p99_reentered_slo": bool(p99_after is not None
                                      and p99_after <= slo_ms),
            "degraded_responses": st["degraded_responses"],
            "shard_replacements": sset.replacements,
            "all_shards_healthy": all(r.state == "healthy"
                                      for r in sset.shards),
            "version_vector": sset.version_vector(),
        }
    finally:
        eng.close()
        sset.close()
    return out


def _spawn_shard_procs(cache_dir, nshards):
    """One ``shard_server`` OS process per slot; returns
    ``(procs, addresses)`` after every SHARD_SERVER_OK sentinel."""
    import subprocess
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-m", "dlrm_flexflow_tpu.serve.shard_server",
         "--cache-dir", cache_dir, "--nshards", str(nshards),
         "--slot", str(slot), "--port", "0"],
        env=env, text=True, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT) for slot in range(nshards)]
    addresses = []
    try:
        for p in procs:
            port = None
            for line in p.stdout:
                if line.startswith("SHARD_SERVER_OK"):
                    kv = dict(i.split("=", 1) for i in line.split()[1:])
                    port = int(kv["port"])
                    break
            if port is None:
                raise RuntimeError(
                    f"shard process never booted (exit {p.poll()})")
            addresses.append(("127.0.0.1", port))
    except BaseException:
        for p in procs:
            p.kill()
        raise
    return procs, addresses


def _reap_procs(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
        try:
            p.wait(5)
        except Exception:   # noqa: BLE001 — best-effort teardown
            pass
        if p.stdout is not None:
            p.stdout.close()


def _measure_wire(slo_ms=50.0, requests=256, proc_counts=(1, 2, 4)):
    """ISSUE-16 acceptance measurements for the wire protocol:

    - **transport tax** — attained QPS at the p99 SLO through the SAME
      sharded tier carried by ``inproc`` method calls vs ``tcp`` real
      sockets to real shard OS processes, for 1/2/4 shard processes;
    - **per-seam RTT** — the lookup seam's p50/p99 RTT the transport's
      own telemetry measured while serving the sweep (what FLX509
      prices the SLO budget against);
    - **proc-kill chaos** — ``kill -9`` (a real SIGKILL to a real pid)
      of one of 3 shard processes under open-loop traffic: zero failed
      requests, warm-cache replacement probes in, p99 re-enters.
    """
    import os as _os
    import signal
    import tempfile

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.serve import percentile
    from dlrm_flexflow_tpu.serve import transport as tp
    from dlrm_flexflow_tpu.serve.shardtier import (EmbeddingShardSet,
                                                   ShardTierConfig)

    model, dcfg = _build_host()
    reqs = _requests(dcfg, requests)
    out = {"slo_ms": slo_ms}

    def _tier_cfg(n, transport):
        return ShardTierConfig(nshards=n, lookup_deadline_ms=1000.0,
                               cooldown_s=0.0, replace_after=2,
                               eject_after=2, transport=transport)

    def _engine(sset):
        return ff.InferenceEngine(model, ff.ServeConfig(
            max_batch=64, queue_capacity=4096, cache_rows=32),
            shard_set=sset).start()

    def _qps(eng, rates):
        for r in reqs[:16]:
            eng.predict(r, timeout=60)                  # warm
        return _qps_at_slo(eng.submit, reqs, slo_ms, rates)

    # rate ladder calibrated off a 1-shard inproc closed-loop probe
    sset = EmbeddingShardSet.build(model, 1, config=_tier_cfg(1, "inproc"))
    eng = _engine(sset)
    try:
        for r in reqs[:16]:
            eng.predict(r, timeout=60)
        t0 = time.perf_counter()
        for r in reqs[:64]:
            eng.predict(r, timeout=60)
        base_qps = 64 / (time.perf_counter() - t0)
    finally:
        eng.close()
        sset.close()
    # wider-than-usual ladder: a 1-process tcp tier pays a socket round
    # trip per lookup, so its knee can sit well under the inproc probe
    rates = [base_qps * f for f in (0.125, 0.25, 0.5, 1.0, 2.0, 4.0)]
    out["closed_loop_qps"] = round(base_qps, 1)

    transports = {}
    for n in proc_counts:
        row = {}
        # inproc twin (same tier geometry, method-call carriage)
        sset = EmbeddingShardSet.build(model, n,
                                       config=_tier_cfg(n, "inproc"))
        eng = _engine(sset)
        try:
            best, _ = _qps(eng, rates)
            row["inproc_qps_at_slo"] = round(best, 1)
        finally:
            eng.close()
            sset.close()
        # tcp: real OS processes behind real sockets
        cache_dir = tempfile.mkdtemp(prefix=f"ff-wire-{n}-")
        cfg = _tier_cfg(n, "tcp")
        EmbeddingShardSet.seed_shard_cache(model, n, cache_dir,
                                           config=cfg)
        procs, addrs = _spawn_shard_procs(cache_dir, n)
        tp.reset_wire_stats()
        try:
            sset = EmbeddingShardSet.connect(addrs, config=cfg,
                                             cache_dir=cache_dir)
            eng = _engine(sset)
            try:
                best, _ = _qps(eng, rates)
                row["tcp_qps_at_slo"] = round(best, 1)
            finally:
                eng.close()
                sset.close()
            seam = tp.wire_stats().get("lookup", {})
            row["lookup_rtt_p50_ms"] = round(
                seam.get("rtt_p50_ms") or 0, 3)
            row["lookup_rtt_p99_ms"] = round(
                seam.get("rtt_p99_ms") or 0, 3)
            row["lookup_frames"] = seam.get("frames_sent", 0)
        finally:
            _reap_procs(procs)
        inp = row.get("inproc_qps_at_slo", 0)
        row["tcp_vs_inproc"] = (round(row["tcp_qps_at_slo"] / inp, 3)
                                if inp else None)
        transports[str(n)] = row
    out["transports"] = transports

    # --- chaos: SIGKILL one of 3 shard OS processes ---------------------
    n = 3
    cache_dir = tempfile.mkdtemp(prefix="ff-wire-chaos-")
    cfg = ShardTierConfig(nshards=n, lookup_deadline_ms=1000.0,
                          cooldown_s=0.0, replace_after=2,
                          eject_after=1, retries=0, transport="tcp")
    EmbeddingShardSet.seed_shard_cache(model, n, cache_dir, config=cfg)
    procs, addrs = _spawn_shard_procs(cache_dir, n)
    try:
        sset = EmbeddingShardSet.connect(addrs, config=cfg,
                                         cache_dir=cache_dir)
        eng = _engine(sset)
        stop = threading.Event()

        def _health_loop():
            while not stop.is_set():
                try:
                    sset.health_tick()
                except Exception:   # noqa: BLE001 — keep ticking
                    pass
                time.sleep(0.05)

        ht = threading.Thread(target=_health_loop, daemon=True,
                              name="ff-bench-wire-health")
        ht.start()
        try:
            rate = max(transports["2"].get("tcp_qps_at_slo", 8.0) * 0.5,
                       8.0)
            half = len(reqs) // 2
            lat_before, failed_before, _ = _poisson_drive(
                eng.submit, reqs[:half], rate)
            _os.kill(procs[0].pid, signal.SIGKILL)     # the real thing
            procs[0].wait(10)
            lat_during, failed_during, _ = _poisson_drive(
                eng.submit, reqs[half:], rate)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and any(
                    r.state != "healthy" for r in sset.shards):
                time.sleep(0.05)
            lat_after, failed_after, _ = _poisson_drive(
                eng.submit, reqs[:half], rate)
            st = eng.stats()
            p99_after = percentile(lat_after, 99)
            out["proc_kill"] = {
                "offered_qps": round(rate, 1),
                "failed_before": failed_before,
                "failed_during_kill": failed_during,
                "failed_after": failed_after,
                "p99_ms_before": round(percentile(lat_before, 99)
                                       or 0, 2),
                "p99_ms_during_kill": round(percentile(lat_during, 99)
                                            or 0, 2),
                "p99_ms_after": round(p99_after or 0, 2),
                "p99_reentered_slo": bool(p99_after is not None
                                          and p99_after <= slo_ms),
                "degraded_responses": st["degraded_responses"],
                "shard_replacements": sset.replacements,
                "all_shards_healthy": all(r.state == "healthy"
                                          for r in sset.shards),
            }
        finally:
            stop.set()
            ht.join(2.0)
            eng.close()
            sset.close()
    finally:
        _reap_procs(procs)
    return out


def measure(requests=256, slo_ms=50.0, replica_counts=(1, 2, 4)):
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.serve import percentile
    from dlrm_flexflow_tpu.utils import faults

    out = {"requests": requests, "slo_ms": slo_ms,
           "devices": len(jax.devices()),
           # read the scaling section with the platform in mind: on a
           # shared-CPU host, N in-process replicas fight for the same
           # cores AND each sees 1/N of the traffic (smaller batches,
           # worse amortization), so attained QPS can go DOWN with N —
           # per-host replicas on real accelerators share neither
           "note": ("in-process replicas share host cores; scaling "
                    "numbers on CPU reflect batch dilution + core "
                    "contention, not the router")}

    # --- scaling sweep: attained QPS at the p99 SLO ---------------------
    # calibrate the rate ladder off a 1-replica closed-loop probe so the
    # same ladder exercises every fleet size
    scaling = {}
    probe_model, dcfg = _build(dev=0)
    reqs = _requests(dcfg, requests)
    eng = ff.InferenceEngine(probe_model, ff.ServeConfig(
        max_batch=64, queue_capacity=4096))
    with eng:
        for r in reqs[:8]:
            eng.predict(r, timeout=60)
        t0 = time.perf_counter()
        for r in reqs[:64]:
            eng.predict(r, timeout=60)
        base_qps = 64 / (time.perf_counter() - t0)
    rates = [base_qps * f for f in (0.5, 1.0, 2.0, 4.0, 8.0, 16.0)]
    out["single_replica_closed_loop_qps"] = round(base_qps, 1)

    for n in replica_counts:
        router = _router(n).start()
        try:
            for r in reqs[:16]:          # warm every replica's buckets
                router.predict(r, timeout=60)
            best, detail = _qps_at_slo(router.submit, reqs, slo_ms,
                                       rates)
            scaling[str(n)] = {"qps_at_slo": round(best, 1),
                               "sweep": detail}
        finally:
            router.close()
    out["scaling"] = scaling

    # --- survival: kill 1 of 2 replicas mid-run -------------------------
    router = _router(2).start()
    try:
        for r in reqs[:16]:
            router.predict(r, timeout=60)
        rate = max(rates[0], scaling.get("2", {}).get(
            "qps_at_slo", rates[0]) * 0.5)
        half = len(reqs) // 2
        lat_before, failed_before, _ = _poisson_drive(
            router.submit, reqs[:half], rate)
        with faults.active_plan(faults.FaultPlan(replica_down={1: -1})):
            lat_during, failed_during, _ = _poisson_drive(
                router.submit, reqs[half:], rate)
        st = router.stats()
        out["replica_kill"] = {
            "offered_qps": round(rate, 1),
            "failed_before": failed_before,
            "failed_during_kill": failed_during,
            "p99_ms_before": round(percentile(lat_before, 99) or 0, 2),
            "p99_ms_during_kill": round(percentile(lat_during, 99) or 0, 2),
            "retries": st["retries"],
            "ejections": st["fleet"]["replicas"][1]["ejections"],
        }
    finally:
        router.close()

    # --- autoscaler chaos: load doubles, fleet grows, p99 re-enters -----
    out["autoscale"] = _measure_autoscale(slo_ms=150.0)

    # --- sharded serving tier (ISSUE 13) --------------------------------
    out["shardtier"] = _measure_shardtier(slo_ms=slo_ms,
                                          requests=requests)

    # --- wire protocol: process + socket boundaries (ISSUE 16) ----------
    out["wire"] = _measure_wire(slo_ms=slo_ms, requests=requests)

    # --- continuous vs flush batching (open-loop ladder each) -----------
    modes = {}
    for continuous in (False, True):
        model, _ = _build(dev=0)
        eng = ff.InferenceEngine(model, ff.ServeConfig(
            max_batch=64, max_delay_ms=2.0, queue_capacity=4096,
            continuous=continuous))
        with eng:
            for r in reqs[:16]:
                eng.predict(r, timeout=60)              # warm
            best, detail = _qps_at_slo(eng.submit, reqs, slo_ms, rates)
            st = eng.stats()
        modes["continuous" if continuous else "flush"] = {
            "qps_at_slo": round(best, 1),
            "batch_fill": round(st["batch_fill"], 3),
            "flushes": st["flushes"],
            "sweep": detail,
        }
    out["batching"] = modes
    # None (not an astronomical epsilon ratio) when flush attains no
    # rate at all inside the SLO — continuous wins outright
    flush_qps = modes["flush"]["qps_at_slo"]
    out["continuous_vs_flush"] = (
        round(modes["continuous"]["qps_at_slo"] / flush_qps, 2)
        if flush_qps > 0 else None)
    return out


if __name__ == "__main__":
    n = 256
    slo = 50.0
    if "--requests" in sys.argv:
        n = int(sys.argv[sys.argv.index("--requests") + 1])
    if "--slo-ms" in sys.argv:
        slo = float(sys.argv[sys.argv.index("--slo-ms") + 1])
    if "--wire-only" in sys.argv:
        print(json.dumps({"wire": _measure_wire(slo_ms=slo,
                                                requests=n)}))
    else:
        print(json.dumps(measure(requests=n, slo_ms=slo)))
