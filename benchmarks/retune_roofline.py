#!/usr/bin/env python
"""Offline roofline re-check: rebuild each calibration point's model on
the CPU and recompute the ROOFLINE simulated time against the
measured_ms recorded in an existing sim_calibration.json — lets cost-
model constants be tuned without burning a fresh on-chip sweep per
iteration (the final numbers still come from a real re-sweep).

  python benchmarks/retune_roofline.py [path/to/sim_calibration.json]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dlrm_flexflow_tpu.utils.testing import ensure_cpu_devices  # noqa: E402

ensure_cpu_devices(1)


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "sim_calibration.json")
    rows = {r["point"]: r for r in json.load(open(path))}

    import calibrate_sim as cal
    from dlrm_flexflow_tpu.search.mcmc import default_strategy
    from dlrm_flexflow_tpu.search.simulator import Simulator

    worst = 0.0
    for name, make in cal.calibration_points():
        if name not in rows:
            continue
        _, model, _ = make()
        strat = default_strategy(model, 1)
        sim_roof = Simulator(model).simulate(strat, 1) * 1e3
        real = rows[name]["measured_ms"]
        err = sim_roof / real - 1.0
        worst = max(worst, abs(err))
        print(f"{name:32s} real {real:8.3f} ms | roofline {sim_roof:8.3f} "
              f"({err:+.0%})")
    print(f"worst roofline |err|: {worst:.0%}")


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    main()
