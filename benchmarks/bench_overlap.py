#!/usr/bin/env python
"""Pipelined-exchange benchmark: overlap on/off for the row-shard
all-to-all (ISSUE 19).

Measures, on the attached mesh (CPU-virtual or real accelerator):

- ``steps_per_s_{serial,overlap}`` — steady-state training rate of the
  same row-sharded DLRM with the exchange as one blocking
  ``lax.all_to_all`` vs decomposed into ppermute/chunked rounds that
  pipeline under the gather/scatter (``ParallelConfig.overlap``);
- ``overlap_vs_serial`` — the measured ratio. NOTE: on a CPU-virtual
  mesh the decomposed rounds SERIALIZE (no DMA engine to ride), so the
  ratio is expected <= 1 there — the measurement is honest about where
  the win comes from, and the simulated section prices the real
  topology;
- ``exposed_comm_fraction`` — from the obs.trace spans wrapped around
  each step: the fraction of the serial step the pipelining uncovered,
  (t_serial - t_overlap) / t_serial, alongside the cost model's
  predicted exchange/window split for the same plan;
- ``sim_overlap_dcn`` — the simulated DCN-topology bar (>= 1.5x step
  time, bench_shard._sim_overlap_dcn) plus whether a from-scratch MCMC
  walk picks the pipelined plan unforced.

Prints ONE JSON line (the BENCH_*.json convention); `measure()` is also
imported by bench.py when BENCH_OVERLAP=1.

Usage: python benchmarks/bench_overlap.py [--steps N]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

ROWS = int(os.environ.get("BENCH_OVERLAP_ROWS", "131072"))
TABLES = 8
DIM = 128
BAG = 4


def _build(ndev, batch, overlap):
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig

    dcfg = DLRMConfig(embedding_size=[ROWS] * TABLES,
                      sparse_feature_size=DIM, embedding_bag_size=BAG,
                      mlp_bot=[DIM, 256, DIM],
                      mlp_top=[DIM * (TABLES + 1), 256, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0))
    build_dlrm(model, dcfg)
    strat = {}
    for op in model.ops:
        nd = op.outputs[0].num_dims if op.outputs else 0
        if type(op).__name__ == "EmbeddingBagStacked":
            strat[op.name] = ParallelConfig((ndev, 1, 1),
                                            param_degree=ndev,
                                            overlap=overlap)
        elif nd:
            strat[op.name] = ParallelConfig.data_parallel(nd, ndev)
    model.compile(ff.SGDOptimizer(lr=0.05), "mean_squared_error",
                  ["mse"], mesh=make_mesh(devices=jax.devices()[:ndev]),
                  strategies=strat)
    model.init_layers()
    return model, dcfg


def _timed_steps(model, batches, steps, label):
    """Run `steps` training steps, each wrapped in an obs.trace span —
    the per-variant step time is then read back OUT of the span ring
    (the exposed-comm fraction is derived from spans, not wall clocks,
    so a trace viewer shows the same numbers this bench reports)."""
    from dlrm_flexflow_tpu.obs import trace as obstrace

    model.train_batch_device(batches[0])          # warm/compile
    n = len(batches)
    for s in range(steps):
        with obstrace.span(f"bench_overlap/{label}", cat="bench"):
            mets = model.train_batch_device(batches[s % n])
            float(mets["loss"])                   # span = true step time
    durs = [ev["dur"] * 1e-6 for ev in obstrace.events()
            if ev.get("name") == f"bench_overlap/{label}"
            and ev.get("ph") == "X"]
    return min(durs) if durs else float("inf")


def measure(steps: int = 8):
    import jax

    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.obs import trace as obstrace

    ndev = len(jax.devices())
    out = {"ndev": ndev, "rows": ROWS, "tables": TABLES, "dim": DIM,
           "bag": BAG}
    if ndev < 2:
        out["skipped"] = "needs >= 2 devices for a row-shard exchange"
    else:
        batch = 256 * ndev
        out["batch"] = batch
        with obstrace.override(True):
            for label, overlap in (("serial", False), ("overlap", True)):
                model, dcfg = _build(ndev, batch, overlap)
                batches = []
                for i in range(4):
                    x, y = synthetic_batch(dcfg, batch, seed=i)
                    x["label"] = y
                    batches.append(model._device_batch(x))
                jax.block_until_ready(batches)
                t = _timed_steps(model, batches, steps, label)
                out[f"step_ms_{label}"] = round(t * 1e3, 3)
                out[f"steps_per_s_{label}"] = round(1.0 / t, 3)
                del model, batches
        t_ser = out["step_ms_serial"]
        t_ovl = out["step_ms_overlap"]
        out["overlap_vs_serial"] = round(t_ser / t_ovl, 3)
        # measured uncovering, from the spans: how much of the serial
        # step the pipelined exchange removed (<= 0 on a CPU mesh)
        out["exposed_comm_fraction"] = round((t_ser - t_ovl) / t_ser, 4)
        out["predicted"] = _predicted_fraction(ndev, batch)

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench_shard import _sim_overlap_dcn
    out["sim_overlap_dcn"] = _sim_overlap_dcn()
    return out


def _predicted_fraction(ndev, batch):
    """Cost-model split for the measured plan: exchange time, the
    exposed-compute window it can hide under, and the exchange share of
    the serial step — the prediction FLX514 compares against."""
    import jax.numpy as jnp

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
    from dlrm_flexflow_tpu.search.cost_model import CostModel

    dcfg = DLRMConfig(embedding_size=[ROWS] * TABLES,
                      sparse_feature_size=DIM, embedding_bag_size=BAG,
                      mlp_bot=[DIM, 256, DIM],
                      mlp_top=[DIM * (TABLES + 1), 256, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0))
    build_dlrm(model, dcfg)
    cost = CostModel()
    emb = next(op for op in model.ops
               if type(op).__name__ == "EmbeddingBagStacked")
    pc = ParallelConfig((ndev, 1, 1), param_degree=ndev)
    itemsize = jnp.dtype(cost.compute_dtype).itemsize
    exch = sum(cost.alltoall_time_axes(b, [("ici", ndev)])
               for b in emb.alltoall_payload_bytes(ndev, itemsize,
                                                   pc=pc))
    window = 0.0
    for op in model.ops:
        if op is emb or not op.outputs:
            continue
        opc = ParallelConfig.data_parallel(op.outputs[0].num_dims, ndev)
        window += cost.op_compute_time(op, opc)
        window += cost.op_compute_time(op, opc, backward=True)
    return {
        "exchange_ms": round(exch * 1e3, 4),
        "window_ms": round(window * 1e3, 4),
        "hideable_fraction": round(
            cost.overlap_efficiency() * min(window, exch)
            / max(exch, 1e-12), 4),
    }


def main(argv):
    steps = 8
    if "--steps" in argv:
        steps = int(argv[argv.index("--steps") + 1])
    print(json.dumps({"metric": "overlap_exchange", **measure(steps)}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
