#!/usr/bin/env python
"""Fault-tolerance smoke benchmark: what does recovery cost?

Measures, on a small DLRM (CPU or attached accelerator):

- ``save_ms`` / ``restore_ms`` — blocking rolling-checkpoint write and
  manifest-scan restore latency (the budget a `save_every` choice spends);
- ``sentinel_overhead`` — steady-state step-time ratio of
  ``anomaly_policy="skip_step"`` (fully async on-device guard) vs the
  sentinel off. This is the number that must stay ~1.0: the whole design
  point is that the finiteness check rides inside the jitted step;
- ``rollback_recovery_ms`` — wall time from an injected NaN step to
  training resumed on the restored snapshot (restore + rewind, measured
  through the real fit() rollback path).

Prints ONE JSON line (the BENCH_*.json convention); `measure()` is also
imported by bench.py when BENCH_RESILIENCE=1 so recovery-cost regressions
show up next to the headline throughput.

Usage: python benchmarks/bench_resilience.py [--steps N]
"""

import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _build(policy, batch):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm

    dcfg = DLRMConfig(embedding_size=[1024] * 8, sparse_feature_size=16,
                      mlp_bot=[13, 64, 16], mlp_top=[144, 64, 1])
    model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0,
                                   anomaly_policy=policy))
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    return model, dcfg


def _step_time(model, batches, steps):
    model.train_batch_device(batches[0])         # warm/compile
    t0 = time.perf_counter()
    mets = None
    for s in range(steps):
        mets = model.train_batch_device(batches[s % len(batches)])
    float(mets["loss"])                          # true completion
    return (time.perf_counter() - t0) / steps


def measure(steps=50, batch=128):
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    from dlrm_flexflow_tpu.utils import faults
    from dlrm_flexflow_tpu.utils.checkpoint import CheckpointManager

    def staged(model, dcfg, n=4):
        out = []
        for i in range(n):
            x, y = synthetic_batch(dcfg, batch, seed=i)
            x["label"] = y
            out.append(model._device_batch(x))
        return out

    base, dcfg = _build("none", batch)
    t_clean = _step_time(base, staged(base, dcfg), steps)

    guarded, _ = _build("skip_step", batch)
    t_sentinel = _step_time(guarded, staged(guarded, dcfg), steps)

    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, keep_last=2)
        t0 = time.perf_counter()
        mgr.save(base)
        save_ms = 1e3 * (time.perf_counter() - t0)
        t0 = time.perf_counter()
        assert mgr.restore_latest(base) is not None
        restore_ms = 1e3 * (time.perf_counter() - t0)

    # rollback drill through the real fit() path: one injected NaN step,
    # recovery time = (faulted fit) - (clean fit) on identical data
    def timed_fit(model, ckdir, plan):
        x, y = synthetic_batch(dcfg, batch * 8, seed=99)
        t0 = time.perf_counter()
        with faults.active_plan(plan):
            res = model.fit(x, y, epochs=1, verbose=False,
                            checkpoint_dir=ckdir, save_every=2)
        return time.perf_counter() - t0, res["rollbacks"]

    with tempfile.TemporaryDirectory() as d:
        m, _ = _build("rollback", batch)
        t_ref, rb = timed_fit(m, d, faults.FaultPlan())
        assert rb == 0
    with tempfile.TemporaryDirectory() as d:
        m, _ = _build("rollback", batch)
        t_fault, rb = timed_fit(m, d, faults.FaultPlan(nan_grad_steps={5}))
        assert rb == 1, f"expected exactly one rollback, got {rb}"

    return {
        "save_ms": round(save_ms, 2),
        "restore_ms": round(restore_ms, 2),
        "sentinel_overhead": round(t_sentinel / t_clean, 4),
        "rollback_recovery_ms": round(1e3 * max(t_fault - t_ref, 0.0), 2),
        "step_ms": round(1e3 * t_clean, 3),
    }


def main():
    steps = 50
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    out = {"metric": "resilience_smoke", "unit": "ms / ratio"}
    out.update(measure(steps=steps))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
