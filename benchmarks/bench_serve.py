#!/usr/bin/env python
"""Serving-engine benchmark: what does dynamic batching buy the read path?

The write path amortizes the dispatch floor with fused supersteps; the
read path amortizes it by coalescing concurrent requests into one padded
bucket dispatch (serve/engine.py). This bench quantifies that trade on
the DLRM random-benchmark topology:

- ``offline_qps``: direct ``forward_bucket`` loop at the largest bucket
  — the roofline the engine cannot beat (zero queueing);
- ``single_qps``: one caller, one row per request, engine in the loop —
  the degenerate no-coalescing case (every dispatch pays the full
  per-dispatch overhead for ONE row);
- per (bucket, max_delay) sweep: N concurrent submitter threads pushing
  single-row requests through the engine — ``qps``, ``p50_ms``,
  ``p99_ms``, ``batch_fill``;
- the same sweep with the embedding-row cache on vs off when the model
  keeps host-resident tables (``--host-tables`` serving).

Acceptance bar (ISSUE 5): the concurrent dynamically-batched
configuration sustains >= 3x ``single_qps`` on CPU.

Prints ONE JSON line; `measure()` is imported by bench.py when
BENCH_SERVE=1. Usage: python benchmarks/bench_serve.py [--requests N]
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _build(host_tables=False, cache_rows=0, max_batch=64):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig, build_dlrm
    dcfg = DLRMConfig(embedding_size=[8192] * 8, sparse_feature_size=16,
                      mlp_bot=[16, 64, 16], mlp_top=[144, 64, 1])
    cfg = ff.FFConfig(batch_size=max_batch, seed=3,
                      host_resident_tables=host_tables,
                      serve_cache_rows=cache_rows,
                      serve_max_batch=max_batch)
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.1), "mean_squared_error", ["mse"])
    model.init_layers()
    return model, dcfg


def _requests(dcfg, n, rows=1, seed=0):
    from dlrm_flexflow_tpu.models.dlrm import synthetic_batch
    x, _ = synthetic_batch(dcfg, n * rows, seed=seed)
    return [{k: v[i * rows:(i + 1) * rows] for k, v in x.items()}
            for i in range(n)]


def _drive(engine, reqs, threads):
    """Push every request through the engine from `threads` concurrent
    submitters; returns wall-clock seconds."""
    import dlrm_flexflow_tpu as ff
    it = iter(range(len(reqs)))
    lock = threading.Lock()
    errors = []

    def worker():
        while True:
            with lock:
                i = next(it, None)
            if i is None:
                return
            while True:
                try:
                    engine.predict(reqs[i], timeout=60)
                    break
                except ff.Overloaded:
                    time.sleep(0.001)
                except Exception as e:     # noqa: BLE001
                    errors.append(e)
                    return

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errors:
        raise errors[0]
    return time.perf_counter() - t0


def measure(requests=256, threads=16):
    import numpy as np
    import dlrm_flexflow_tpu as ff

    out = {"requests": requests, "threads": threads}
    model, dcfg = _build()
    reqs = _requests(dcfg, requests)

    # offline roofline: full buckets straight through forward_bucket
    bucket = model.bucket_sizes(64)[-1]
    from dlrm_flexflow_tpu.data.dataloader import coalesce_batches
    full = coalesce_batches(reqs[:bucket])
    np.asarray(model.forward_bucket(full, bucket=bucket))   # warm
    t0 = time.perf_counter()
    n_off = 0
    while n_off < requests:
        np.asarray(model.forward_bucket(full, bucket=bucket))
        n_off += bucket
    out["offline_qps"] = round(n_off / (time.perf_counter() - t0), 1)

    # single-request degenerate case: no coalescing possible
    eng = ff.InferenceEngine(model, ff.ServeConfig(
        max_batch=64, max_delay_ms=0.1, queue_capacity=1024))
    with eng:
        for r in reqs[:4]:
            eng.predict(r, timeout=60)                      # warm
        t0 = time.perf_counter()
        for r in reqs:
            eng.predict(r, timeout=60)
        single_s = time.perf_counter() - t0
    out["single_qps"] = round(requests / single_s, 1)

    # dynamic batching sweep
    sweep = []
    for max_batch in (16, 64):
        for delay_ms in (1.0, 5.0):
            eng = ff.InferenceEngine(model, ff.ServeConfig(
                max_batch=max_batch, max_delay_ms=delay_ms,
                queue_capacity=1024))
            with eng:
                _drive(eng, reqs[:64], threads)             # warm
                el = _drive(eng, reqs, threads)
                st = eng.stats()
            sweep.append({
                "max_batch": max_batch, "max_delay_ms": delay_ms,
                "qps": round(requests / el, 1),
                "p50_ms": round(st["p50_ms"], 3),
                "p99_ms": round(st["p99_ms"], 3),
                "batch_fill": round(st["batch_fill"], 3)})
    out["dynamic"] = sweep
    best = max(s["qps"] for s in sweep)
    out["best_dynamic_qps"] = best
    out["dynamic_vs_single"] = round(best / max(out["single_qps"], 1e-9), 2)

    # embedding-row cache on/off (host-resident tables)
    cache = {}
    for cache_rows in (0, 4096):
        m2, d2 = _build(host_tables=True, cache_rows=cache_rows)
        # skewed traffic: 32 hot index patterns cycled across requests
        hot = _requests(d2, 32, seed=5)
        seq = [hot[i % 32] for i in range(requests)]
        eng = ff.InferenceEngine(m2, ff.ServeConfig(
            max_batch=64, max_delay_ms=1.0, queue_capacity=1024,
            cache_rows=cache_rows))
        with eng:
            _drive(eng, seq[:64], threads)                  # warm
            el = _drive(eng, seq, threads)
            st = eng.stats()
        key = "cache_on" if cache_rows else "cache_off"
        cache[key] = {"qps": round(requests / el, 1)}
        if cache_rows:
            cache[key]["hit_rate"] = round(
                st["embedding_cache"]["hit_rate"], 3)
    out["host_tables"] = cache
    return out


if __name__ == "__main__":
    n = 256
    if "--requests" in sys.argv:
        n = int(sys.argv[sys.argv.index("--requests") + 1])
    print(json.dumps(measure(requests=n)))
