#!/usr/bin/env python
"""Simulator calibration against real measured step times.

The reference grounds its simulator in real kernel timings by construction
(reference: src/runtime/simulator.cc:235-273 microbenchmarks every op's
forward AND backward on the GPU). This harness closes the same loop for the
TPU cost model: for a set of model/config points it measures the real
per-step time on the attached chip, the analytical (roofline) simulated
time, and the measured-mode simulated time (per-op compiled subgraph
timings), and reports the relative error of each.

Run on a real TPU:  python benchmarks/calibrate_sim.py
Writes benchmarks/sim_calibration.json and prints a table.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_step_time(model, batches, steps=200, windows=3) -> float:
    """Best-window measured seconds per training step (same methodology as
    bench.py: interference on a shared chip only ever slows a window)."""
    model.train_batch_device(batches[0])  # warm/compile
    best = float("inf")
    n = len(batches)
    for _ in range(windows):
        t0 = time.time()
        mets = None
        for s in range(steps):
            mets = model.train_batch_device(batches[s % n])
        float(mets["loss"])  # dependent readback = true completion
        best = min(best, (time.time() - t0) / steps)
    return best


def build_point(name, dcfg, batch, dtype, sparse_update=True):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import build_dlrm, synthetic_batch

    cfg = ff.FFConfig(batch_size=batch, compute_dtype=dtype,
                      sparse_embedding_update=sparse_update)
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error", ["mse"])
    model.init_layers()
    batches = []
    for i in range(4):
        x, y = synthetic_batch(dcfg, batch, seed=i)
        x["label"] = y
        batches.append(model._device_batch(x))
    return name, model, batches


def build_image_point(name, build_fn, batch, hw, steps_scale=1.0,
                      **build_kw):
    import numpy as np

    import dlrm_flexflow_tpu as ff

    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    model = ff.FFModel(cfg)
    build_fn(model, num_classes=1000, image_hw=hw, **build_kw)
    model.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    rng = np.random.RandomState(0)
    batches = [model._device_batch({
        "image": rng.rand(batch, 3, hw, hw).astype(np.float32),
        "label": rng.randint(0, 1000, (batch, 1)).astype(np.int32)})
        for _ in range(2)]
    return name, model, batches


def build_attention_point(name, batch, seq, d, heads):
    import numpy as np

    import dlrm_flexflow_tpu as ff

    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    model = ff.FFModel(cfg)
    x = model.create_tensor((batch, seq, d), name="x")
    t = model.multihead_attention(x, num_heads=heads, causal=True,
                                  name="attn")
    t = model.dense(model.reshape(t, (batch * seq, d), name="fold"),
                    d, activation="relu", name="ff1")
    t = model.dense(t, 1, name="head")
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error",
                  ["mse"], final_tensor=t)
    model.init_layers()
    rng = np.random.RandomState(0)
    batches = [model._device_batch({
        "x": rng.rand(batch, seq, d).astype(np.float32),
        "label": rng.rand(batch * seq, 1).astype(np.float32)})
        for _ in range(2)]
    return name, model, batches


def build_lstm_point(name, batch, seq, vocab, hidden):
    import numpy as np

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.nmt import build_nmt

    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    model = ff.FFModel(cfg)
    build_nmt(model, src_vocab=vocab, tgt_vocab=vocab, embed_dim=hidden,
              hidden=hidden, num_layers=2, src_len=seq, tgt_len=seq)
    model.compile(ff.SGDOptimizer(lr=0.1),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    rng = np.random.RandomState(0)
    batches = [model._device_batch({
        "src": rng.randint(0, vocab, (batch, seq)).astype(np.int32),
        "tgt": rng.randint(0, vocab, (batch, seq)).astype(np.int32),
        "label": rng.randint(0, vocab, (batch, seq)).astype(np.int32)})
        for _ in range(2)]
    return name, model, batches


def calibration_points():
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig

    rnd = DLRMConfig.random_benchmark()          # 8 x 1M x 64-d tables
    kaggle = DLRMConfig(                          # run_criteo_kaggle.sh shape
        embedding_size=[1396, 550, 2700000, 2160000, 301, 22, 11878, 619,
                        3, 64889, 5236, 2567820, 3136, 26, 12607, 471917,
                        11, 4970, 2159, 4, 2586596, 7043, 61, 4, 930, 14][:26],
        sparse_feature_size=16,
        mlp_bot=[13, 512, 256, 64, 16],
        mlp_top=[432, 512, 256, 1])
    mlp = DLRMConfig(embedding_size=[64] * 4, sparse_feature_size=8,
                     mlp_bot=[32, 1024, 1024, 8],
                     mlp_top=[40, 1024, 1024, 1])
    def point(name, fn, *a, **kw):
        return name, lambda: fn(name, *a, **kw)

    yield point("dlrm_random_bf16_b256", build_point, rnd, 256, "bfloat16")
    yield point("dlrm_random_bf16_b1024", build_point, rnd, 1024,
                "bfloat16")
    yield point("dlrm_random_f32_b256", build_point, rnd, 256, "float32")
    yield point("dlrm_kaggle_bf16_b256", build_point, kaggle, 256,
                "bfloat16")
    yield point("dlrm_kaggle_bf16_b1024", build_point, kaggle, 1024,
                "bfloat16")
    yield point("mlp_heavy_bf16_b1024", build_point, mlp, 1024, "bfloat16")
    yield point("dlrm_random_dense_upd_b256", build_point, rnd, 256,
                "bfloat16", sparse_update=False)
    # conv / attention / LSTM families: the shapes the InceptionV3
    # searched strategy and the NMT/attention configs are optimized
    # against must be checked against the chip too (round-2 calibrated
    # only DLRM/MLP shapes)
    from dlrm_flexflow_tpu.models.alexnet import build_alexnet
    from dlrm_flexflow_tpu.models.resnet import build_resnet
    yield point("alexnet_bf16_b256", build_image_point, build_alexnet,
                256, 224)
    yield point("resnet18_bf16_b128", build_image_point, build_resnet,
                128, 224, depth=18)
    yield point("resnet18_bf16_b64_hw112", build_image_point,
                build_resnet, 64, 112, depth=18)
    yield point("attention_bf16_b8_s2048_d1024", build_attention_point,
                8, 2048, 1024, 16)
    yield point("nmt_lstm_bf16_b64_s40", build_lstm_point, 64, 40,
                32 * 1024, 1024)


def measure_dispatch_floor(steps=200, ks=(1, 2, 4, 8, 16)):
    """Measure the per-step dispatch floor via the fused-superstep K→∞
    intercept (bench_superstep.fit_dispatch_floor): a one-dense-layer
    model is floor-bound by construction, so sweeping K and fitting
    t(K) = t_device + floor/K recovers the floor as the slope — a
    direct observation of the constant the cost model pins as
    MEASURED_DISPATCH_FLOOR_S (search/cost_model.py). Recording it each
    sweep lets future rounds tell floor drift (the documented ~1.5×
    tunnel volatility, BENCHMARKS.md r5) from code regressions."""
    import numpy as np

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.data.prefetch import stack_batches

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench_superstep import fit_dispatch_floor

    bs = 256
    model = ff.FFModel(ff.FFConfig(batch_size=bs,
                                   compute_dtype="bfloat16"))
    x = model.create_tensor((bs, 64), name="x")
    t = model.dense(x, 64, activation="relu", name="fc1")
    t = model.dense(t, 1, name="head")
    model.compile(ff.SGDOptimizer(0.01), "mean_squared_error", ["mse"],
                  final_tensor=t)
    model.init_layers()
    rng = np.random.RandomState(0)
    host = {"x": rng.rand(bs, 64).astype(np.float32),
            "label": rng.rand(bs, 1).astype(np.float32)}
    per_k = {}
    for k in sorted(ks):
        if k == 1:
            db = model._device_batch(host)
            mets = model.train_batch_device(db)       # warm/compile
            float(mets["loss"])
            t0 = time.time()
            for _ in range(steps):
                mets = model.train_batch_device(db)
            float(mets["loss"])                       # true completion
            per_k[1] = (time.time() - t0) / steps * 1e3
        else:
            mega = model._stage_superstep(stack_batches([host] * k))
            mets = model.train_batch_staged(mega)     # warm/compile
            float(mets["loss"])
            rounds = max(1, steps // k)
            t0 = time.time()
            for _ in range(rounds):
                mets = model.train_batch_staged(mega)
            float(mets["loss"])
            per_k[k] = (time.time() - t0) / (rounds * k) * 1e3
    floor_ms, t_dev_ms = fit_dispatch_floor(per_k)
    return floor_ms, t_dev_ms, per_k


def measure_skew_distinct(alphas=(0.0, 0.8, 1.0, 1.2),
                          rows=1_000_000, draws=65536, trials=3):
    """Calibrate the cost model's SKEW TERM: the analytic
    expected-distinct estimate (IdFrequencySketch.expected_distinct —
    what prices the dedup'd exchange) against the EMPIRICAL distinct-id
    count of fresh zipf draws from the same observed histogram. Written
    to benchmarks/skew_calibration.json; the prediction error is the
    honesty bound on every dedup'd-exchange price the search sees."""
    import numpy as np

    from dlrm_flexflow_tpu.data.dataloader import zipf_indices
    from dlrm_flexflow_tpu.utils.histogram import IdFrequencySketch
    out = {}
    for alpha in alphas:
        rng = np.random.RandomState(7)
        sk = IdFrequencySketch(rows)
        sk.observe(zipf_indices(rng, rows, 4 * draws, alpha))
        pred = sk.expected_distinct(draws)
        emp = float(np.mean([
            len(np.unique(zipf_indices(rng, rows, draws, alpha)))
            for _ in range(trials)]))
        out[f"alpha_{alpha:g}"] = {
            "predicted_distinct": round(pred, 1),
            "empirical_distinct": round(emp, 1),
            "err": round(pred / emp - 1.0, 4) if emp else None,
            "draws": draws, "rows": rows,
        }
    return out


def measure_overlap_window(steps=60):
    """Calibrate the cost model's OVERLAP TERM (ISSUE 19): run the same
    row-sharded DLRM with the exchange serial and pipelined on the
    attached mesh, and solve the hidden fraction of the exchange window
    from the step-time delta:

        eff = (t_serial - t_overlap + rounds * per_round)
              / min(window, exchange)

    where `exchange` is the cost model's predicted all-to-all transfer
    time, `window` is the predicted exposed-compute window the exchange
    can hide under (every other op's fwd+bwd compute), and the
    per-round handoff overhead stays pinned at the spec default (the
    two are not separable from one scalar observation; the pinned term
    is what keeps zero-window plans from pricing overlap as free).
    Written to benchmarks/overlap_calibration.json — the artifact
    cost_model.load_overlap_calibration() serves back to the search as
    overlap_efficiency / round_overhead_s."""
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               synthetic_batch)
    from dlrm_flexflow_tpu.parallel.mesh import make_mesh
    from dlrm_flexflow_tpu.parallel.pconfig import ParallelConfig
    from dlrm_flexflow_tpu.parallel.sharding import param_axis_indices
    from dlrm_flexflow_tpu.search.cost_model import CostModel

    ndev = len(jax.devices())
    if ndev < 2:
        return None
    batch = 256 * ndev
    # exchange-heavy shape: wide rows, deep-enough dense stack that a
    # real compute window exists to hide the exchange under
    dcfg = DLRMConfig(embedding_size=[262144] * 8,
                      sparse_feature_size=128,
                      mlp_bot=[64, 512, 128],
                      mlp_top=[128 * 9, 512, 256, 1])
    times = {}
    model = None
    for label, overlap in (("serial", False), ("overlap", True)):
        model = ff.FFModel(ff.FFConfig(batch_size=batch, seed=0))
        build_dlrm(model, dcfg)
        strat = {}
        for op in model.ops:
            nd = op.outputs[0].num_dims if op.outputs else 0
            if type(op).__name__ == "EmbeddingBagStacked":
                strat[op.name] = ParallelConfig(
                    (ndev, 1, 1), param_degree=ndev, overlap=overlap)
            elif nd:
                strat[op.name] = ParallelConfig.data_parallel(nd, ndev)
        model.compile(ff.SGDOptimizer(lr=0.05), "mean_squared_error",
                      ["mse"], mesh=make_mesh(devices=jax.devices()),
                      strategies=strat)
        model.init_layers()
        batches = []
        for i in range(4):
            x, y = synthetic_batch(dcfg, batch, seed=i)
            x["label"] = y
            batches.append(model._device_batch(x))
        jax.block_until_ready(batches)
        times[label] = measure_step_time(model, batches,
                                         steps=steps, windows=3)
        del batches

    # predicted exchange + window for the SAME plan, so the solved
    # efficiency lands in the units exposed_exchange_time consumes
    import jax.numpy as jnp
    cost = CostModel(compute_dtype=model.config.jnp_compute_dtype)
    emb = next(op for op in model.ops
               if type(op).__name__ == "EmbeddingBagStacked")
    plan = emb._row_plan
    axis_sizes = tuple(plan.mesh.devices.shape) if plan is not None \
        else (ndev,)
    topo = [("ici", int(s)) for s in axis_sizes]
    pc = ParallelConfig((ndev, 1, 1), param_degree=ndev)
    itemsize = jnp.dtype(cost.compute_dtype).itemsize
    axes = [topo[i] for i in param_axis_indices(ndev, axis_sizes)]
    exchange = sum(
        cost.alltoall_time_axes(b, axes)
        for b in emb.alltoall_payload_bytes(ndev, itemsize, pc=pc))
    window = 0.0
    for op in model.ops:
        if op is emb or not op.outputs:
            continue
        opc = ParallelConfig.data_parallel(op.outputs[0].num_dims, ndev)
        window += cost.op_compute_time(op, opc)
        window += cost.op_compute_time(op, opc, backward=True)
    rounds = ndev - 1 if len(axes) == 1 else 4
    per_round = cost.spec.overlap_round_overhead_s
    hidden = times["serial"] - times["overlap"] + rounds * per_round
    denom = max(min(window, exchange), 1e-12)
    eff = max(0.0, min(0.99, hidden / denom))
    return {
        "overlap_efficiency": round(eff, 4),
        "round_overhead_s": per_round,
        "t_serial_ms": round(times["serial"] * 1e3, 4),
        "t_overlap_ms": round(times["overlap"] * 1e3, 4),
        "exchange_ms": round(exchange * 1e3, 4),
        "window_ms": round(window * 1e3, 4),
        "rounds": rounds,
        "ndev": ndev,
        "source": "calibrate_sim.measure_overlap_window",
    }


def main():
    from dlrm_flexflow_tpu.search.cost_model import CostModel
    from dlrm_flexflow_tpu.search.mcmc import default_strategy
    from dlrm_flexflow_tpu.search.simulator import Simulator

    steps = int(os.environ.get("CAL_STEPS", "200"))
    only = os.environ.get("CAL_ONLY")           # substring filter
    # CAL_OUT: write elsewhere (the hardware-gated test measures into a
    # temp file and only replaces the committed artifact on success —
    # a failed sweep must not destroy the record the always-on gate
    # validates)
    out = os.environ.get("CAL_OUT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "sim_calibration.json")
    # resumable: each finished point lands on disk immediately, and an
    # interrupted run (the tunneled chip can die mid-sweep) picks up
    # where it left off with CAL_RESUME=1. Existing rows are ALWAYS
    # loaded and merged by point name — a CAL_ONLY-filtered run must
    # never discard the other points' committed rows
    rows = []
    if os.path.exists(out):
        with open(out) as f:
            rows = json.load(f)
    # prune rows whose point no longer exists: a renamed/removed point
    # must not keep a stale row alive forever (it would keep counting
    # toward the gate's coverage bar while no sweep can refresh it)
    live = {name for name, _ in calibration_points()}
    rows = [r for r in rows if r["point"] in live]
    done = ({r["point"] for r in rows}
            if os.environ.get("CAL_RESUME") else set())
    for name, make in calibration_points():
        if name in done or (only and only not in name):
            continue
        _, model, batches = make()
        measured = measure_step_time(model, batches, steps=steps)
        strat = default_strategy(model, 1)
        sim_roof = Simulator(model).simulate(strat, 1)
        # CAL_KEEP_BEST=1: merge with the best PREVIOUSLY recorded real
        # for this point. The tunneled chip's per-step floor drifts
        # ~1.5x between phases (identical code measured mlp_heavy at
        # 0.79 and 1.27 ms hours apart, r5); interference and tunnel
        # state only ever SLOW a run, so the minimum across sweeps is
        # the closest observation of silicon truth — the same best-window
        # principle measure_step_time applies within a run. Guard: the
        # old best only survives while the point's ROOFLINE matches the
        # recorded one (a changed workload definition, kernel lowering,
        # or cost-model constant shifts it) — otherwise an obsolete fast
        # number could mask a real regression forever
        measured_latest = measured
        if os.environ.get("CAL_KEEP_BEST"):
            prev = next((r for r in rows if r["point"] == name), None)
            if prev is not None and abs(
                    prev["sim_roofline_ms"] - sim_roof * 1e3) \
                    <= 0.02 * sim_roof * 1e3:
                measured = min(measured, prev["measured_ms"] / 1e3)
        cm = CostModel(measure=True,
                       compute_dtype=model.config.jnp_compute_dtype)
        sim_meas = Simulator(model, cost_model=cm).simulate(strat, 1)
        row = {
            "point": name,
            # measured_ms: the number calibration consumes (CAL_KEEP_BEST
            # may substitute the historical minimum); measured_ms_latest +
            # kept_best make the artifact distinguish a fresh measurement
            # from a kept minimum
            "measured_ms": measured * 1e3,
            "measured_ms_latest": measured_latest * 1e3,
            "kept_best": measured < measured_latest,
            "sim_roofline_ms": sim_roof * 1e3,
            "sim_measured_ms": sim_meas * 1e3,
            "err_roofline": sim_roof / measured - 1.0,
            "err_measured": sim_meas / measured - 1.0,
        }
        rows = [r for r in rows if r["point"] != name] + [row]
        r = row
        print(f"{name:32s} real {r['measured_ms']:8.3f} ms | "
              f"sim(roofline) {r['sim_roofline_ms']:8.3f} "
              f"({r['err_roofline']:+.0%}) | "
              f"sim(measured) {r['sim_measured_ms']:8.3f} "
              f"({r['err_measured']:+.0%})", flush=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rows, f, indent=1)
        os.replace(tmp, out)   # atomic: a mid-write kill can't corrupt
        # the only copy of completed rows

    # dispatch-floor record (skipped under CAL_ONLY point-debugging):
    # the measured K→∞ intercept lands in dispatch_floor.json next to
    # the sweep artifact, compared against the cost model's pinned
    # MEASURED_DISPATCH_FLOOR_S so floor drift is visible as data
    if not only:
        from dlrm_flexflow_tpu.search.cost_model import \
            MEASURED_DISPATCH_FLOOR_S
        floor_ms, t_dev_ms, per_k = measure_dispatch_floor(
            steps=min(steps, 200))
        pinned_ms = MEASURED_DISPATCH_FLOOR_S * 1e3
        rec = {
            "dispatch_floor_ms": round(floor_ms, 4),
            "t_device_ms": round(t_dev_ms, 4),
            "ms_per_step_by_k": {str(k): round(v, 4)
                                 for k, v in sorted(per_k.items())},
            "pinned_ms": round(pinned_ms, 4),
            "drift_vs_pinned": (round(floor_ms / pinned_ms, 3)
                                if pinned_ms else None),
        }
        floor_out = os.path.join(os.path.dirname(out),
                                 "dispatch_floor.json")
        tmp = floor_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f, indent=1)
        os.replace(tmp, floor_out)
        print(f"dispatch floor: measured {floor_ms:.3f} ms vs pinned "
              f"{pinned_ms:.3f} ms (x{rec['drift_vs_pinned']}) -> "
              f"{floor_out}")

        # skew-term calibration: expected-distinct vs empirical (the
        # dedup'd exchange's pricing input, ISSUE 11)
        skew = measure_skew_distinct()
        skew_out = os.path.join(os.path.dirname(out),
                                "skew_calibration.json")
        tmp = skew_out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(skew, f, indent=1)
        os.replace(tmp, skew_out)
        worst_skew = max(abs(v["err"]) for v in skew.values()
                         if v["err"] is not None)
        print(f"skew expected-distinct worst |err|: {worst_skew:.1%} "
              f"-> {skew_out}")

        # overlap-window calibration (ISSUE 19): serial vs pipelined
        # row-shard exchange -> the hidden-fraction scalar the search
        # prices overlapped plans with
        ovl = measure_overlap_window(steps=min(steps, 60))
        if ovl is not None:
            ovl_out = os.path.join(os.path.dirname(out),
                                   "overlap_calibration.json")
            tmp = ovl_out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(ovl, f, indent=1)
            os.replace(tmp, ovl_out)
            print(f"overlap window: eff {ovl['overlap_efficiency']:.2f} "
                  f"(serial {ovl['t_serial_ms']:.3f} ms, overlap "
                  f"{ovl['t_overlap_ms']:.3f} ms) -> {ovl_out}")

    if not rows:
        print("no calibration points matched (CAL_ONLY filter?)")
        return rows
    worst = max(abs(r["err_measured"]) for r in rows)
    print(f"worst |err| (measured mode): {worst:.0%}")
    return rows


if __name__ == "__main__":
    main()
