#!/usr/bin/env python
"""Model-zoo throughput sweep (the BASELINE.md tracked configs).

Measures honest per-chip training throughput for each model family at its
reference benchmark shape, synchronizing every window with a dependent
host readback (async dispatch timing is fiction on some PJRT backends).
Prints one JSON line per config; bench.py remains the driver's single
headline metric.

Usage: python benchmarks/run_zoo.py [--quick] [--only NAME]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _measure(model, batch_dict, batch_size, steps=30, windows=3):
    import jax
    import jax.numpy as jnp

    db = model._device_batch(batch_dict)
    args = (model.params, model.opt_state, model.op_state,
            model._zero_msums(), db, jnp.asarray(0, jnp.int32))
    compiled = model._train_step.lower(*args).compile()
    p, o, s, m, st, mets = compiled(*args)
    float(mets["loss"])
    best = 0.0
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            p, o, s, m, st, mets = compiled(p, o, s, m, db, st)
        float(mets["loss"])                 # real synchronization
        best = max(best, steps * batch_size / (time.perf_counter() - t0))
    return best


def _bench_dlrm(cfg_factory, quick):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (build_dlrm, dlrm_strategy,
                                               synthetic_batch)
    batch = 256
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    dcfg = cfg_factory()
    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error", ["mse"],
                  strategies=dlrm_strategy(model, dcfg, 1))
    model.init_layers()
    x, y = synthetic_batch(dcfg, batch)
    x["label"] = y
    # short-step configs need DEEP windows: ~100 ms of tunnel dispatch
    # fill amortized over N steps adds 100/N ms to every apparent step
    return _measure(model, x, batch, steps=10 if quick else 500)


def bench_dlrm_random(quick):
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    return _bench_dlrm(DLRMConfig.random_benchmark, quick)


def bench_dlrm_criteo(quick):
    from dlrm_flexflow_tpu.models.dlrm import DLRMConfig
    return _bench_dlrm(DLRMConfig.criteo_kaggle, quick)


def _image_batch(batch, hw, classes=1000, seed=0):
    rng = np.random.RandomState(seed)
    return {"image": rng.rand(batch, 3, hw, hw).astype(np.float32),
            "label": rng.randint(0, classes, (batch, 1)).astype(np.int32)}


def bench_alexnet(quick):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.alexnet import build_alexnet
    batch = 256
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    cfg.conv_s2d = os.environ.get("FF_CONV_S2D", "off")
    if cfg.conv_s2d not in ("on", "off", "auto"):
        raise ValueError(f"FF_CONV_S2D expects on|off|auto, "
                         f"got {cfg.conv_s2d!r}")
    model = ff.FFModel(cfg)
    build_alexnet(model, num_classes=1000, image_hw=224)
    model.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    return _measure(model, _image_batch(batch, 224), batch,
                    steps=5 if quick else 60)


def bench_resnet18(quick):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.resnet import build_resnet
    batch = 256
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    cfg.conv_s2d = os.environ.get("FF_CONV_S2D", "off")
    if cfg.conv_s2d not in ("on", "off", "auto"):
        raise ValueError(f"FF_CONV_S2D expects on|off|auto, "
                         f"got {cfg.conv_s2d!r}")
    model = ff.FFModel(cfg)
    build_resnet(model, depth=18, num_classes=1000, image_hw=224)
    model.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    return _measure(model, _image_batch(batch, 224), batch,
                    steps=5 if quick else 60)


def bench_inception(quick):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.inception import build_inception_v3
    batch = 256
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    cfg.conv_s2d = os.environ.get("FF_CONV_S2D", "off")
    if cfg.conv_s2d not in ("on", "off", "auto"):
        raise ValueError(f"FF_CONV_S2D expects on|off|auto, "
                         f"got {cfg.conv_s2d!r}")
    model = ff.FFModel(cfg)
    build_inception_v3(model, num_classes=1000)
    model.compile(ff.SGDOptimizer(lr=0.01),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    return _measure(model, _image_batch(batch, 299), batch,
                    steps=3 if quick else 30, windows=2)


def bench_nmt(quick):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.nmt import build_nmt
    batch, seq, vocab = 64, 40, 32 * 1024
    model = ff.FFModel(ff.FFConfig(batch_size=batch,
                                   compute_dtype="bfloat16"))
    build_nmt(model, src_vocab=vocab, tgt_vocab=vocab, embed_dim=1024,
              hidden=1024, num_layers=2, src_len=seq, tgt_len=seq)
    model.compile(ff.SGDOptimizer(lr=0.1),
                  "sparse_categorical_crossentropy", ["accuracy"])
    model.init_layers()
    rng = np.random.RandomState(0)
    x = {"src": rng.randint(0, vocab, (batch, seq)).astype(np.int32),
         "tgt": rng.randint(0, vocab, (batch, seq)).astype(np.int32),
         "label": rng.randint(0, vocab, (batch, seq)).astype(np.int32)}
    return _measure(model, x, batch, steps=5 if quick else 100)


def bench_candle_uno(quick):
    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.candle_uno import build_candle_uno
    batch = 256
    model = ff.FFModel(ff.FFConfig(batch_size=batch,
                                   compute_dtype="bfloat16"))
    inputs = build_candle_uno(model)
    if isinstance(inputs, tuple):
        inputs = inputs[0]
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error", ["mse"])
    model.init_layers()
    rng = np.random.RandomState(0)
    x = {name: rng.rand(*shape).astype(np.float32)
         for name, shape in inputs.items()}
    x["label"] = rng.rand(batch, 1).astype(np.float32)
    return _measure(model, x, batch, steps=10 if quick else 500)


BENCHES = {
    "dlrm_random": bench_dlrm_random,
    "dlrm_criteo_kaggle": bench_dlrm_criteo,
    "alexnet_224": bench_alexnet,
    "resnet18_224": bench_resnet18,
    "inception_v3_299": bench_inception,
    "nmt_lstm_2x1024": bench_nmt,
    "candle_uno": bench_candle_uno,
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--only", default=None)
    args = ap.parse_args()
    for name, fn in BENCHES.items():
        if args.only and args.only not in name:
            continue
        try:
            sps = fn(args.quick)
            print(json.dumps({"config": name,
                              "samples_per_sec_per_chip": round(sps, 1)}),
                  flush=True)
        except Exception as e:  # keep sweeping
            print(json.dumps({"config": name, "error": str(e)[:200]}),
                  flush=True)


if __name__ == "__main__":
    main()
