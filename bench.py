#!/usr/bin/env python
"""Headline benchmark: DLRM random-data training throughput, samples/s/chip.

Mirrors the reference benchmark config (reference:
examples/cpp/DLRM/run_random.sh:1-10 — batch 256/device, 8 embedding tables
× 1M rows × 64-d, bot MLP 64-512-512-64, top MLP 576-1024-1024-1024-1) and
its throughput report (dlrm.cc:197-198: THROUGHPUT = samples*epochs/elapsed).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is the
ratio against the recorded previous round (BENCH_BASELINE file) or 1.0.
"""

import json
import os
import sys
import time

import numpy as np


def _emit_error(exc):
    """Print ONE machine-readable JSON line when the backend is down.

    Keeps BENCH_r*.json parseable through tunnel outages (round-3's
    BENCH_r03.json was a raw traceback) so the driver/judge can tell an
    infra outage apart from a perf regression. Exit code stays nonzero.
    """
    print(json.dumps({
        "metric": "dlrm_random_train_throughput_per_chip",
        "value": None,
        "unit": "samples/s/chip",
        "vs_baseline": None,
        "error": "tpu backend unavailable: %s" % next(
            (l.strip()[:200] for l in str(exc).splitlines() if l.strip()),
            type(exc).__name__),
    }))
    return 1


def _chip_health(jax, size=2048, iters0=100):
    """Measure the chip itself: in-jit bf16 matmul TFLOP/s + RPC roundtrip.

    The tunneled chip's condition varies between rounds (round 2: healthy,
    ~2.2 ms DLRM steps; round 3: down; round 4: reachable but ~3 TFLOP/s
    bf16 vs the v5e nominal ~394 and ~100 ms roundtrip). Reporting these
    two numbers alongside the throughput lets a reader normalize the
    headline across rounds. Timings force a device->host readback because
    block_until_ready does not actually wait on this PJRT backend.
    """
    import jax.numpy as jnp
    from jax import lax

    try:
        a = jnp.ones((size, size), jnp.bfloat16)

        tiny = jax.jit(lambda x: x + 1)
        float(tiny(jnp.float32(0.0)))
        rts = []
        for _ in range(5):
            t0 = time.time()
            float(tiny(jnp.float32(0.0)))
            rts.append(time.time() - t0)
        rt = min(rts)
        jitter = max(rts) - rt

        # the matmul window includes one roundtrip; subtract it. When the
        # compute is buried under roundtrip jitter (r4's probe returned
        # null at ~100 ms roundtrip), LENGTHEN the in-jit loop until it
        # dominates instead of giving up — one extra compile per retry,
        # bounded (VERDICT r4 weak #2)
        iters = iters0
        for _attempt in range(4):
            # return a scalar: reading back the full 8 MB product would
            # cost ~0.5 s over the tunnel and swamp the measurement
            mm = jax.jit(lambda a, n=iters: lax.fori_loop(
                0, n, lambda i, x: x @ a, a)[0, 0].astype(jnp.float32))
            float(mm(a))  # warm/compile + true wait
            mms = []
            for _ in range(5):
                t0 = time.time()
                float(mm(a))
                mms.append(time.time() - t0)
            compute_s = min(mms) - rt
            if compute_s >= max(2 * jitter, 1e-3):
                tflops = iters * 2 * size ** 3 / compute_s / 1e12
                return round(tflops, 1), round(rt * 1e3, 1)
            iters *= 8
        return None, round(rt * 1e3, 1)
    except Exception:
        return None, None


def main():
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               dlrm_strategy, synthetic_batch)

    try:
        return _run(jax, ff, DLRMConfig, build_dlrm, dlrm_strategy,
                    synthetic_batch)
    except (RuntimeError, OSError) as exc:
        # backend-init failure OR a tunnel drop mid-run (round 3's outage
        # began as hangs/errors during execution, not only at init) —
        # either way the output must stay one parseable JSON line
        return _emit_error(exc)


def _run(jax, ff, DLRMConfig, build_dlrm, dlrm_strategy, synthetic_batch):
    ndev = len(jax.devices())
    tflops, roundtrip_ms = _chip_health(jax)
    batch_per_chip = 256
    batch = batch_per_chip * ndev
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    dcfg = DLRMConfig.random_benchmark()

    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    strat = dlrm_strategy(model, dcfg, ndev)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error",
                  ["mse"], strategies=strat)
    model.init_layers()

    # stage batches on device once, then train from device-resident data —
    # the analog of the reference's design, which loads the ENTIRE dataset
    # into zero-copy memory up front and feeds each step with a
    # device-side scatter (load_entire_dataset + next_batch,
    # dlrm.cc:384-589); per-step host→device copies are not part of its
    # steady-state loop either
    nbatch = 8
    batches = []
    for i in range(nbatch):
        x, y = synthetic_batch(dcfg, batch, seed=i)
        x["label"] = y
        batches.append(model._device_batch(x))
    jax.block_until_ready(batches)

    # warmup/compile
    model.train_batch_device(batches[0])
    jax.block_until_ready(model.params)

    # measure several windows and report the best one: the jitted step is
    # ~0.1 ms, and a shared/tunneled chip sees external interference that
    # only ever slows a window down
    steps = max(1, int(os.environ.get("BENCH_STEPS", "500")))
    windows = int(os.environ.get("BENCH_WINDOWS", "5"))
    best = 0.0
    for _w in range(windows):
        t0 = time.time()
        mets = None
        for s in range(steps):
            mets = model.train_batch_device(batches[s % nbatch])
        # host readback forces TRUE completion of the whole window —
        # block_until_ready alone does not wait on some experimental
        # PJRT backends (observed on the axon tunnel)
        float(mets["loss"])
        elapsed = time.time() - t0
        best = max(best, steps * batch / elapsed)

    per_chip = best / ndev

    # opt-in recovery-cost smoke (BENCH_RESILIENCE=1): save/restore
    # latency, sentinel overhead, rollback recovery — kept out of the
    # default run so the headline metric's conditions stay comparable
    # across rounds
    resilience = None
    if os.environ.get("BENCH_RESILIENCE"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_resilience import measure as _res_measure
            resilience = _res_measure(steps=20)
        except Exception as exc:
            resilience = {"error": str(exc)[:200]}

    # opt-in input-pipeline smoke (BENCH_PIPELINE=1): staged vs streamed
    # vs prefetched steps/s + staging overlap fraction + host-table
    # double-buffering speedup
    pipeline = None
    if os.environ.get("BENCH_PIPELINE"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_pipeline import measure as _pipe_measure
            pipeline = _pipe_measure(steps=30)
        except Exception as exc:
            pipeline = {"error": str(exc)[:200]}

    # opt-in elastic-recovery smoke (BENCH_ELASTIC=1): detection latency,
    # re-search time, reshard time, steps/s before vs after a half-fleet
    # shrink
    elastic = None
    if os.environ.get("BENCH_ELASTIC"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_elastic import measure as _el_measure
            elastic = _el_measure(steps=20)
        except Exception as exc:
            elastic = {"error": str(exc)[:200]}

    # opt-in fused-superstep smoke (BENCH_SUPERSTEP=1): ms/step for
    # K ∈ {1,2,4,8,16} on the floor-sensitive DLRM configs plus the
    # measured dispatch floor (the K→∞ intercept)
    superstep = None
    if os.environ.get("BENCH_SUPERSTEP"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_superstep import measure as _ss_measure
            superstep = _ss_measure(
                steps=int(os.environ.get("BENCH_SUPERSTEP_STEPS", "48")))
        except Exception as exc:
            superstep = {"error": str(exc)[:200]}

    # opt-in serving smoke (BENCH_SERVE=1): offline vs online throughput,
    # p99 across bucket/deadline settings, embedding cache on/off
    serve = None
    if os.environ.get("BENCH_SERVE"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_serve import measure as _serve_measure
            serve = _serve_measure(
                requests=int(os.environ.get("BENCH_SERVE_REQUESTS", "256")))
        except Exception as exc:
            serve = {"error": str(exc)[:200]}

    # opt-in embedding-sharding smoke (BENCH_SHARD=1): row-sharded
    # all-to-all lookups vs replicated vs table-sharded steps/s,
    # a2a bytes/step, and the simulated pod-topology sweep
    shard = None
    if os.environ.get("BENCH_SHARD"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_shard import measure as _shard_measure
            shard = _shard_measure(
                steps=int(os.environ.get("BENCH_SHARD_STEPS", "12")))
        except Exception as exc:
            shard = {"error": str(exc)[:200]}

    # opt-in pipelined-exchange smoke (BENCH_OVERLAP=1): row-shard
    # all-to-all overlap on/off step time, the trace-span-derived
    # exposed-comm fraction, and the simulated DCN-topology bar
    overlap = None
    if os.environ.get("BENCH_OVERLAP"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_overlap import measure as _ovl_measure
            overlap = _ovl_measure(
                steps=int(os.environ.get("BENCH_OVERLAP_STEPS", "8")))
        except Exception as exc:
            overlap = {"error": str(exc)[:200]}

    # opt-in lowered-HLO collective audit (BENCH_AUDIT=1): predicted-vs-
    # lowered collective-bytes drift for the bench_shard row-sharded and
    # replicated plans (shardcheck FLX51x over the real bench model)
    audit = None
    if os.environ.get("BENCH_AUDIT"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_audit import measure as _audit_measure
            audit = _audit_measure(
                tolerance=float(os.environ.get("BENCH_AUDIT_TOLERANCE",
                                               "0.25")))
        except Exception as exc:
            audit = {"error": str(exc)[:200]}

    # opt-in serving-fleet smoke (BENCH_SERVE_FLEET=1): attained QPS at
    # a p99 SLO for 1/2/4 replicas under open-loop Poisson load, zero
    # failed requests with one replica killed mid-run, continuous vs
    # flush-cycle batching throughput
    serve_fleet = None
    if os.environ.get("BENCH_SERVE_FLEET"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_serve_fleet import measure as _fleet_measure
            serve_fleet = _fleet_measure(
                requests=int(os.environ.get("BENCH_SERVE_FLEET_REQUESTS",
                                            "256")),
                slo_ms=float(os.environ.get("BENCH_SERVE_FLEET_SLO_MS",
                                            "50")))
        except Exception as exc:
            serve_fleet = {"error": str(exc)[:200]}

    # opt-in continual-learning freshness smoke (BENCH_FRESHNESS=1):
    # train-step → servable p50/p99 for delta-chain publication vs
    # full-checkpoint reloads on a tables-dominated DLRM
    freshness = None
    if os.environ.get("BENCH_FRESHNESS"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_freshness import measure as _fresh_measure
            freshness = _fresh_measure(
                publishes=int(os.environ.get("BENCH_FRESHNESS_PUBLISHES",
                                             "12")))
        except Exception as exc:
            freshness = {"error": str(exc)[:200]}

    # opt-in quantized-storage smoke (BENCH_QUANT=1): footprint /
    # exchange / delta-publish / cache byte ratios under the int8 row
    # policy, plus the AUC cost on a kaggle-shaped model
    quant = None
    if os.environ.get("BENCH_QUANT"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_quant import measure as _quant_measure
            quant = _quant_measure(
                auc_epochs=int(os.environ.get("BENCH_QUANT_EPOCHS",
                                              "2")))
        except Exception as exc:
            quant = {"error": str(exc)[:200]}

    # opt-in observability-overhead smoke (BENCH_OBS=1): train steps/s
    # and serve p99 with --obs off vs on (bar: <= 2% on both) plus the
    # trace-export size/latency for a 200-step run
    obs = None
    if os.environ.get("BENCH_OBS"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_obs import measure as _obs_measure
            obs = _obs_measure(
                steps=int(os.environ.get("BENCH_OBS_STEPS", "200")))
        except Exception as exc:
            obs = {"error": str(exc)[:200]}

    # opt-in closed-loop online-learning smoke (BENCH_SCENARIO=1): the
    # compressed drifting-zipf replay — feedback-spool training, delta
    # publication, live hot/cold re-placement — reporting AUC / p99 /
    # fleet size / freshness lag and whether every budget held with
    # chaos active
    scenario = None
    if os.environ.get("BENCH_SCENARIO"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_scenario import measure as _scn_measure
            scenario = _scn_measure(
                steps=int(os.environ.get("BENCH_SCENARIO_STEPS", "48")))
        except Exception as exc:
            scenario = {"error": str(exc)[:200]}

    # opt-in retrieval smoke (BENCH_RETRIEVE=1): recall@100 of the int8
    # sharded MIPS top-k vs the fp32 exact scan (bar: >= 0.95), per-
    # shard scoring throughput for 1/2/4 shards, and cascade QPS at a
    # p99 SLO under open-loop Poisson load with a one-shard-dead chaos
    # phase (bar: zero failed requests, degraded-flagged only)
    retrieve = None
    if os.environ.get("BENCH_RETRIEVE"):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
        try:
            from bench_retrieve import measure as _rtv_measure
            retrieve = _rtv_measure(
                requests=int(os.environ.get("BENCH_RETRIEVE_REQUESTS",
                                            "128")),
                slo_ms=float(os.environ.get("BENCH_RETRIEVE_SLO_MS",
                                            "150")))
        except Exception as exc:
            retrieve = {"error": str(exc)[:200]}

    vs = 1.0
    base_file = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE")
    if os.path.exists(base_file):
        try:
            vs = per_chip / float(open(base_file).read().strip())
        except Exception:
            vs = 1.0

    out = {
        "metric": "dlrm_random_train_throughput_per_chip",
        "value": round(per_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(vs, 4),
        # chip condition at measurement time (None if unmeasurable);
        # v5e nominal is ~394 bf16 TFLOP/s and sub-ms dispatch — large
        # deviations mean the number above reflects the tunnel, not the code
        "chip_bf16_tflops": tflops,
        "chip_roundtrip_ms": roundtrip_ms,
    }
    if resilience is not None:
        out["resilience"] = resilience
    if pipeline is not None:
        out["pipeline"] = pipeline
    if elastic is not None:
        out["elastic"] = elastic
    if superstep is not None:
        out["superstep"] = superstep
    if serve is not None:
        out["serve"] = serve
    if serve_fleet is not None:
        out["serve_fleet"] = serve_fleet
    if shard is not None:
        out["shard"] = shard
    if overlap is not None:
        out["overlap"] = overlap
    if audit is not None:
        out["audit"] = audit
    if freshness is not None:
        out["freshness"] = freshness
    if quant is not None:
        out["quant"] = quant
    if obs is not None:
        out["obs"] = obs
    if scenario is not None:
        out["scenario"] = scenario
    if retrieve is not None:
        out["retrieve"] = retrieve
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
