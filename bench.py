#!/usr/bin/env python
"""Headline benchmark: DLRM random-data training throughput, samples/s/chip.

Mirrors the reference benchmark config (reference:
examples/cpp/DLRM/run_random.sh:1-10 — batch 256/device, 8 embedding tables
× 1M rows × 64-d, bot MLP 64-512-512-64, top MLP 576-1024-1024-1024-1) and
its throughput report (dlrm.cc:197-198: THROUGHPUT = samples*epochs/elapsed).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is the
ratio against the recorded previous round (BENCH_BASELINE file) or 1.0.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               dlrm_strategy, synthetic_batch)

    ndev = len(jax.devices())
    batch_per_chip = 256
    batch = batch_per_chip * ndev
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    dcfg = DLRMConfig.random_benchmark()

    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    strat = dlrm_strategy(model, dcfg, ndev)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error",
                  ["mse"], strategies=strat)
    model.init_layers()

    # stage batches on device once, then train from device-resident data —
    # the analog of the reference's design, which loads the ENTIRE dataset
    # into zero-copy memory up front and feeds each step with a
    # device-side scatter (load_entire_dataset + next_batch,
    # dlrm.cc:384-589); per-step host→device copies are not part of its
    # steady-state loop either
    nbatch = 8
    batches = []
    for i in range(nbatch):
        x, y = synthetic_batch(dcfg, batch, seed=i)
        x["label"] = y
        batches.append(model._device_batch(x))
    jax.block_until_ready(batches)

    # warmup/compile
    model.train_batch_device(batches[0])
    jax.block_until_ready(model.params)

    # measure several windows and report the best one: the jitted step is
    # ~0.1 ms, and a shared/tunneled chip sees external interference that
    # only ever slows a window down
    steps = max(1, int(os.environ.get("BENCH_STEPS", "500")))
    windows = int(os.environ.get("BENCH_WINDOWS", "5"))
    best = 0.0
    for _w in range(windows):
        t0 = time.time()
        mets = None
        for s in range(steps):
            mets = model.train_batch_device(batches[s % nbatch])
        # host readback forces TRUE completion of the whole window —
        # block_until_ready alone does not wait on some experimental
        # PJRT backends (observed on the axon tunnel)
        float(mets["loss"])
        elapsed = time.time() - t0
        best = max(best, steps * batch / elapsed)

    per_chip = best / ndev

    vs = 1.0
    base_file = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE")
    if os.path.exists(base_file):
        try:
            vs = per_chip / float(open(base_file).read().strip())
        except Exception:
            vs = 1.0

    print(json.dumps({
        "metric": "dlrm_random_train_throughput_per_chip",
        "value": round(per_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
