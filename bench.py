#!/usr/bin/env python
"""Headline benchmark: DLRM random-data training throughput, samples/s/chip.

Mirrors the reference benchmark config (reference:
examples/cpp/DLRM/run_random.sh:1-10 — batch 256/device, 8 embedding tables
× 1M rows × 64-d, bot MLP 64-512-512-64, top MLP 576-1024-1024-1024-1) and
its throughput report (dlrm.cc:197-198: THROUGHPUT = samples*epochs/elapsed).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no numbers (BASELINE.md), so vs_baseline is the
ratio against the recorded previous round (BENCH_BASELINE file) or 1.0.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    import dlrm_flexflow_tpu as ff
    from dlrm_flexflow_tpu.models.dlrm import (DLRMConfig, build_dlrm,
                                               dlrm_strategy, synthetic_batch)

    ndev = len(jax.devices())
    batch_per_chip = 256
    batch = batch_per_chip * ndev
    cfg = ff.FFConfig(batch_size=batch, compute_dtype="bfloat16")
    dcfg = DLRMConfig.random_benchmark()

    model = ff.FFModel(cfg)
    build_dlrm(model, dcfg)
    strat = dlrm_strategy(model, dcfg, ndev)
    model.compile(ff.SGDOptimizer(lr=0.01), "mean_squared_error",
                  ["mse"], strategies=strat)
    model.init_layers()

    # pre-generate host batches; the loop includes H2D staging like the
    # reference's zero-copy -> FB scatter (dlrm.cc:486-589)
    nbatch = 8
    batches = []
    for i in range(nbatch):
        x, y = synthetic_batch(dcfg, batch, seed=i)
        x["label"] = y
        batches.append(x)

    # warmup/compile
    model.train_batch(batches[0])
    jax.block_until_ready(model.params)

    steps = int(os.environ.get("BENCH_STEPS", "50"))
    t0 = time.time()
    for s in range(steps):
        model.train_batch(batches[s % nbatch])
    jax.block_until_ready(model.params)
    elapsed = time.time() - t0

    samples_per_sec = steps * batch / elapsed
    per_chip = samples_per_sec / ndev

    vs = 1.0
    base_file = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE")
    if os.path.exists(base_file):
        try:
            vs = per_chip / float(open(base_file).read().strip())
        except Exception:
            vs = 1.0

    print(json.dumps({
        "metric": "dlrm_random_train_throughput_per_chip",
        "value": round(per_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
