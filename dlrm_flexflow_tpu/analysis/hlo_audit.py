"""Lowered-HLO collective auditor (the FLX51x rules).

The static plan verifier (:mod:`.shardcheck`) reasons about what GSPMD
*will* do; this module checks what it *did*: AOT-lower the train step /
serving forward through `FFModel.lowered_train_hlo` /
`lowered_eval_hlo` (the post-SPMD-partitioning program, every inserted
collective visible at concrete per-device shapes) and scan the text for
hazards the type system cannot express:

- FLX511 hlo-table-collective — an all-gather / all-reduce /
  reduce-scatter moving a table-scale buffer. This is the lowered form
  of the silent 66x failure: a replicated table under data-parallel
  updates lowers to a full-table gradient collective every step.
- FLX512 hlo-missed-donation — a large entry parameter with no
  input-output alias: the buffer double-allocates (donate_argnums
  regressions show up here before they show up as OOMs).
- FLX513 hlo-collective-drift — measured collective bytes disagree with
  the cost model's prediction beyond tolerance: the strategy search is
  pricing a different program than the one that runs.

Byte accounting convention: a collective "costs" its per-device buffer
bytes (tuple results sum their elements) — the same quantity the
predictions compute, so measured and predicted compare like for like.
The drift report also carries the BALANCED (ragged/production) exchange
bytes the cost model prices, so the dense-padding factor stays visible
instead of being silently mixed into "drift".
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding, make_finding, sort_findings
from .shardcheck import _fmt_bytes, table_scale_threshold

# entry parameters at/above this size must be donated unless they are
# step inputs (batches re-stage every step and cannot alias)
DONATE_MIN_BYTES = 1 << 20

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z]+\d*)\[([\d,]*)\]")
_COLLECTIVE_RE = re.compile(
    r"%?([\w.-]+) = (\([^)]*\)|[a-z]+\d*\[[\d,]*\][^ ]*) "
    r"(all-gather|all-reduce|all-to-all|reduce-scatter|"
    r"collective-permute)(?:-start)?\(")


def _shape_bytes(dtype: str, dims: str) -> float:
    isz = _DTYPE_BYTES.get(dtype)
    if isz is None:
        return 0.0
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return float(n * isz)


def _type_bytes(type_str: str) -> float:
    """Bytes of an HLO result type: plain `f32[4,16384,32]{...}` or a
    tuple `(s32[1,32]{1,0}, s32[1,32]{1,0}, ...)` (summed)."""
    return sum(_shape_bytes(dt, dims)
               for dt, dims in _SHAPE_RE.findall(type_str))


class HloAudit:
    """Parsed collective/donation facts of one lowered module."""

    def __init__(self, text: str):
        self.collectives: List[Tuple[str, str, float]] = []  # kind,name,B
        for m in _COLLECTIVE_RE.finditer(text):
            name, type_str, kind = m.group(1), m.group(2), m.group(3)
            self.collectives.append((kind, name, _type_bytes(type_str)))
        self.counts: Dict[str, int] = {}
        self.bytes_by_kind: Dict[str, float] = {}
        for kind, _name, b in self.collectives:
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.bytes_by_kind[kind] = self.bytes_by_kind.get(kind,
                                                             0.0) + b
        self.entry_param_bytes = self._parse_entry_params(text)
        self.aliased_params = self._parse_aliased(text)

    @staticmethod
    def _parse_entry_params(text: str) -> List[float]:
        m = re.search(r"entry_computation_layout=\{\((.*?)\)->", text,
                      re.S)
        if not m:
            return []
        return [_shape_bytes(dt, dims)
                for dt, dims in _SHAPE_RE.findall(m.group(1))]

    @staticmethod
    def _parse_aliased(text: str) -> set:
        start = text.find("input_output_alias={")
        if start < 0:
            return set()
        # brace-balanced scan: alias entries nest one level ({0}: (0,
        # {}, may-alias)), so a lazy regex would cut at the first '}'
        i = text.index("{", start)
        depth, j = 0, i
        while j < len(text):
            if text[j] == "{":
                depth += 1
            elif text[j] == "}":
                depth -= 1
                if depth == 0:
                    break
            j += 1
        return {int(p) for p in
                re.findall(r":\s*\((\d+)", text[i:j + 1])}


def predicted_collective_bytes(model) -> Dict[str, float]:
    """Per-device collective bytes per train step the cost model's view
    of the COMPILED strategies implies.

    - ``all-to-all``: the dense padded row-shard exchange
      (`parallel.alltoall.dense_exchange_hlo_bytes`) for every op with
      an active `_row_plan` — the lowering is deterministic, so this is
      exact, not approximate.
    - ``all-to-all-balanced``: the balanced (ragged/production) exchange
      the cost model actually prices (`exchange_bytes_per_step`), for
      the drift report's context.
    - ``all-reduce``: the data-parallel gradient sync the simulator
      prices per parameter — min(shard bytes, touched bytes), fp32 —
      for every replicated-updated op. A replicated table on the dense
      path predicts its full table here; measured exceeding predicted
      is exactly the cost-model drift FLX513 exists to surface.
    """
    from ..core.op import InputOp
    from ..parallel.alltoall import (dedup_exchange_hlo_bytes,
                                     dense_exchange_hlo_bytes,
                                     exchange_bytes_per_step)
    host_res = set(getattr(model, "_host_resident_ops", set()) or set())
    out = {"all-to-all": 0.0, "all-to-all-balanced": 0.0,
           "all-reduce": 0.0}
    ndev = int(model.mesh.size) if model.mesh is not None else 1
    for op in model.ops:
        if isinstance(op, InputOp) or op.name in host_res:
            continue
        plan = getattr(op, "_row_plan", None)
        if plan is not None:
            from ..ops.embedding import (_lookup_count,
                                         expected_routed_lookups)
            lookups = int(_lookup_count(op))
            d = op.out_dim
            # the padded exchange the lowering actually emits: dense
            # capacity n_local, or min(n_local, flat cold rows) under
            # dedup — both deterministic, so drift pins exactly
            fn = (dedup_exchange_hlo_bytes if plan.dedup
                  else dense_exchange_hlo_bytes)
            out["all-to-all"] += fn(plan, lookups, d)
            # the balanced/ragged bytes the cost model prices — with
            # the skew term (expected distinct / cold-only routed ids)
            # when the strategy carries a skew policy
            pc = (getattr(model, "strategies", None) or {}).get(op.name)
            distinct = None
            if pc is not None and (
                    getattr(pc, "exchange", "dense") == "dedup"
                    or getattr(pc, "hot_fraction", 0.0) > 0):
                distinct = expected_routed_lookups(
                    op, pc, lookups / max(ndev, 1))
            out["all-to-all-balanced"] += exchange_bytes_per_step(
                plan, lookups, d, distinct_per_device=distinct)
            continue
        if not op.param_defs():
            continue
        pc = model.strategies.get(op.name)
        if pc is None or pc.device_type == "CPU":
            continue
        replicas = pc.degrees[0] if pc.degrees else 1
        if replicas <= 1:
            continue
        # predicted at each param's DECLARED dtype (what the lowering
        # actually moves — a bf16 table's gradient all-reduce is half
        # the fp32 bytes; the old flat 4 B/elem over-billed it)
        import jax.numpy as jnp
        defs = op.param_defs()
        shard_bytes = sum(
            math.prod(shape)
            * float(jnp.dtype(defs[p].dtype).itemsize if p in defs else 4)
            for p, shape in op.param_shard_shapes(pc, ndev).items())
        touched = op.param_bytes_touched_per_step(max(pc.num_parts, 1))
        out["all-reduce"] += min(shard_bytes, touched)
    return out


def audit_hlo_text(text: str, *, table_scale_bytes: Optional[float],
                   nondonated_ok_bytes: float = 0.0,
                   check_donation: bool = True,
                   path: str = "<hlo>",
                   scope: str = "train_step"
                   ) -> Tuple[List[Finding], HloAudit]:
    """Structure-only audit of one lowered module (FLX511/512). Pure
    text analysis so tests can feed synthetic modules; byte thresholds
    come from the caller."""
    audit = HloAudit(text)
    findings: List[Finding] = []
    if table_scale_bytes is not None:
        for kind, name, b in audit.collectives:
            if kind in ("all-gather", "all-reduce", "reduce-scatter") \
                    and b >= table_scale_bytes:
                findings.append(make_finding(
                    "FLX511", path, 0,
                    f"{scope}: {kind} {name!r} moves {_fmt_bytes(b)} "
                    f"(table-scale) every step — an implicit reshard or "
                    f"replicated-table gradient sync; row-shard the "
                    f"table (param_degree) or fix the producer/consumer "
                    f"shardings", scope=scope, token=f"{kind}:{name}"))
    if check_donation:
        floor = max(float(DONATE_MIN_BYTES), float(nondonated_ok_bytes))
        for i, b in enumerate(audit.entry_param_bytes):
            if b > floor and i not in audit.aliased_params:
                findings.append(make_finding(
                    "FLX512", path, 0,
                    f"{scope}: entry parameter {i} ({_fmt_bytes(b)}) is "
                    f"not input-output aliased — the buffer double-"
                    f"allocates (missed donate_argnums?)",
                    scope=scope, token=f"param{i}"))
    return findings, audit


def audit_model(model, device_batch=None, *, tolerance: float = 0.25,
                table_scale_bytes: Optional[float] = None,
                include_eval: bool = False,
                path: str = "<model>"
                ) -> Tuple[List[Finding], Dict[str, object]]:
    """Lower the model's train step (and optionally the serving forward)
    and audit the partitioned HLO. Returns (findings, report); report
    carries per-kind collective counts/bytes, the cost-model
    predictions, and the relative drift per kind."""
    tscale = table_scale_threshold(model, table_scale_bytes)
    # batch inputs re-stage every step and legitimately aren't donated;
    # anything bigger than the largest batch leaf must alias
    ndev = int(model.mesh.size) if model.mesh is not None else 1
    batch_leaf = 0.0
    for t in model.input_tensors + ([model.label_tensor]
                                    if model.label_tensor is not None
                                    else []):
        import numpy as np
        import jax.numpy as jnp
        b = float(math.prod(t.shape)) * jnp.dtype(t.dtype).itemsize
        batch_leaf = max(batch_leaf, b / max(ndev, 1))
    text = model.lowered_train_hlo(device_batch)
    findings, audit = audit_hlo_text(
        text, table_scale_bytes=tscale, nondonated_ok_bytes=batch_leaf,
        path=path, scope="train_step")

    predicted = predicted_collective_bytes(model)
    measured = dict(audit.bytes_by_kind)
    report: Dict[str, object] = {
        "collective_counts": dict(audit.counts),
        "measured_bytes": {k: round(v) for k, v in measured.items()},
        "predicted_bytes": {k: round(v) for k, v in predicted.items()},
        "tolerance": tolerance,
    }
    drift: Dict[str, float] = {}
    # all-to-all: the dense exchange is deterministic — symmetric drift.
    # An OVERLAPPED single-axis exchange lowers its S-1 pipelined rounds
    # as collective-permutes, not one fused all-to-all (the bytes are the
    # same exchange, just decomposed — and the ring's missing self-block
    # is already out of the prediction via _exchange_buffer_blocks), so
    # fold the measured permute bytes into the exchange bucket whenever
    # any compiled row plan pipelines
    meas_a2a = measured.get("all-to-all", 0.0)
    if any(getattr(getattr(op, "_row_plan", None), "overlap", False)
           for op in model.ops):
        meas_a2a += measured.get("collective-permute", 0.0)
    pred_a2a = predicted.get("all-to-all", 0.0)
    if pred_a2a > 0:
        drift["all-to-all"] = abs(meas_a2a - pred_a2a) / pred_a2a
        if drift["all-to-all"] > tolerance:
            findings.append(make_finding(
                "FLX513", path, 0,
                f"all-to-all bytes drift: lowered HLO moves "
                f"{_fmt_bytes(meas_a2a)}/device/step, the cost model "
                f"prices {_fmt_bytes(pred_a2a)} "
                f"({drift['all-to-all']:+.0%} vs tolerance "
                f"{tolerance:.0%}) — the search is pricing a different "
                f"exchange than the one that runs",
                scope="train_step", token="a2a-drift"))
    elif meas_a2a > 0:
        drift["all-to-all"] = float("inf")
    # all-reduce: scalar metric/loss reductions ride along, so only an
    # EXCESS beyond tolerance (and at least 1 MiB) is drift — that is
    # precisely the replicated-table gradient the model did not price
    pred_ar = predicted.get("all-reduce", 0.0)
    meas_ar = measured.get("all-reduce", 0.0)
    if pred_ar > 0 or meas_ar > 0:
        base = max(pred_ar, 1.0)
        drift["all-reduce"] = (meas_ar - pred_ar) / base
        if (meas_ar - pred_ar) > tolerance * base \
                and (meas_ar - pred_ar) >= float(1 << 20):
            findings.append(make_finding(
                "FLX513", path, 0,
                f"all-reduce bytes drift: lowered HLO moves "
                f"{_fmt_bytes(meas_ar)}/device/step, the cost model "
                f"prices {_fmt_bytes(pred_ar)} — GSPMD is syncing "
                f"{_fmt_bytes(meas_ar - pred_ar)} the search never "
                f"charged for (replicated-table gradient?)",
                scope="train_step", token="ar-drift"))
    report["drift"] = {k: (round(v, 4) if v != float("inf") else "inf")
                       for k, v in drift.items()}

    if include_eval:
        eval_text = model.lowered_eval_hlo()
        eval_findings, eval_audit = audit_hlo_text(
            eval_text, table_scale_bytes=tscale, check_donation=False,
            path=path, scope="eval_step")
        findings.extend(eval_findings)
        report["eval_collective_counts"] = dict(eval_audit.counts)
    return sort_findings(findings), report


def audit_interaction_fusion(model, device_batch=None, *,
                             path: str = "<model>") -> List[Finding]:
    """FLX515: verify the fused dot-interaction actually fused.

    For every FusedDotInteraction op, scan the lowered SERVING forward
    (the fusion is a forward claim — the training backward re-derives
    g_Z in plain XLA by design) for a rank-3 [*, F, F] buffer. The fused
    Pallas lowering keeps Z in VMEM, so any such buffer means the op
    fell back to the unfused jnp path (non-TPU backend, unsupported
    width, multi-chip mesh, host offload) — silently giving back the
    HBM round-trips the plan was priced without."""
    from ..ops.interaction import FusedDotInteraction
    fused = [op for op in model.ops
             if isinstance(op, FusedDotInteraction)]
    if not fused:
        return []
    text = model.lowered_eval_hlo(device_batch)
    findings: List[Finding] = []
    for op in fused:
        F = op.num_tables + 1
        pat = re.compile(r"[a-z]+\d*\[(\d+),%d,%d\]" % (F, F))
        hits = {m.group(0) for m in pat.finditer(text)}
        if not hits:
            continue
        shapes = ", ".join(sorted(hits)[:4])
        findings.append(make_finding(
            "FLX515", path, 0,
            f"{op.name!r}: lowered serving HLO materializes the "
            f"pairwise-dot interaction tensor ({shapes}) — the fused "
            f"Pallas kernel fell back to the unfused gather→bmm→tril "
            f"chain (non-TPU backend, dim % 128 != 0, multi-chip mesh, "
            f"or host offload), paying the [B, F, F] HBM round-trips "
            f"the fused plan was priced without",
            scope=op.name, token="interaction-materialized"))
    return sort_findings(findings)


def audit_file(path: str, model_name: Optional[str] = None,
               ndev: Optional[int] = None, batch: Optional[int] = None,
               tolerance: float = 0.25
               ) -> Tuple[List[Finding], Dict[str, object]]:
    """CLI entry: build + compile the strategy file's target model on
    the attached devices and audit its lowered train step. Raises
    RuntimeError when the local device count cannot host the plan's
    mesh (the static verifier still covers those plans)."""
    import os

    import jax

    from .shardcheck import build_target_model, infer_target
    from ..parallel.mesh import make_mesh
    from ..parallel.strategy_io import load_strategies
    inferred = infer_target(path)
    if model_name is None or ndev is None:
        if inferred is None:
            raise ValueError(
                f"{path}: cannot infer target model/mesh — pass "
                f"--model/--ndev")
        model_name = model_name or inferred[0]
        ndev = ndev or inferred[1]
    devs = jax.devices()
    if len(devs) < ndev:
        raise RuntimeError(
            f"audit needs {ndev} local devices, have {len(devs)} "
            f"(JAX_PLATFORMS=cpu + XLA_FLAGS "
            f"--xla_force_host_platform_device_count={ndev} to "
            f"virtualize)")
    model = build_target_model(model_name, ndev, batch=batch)
    strategies = load_strategies(path, num_devices=ndev,
                                 known_ops={op.name for op in model.ops})
    from ..core.optimizers import SGDOptimizer
    model.compile(SGDOptimizer(lr=0.05), "mean_squared_error", ["mse"],
                  mesh=make_mesh(devices=devs[:ndev]),
                  strategies=strategies)
    model.init_layers()
    return audit_model(model, tolerance=tolerance,
                       path=os.path.basename(path))
