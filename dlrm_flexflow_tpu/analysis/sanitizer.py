"""Runtime lock-order sanitizer (lockdep-lite), opt-in via FF_SANITIZE=1.

The static passes in this package reason about the code; this module
watches the PROCESS. Every interesting lock in the framework is created
through :func:`make_lock`:

- ``FF_SANITIZE`` unset/0 (the default): :func:`make_lock` returns a
  plain ``threading.Lock`` — literally the same object type as before,
  zero proxy overhead on the hot path (tests pin this by type identity
  and a micro-benchmark bound).
- ``FF_SANITIZE=1``: the returned :class:`TrackedLock` records, per
  thread, the stack of held locks and feeds a process-global
  acquisition-order graph. Three checks run live:

  1. **Lock-order cycles** (ThreadSanitizer's deadlock inference): if
     lock B is ever acquired while holding A, the edge A→B is recorded;
     a later acquisition establishing a path B→…→A reports a cycle —
     BEFORE the interleaving that would actually deadlock ever runs.
  2. **Held-too-long**: a lock held longer than
     ``FF_SANITIZE_HOLD_S`` (default 1.0s) is reported on release —
     the serving engine's p99 lives under these locks.
  3. **Dispatch-under-lock**: locks created with ``no_dispatch=True``
     (the engine's dispatch/swap lock, the model's host-table lock)
     must never be held across a JAX dispatch; the model's dispatch
     sites call :func:`note_jax_dispatch`, and a violation raises
     :class:`DispatchUnderLock` (a
     :class:`~..utils.watchdog.WorkerStalled`) carrying the structured
     StallReport.

Violations are recorded in a process-global list (:func:`violations`)
and logged; only dispatch-under-lock raises (it is always a bug in THIS
process's call stack). ``FF_SANITIZE=strict`` additionally raises on
lock-order cycles — used by the fixtures that pin detection.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

_MODE = os.environ.get("FF_SANITIZE", "0").strip().lower()
_ENABLED = _MODE not in ("", "0", "false", "off")
_STRICT = _MODE == "strict"
_HOLD_S = float(os.environ.get("FF_SANITIZE_HOLD_S", "1.0") or 0)


def enabled() -> bool:
    return _ENABLED


def override(on: bool, strict: bool = False, hold_s: Optional[float]
             = None):
    """Context manager flipping the sanitizer for tests. Only affects
    locks CREATED inside the scope (existing plain locks stay plain)."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        global _ENABLED, _STRICT, _HOLD_S
        prev = (_ENABLED, _STRICT, _HOLD_S)
        _ENABLED, _STRICT = bool(on), bool(strict)
        if hold_s is not None:
            _HOLD_S = float(hold_s)
        try:
            yield
        finally:
            _ENABLED, _STRICT, _HOLD_S = prev

    return _scope()


class LockOrderViolation(RuntimeError):
    """A lock-acquisition order cycle was observed (deadlock hazard)."""

    def __init__(self, report):
        super().__init__(str(report))
        self.report = report


class DispatchUnderLock(RuntimeError):
    """JAX dispatch attempted while holding a no-dispatch lock."""

    def __init__(self, report):
        super().__init__(str(report))
        self.report = report


class _State:
    """Process-global sanitizer state: the acquisition graph + record of
    violations. Its own plain (untracked) lock guards the graph."""

    def __init__(self):
        self.lock = threading.Lock()
        # edge "A" -> set of "B" acquired while holding A, with one
        # representative site per edge
        self.graph: Dict[str, Set[str]] = {}
        self.edge_site: Dict[Tuple[str, str], str] = {}
        self.violations: List = []   # StallReport list
        self.tls = threading.local()

    def held(self) -> List["TrackedLock"]:
        return getattr(self.tls, "stack", [])

    def _path(self, a: str, b: str) -> Optional[List[str]]:
        """Edge path a→…→b in the graph, or None."""
        seen = {a}
        stack = [(a, [a])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(self.graph.get(node, ())):
                if nxt == b:
                    return path + [b]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


_STATE = _State()


def _stall_report(waiting_for: str, detail: str, waited_s: float = 0.0,
                  deadline_s: float = 0.0):
    from ..utils.watchdog import StallReport
    return StallReport(worker=threading.current_thread().name,
                       waiting_for=waiting_for, waited_s=waited_s,
                       deadline_s=deadline_s, detail=detail)


def _log():
    from ..utils.logging import get_logger
    return get_logger("sanitizer")


class TrackedLock:
    """Named ``threading.Lock`` proxy feeding the sanitizer. API-matches
    the subset of Lock the framework uses (acquire/release/context
    manager/locked)."""

    __slots__ = ("name", "no_dispatch", "_lock", "_t_acquired")

    def __init__(self, name: str, no_dispatch: bool = False):
        self.name = name
        self.no_dispatch = no_dispatch
        self._lock = threading.Lock()
        self._t_acquired = 0.0

    # --- Lock protocol -------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            try:
                self._note_acquired()
            except BaseException:   # strict-mode cycle report: do not
                self._lock.release()   # leave the lock held behind the
                raise                  # raising __enter__
        return got

    def release(self) -> None:
        self._note_released()
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TrackedLock {self.name!r}>"

    # --- sanitizer hooks ----------------------------------------------
    def _note_acquired(self) -> None:
        st = _STATE
        stack = getattr(st.tls, "stack", None)
        if stack is None:
            stack = st.tls.stack = []
        self._t_acquired = time.monotonic()
        if stack:
            with st.lock:
                for held in stack:
                    if held.name == self.name:
                        continue
                    back = st._path(self.name, held.name)
                    fresh = self.name not in st.graph.get(held.name,
                                                          ())
                    st.graph.setdefault(held.name, set()).add(self.name)
                    st.edge_site.setdefault(
                        (held.name, self.name),
                        threading.current_thread().name)
                    if back is not None and fresh:
                        cyc = [held.name] + back
                        rep = _stall_report(
                            f"lock {self.name!r}",
                            f"lock-order cycle: {' -> '.join(cyc)} "
                            f"(opposite acquisition orders observed)")
                        st.violations.append(rep)
                        _log().error("lock-order cycle detected: %s",
                                     rep)
                        if _STRICT:
                            raise LockOrderViolation(rep)
        stack.append(self)

    def _note_released(self) -> None:
        st = _STATE
        stack = getattr(st.tls, "stack", None)
        if stack and self in stack:
            stack.remove(self)
        held = time.monotonic() - self._t_acquired
        if _HOLD_S > 0 and held > _HOLD_S:
            rep = _stall_report(
                f"release of lock {self.name!r}",
                f"lock held {held:.3g}s (> {_HOLD_S:.3g}s budget) — "
                f"every contending thread stalled that long",
                waited_s=held, deadline_s=_HOLD_S)
            st.violations.append(rep)
            _log().warning("lock held too long: %s", rep)


def make_lock(name: str, no_dispatch: bool = False):
    """The framework's lock factory. Disabled (the default): a plain
    ``threading.Lock`` — zero overhead, type-identical to before.
    Enabled: a named :class:`TrackedLock` feeding the sanitizer."""
    if not _ENABLED:
        return threading.Lock()
    return TrackedLock(name, no_dispatch=no_dispatch)


def note_jax_dispatch(what: str = "dispatch") -> None:
    """Called at the model's JAX dispatch sites (device_put, compiled
    executable calls). No-op unless the sanitizer is on; raises
    :class:`DispatchUnderLock` when a no-dispatch lock is held."""
    if not _ENABLED:
        return
    for held in _STATE.held():
        if held.no_dispatch:
            rep = _stall_report(
                f"JAX {what}",
                f"JAX {what} while holding no-dispatch lock "
                f"{held.name!r}: device work (or a compile) under this "
                f"lock stalls every contending thread")
            _STATE.violations.append(rep)
            raise DispatchUnderLock(rep)


def violations() -> List:
    """StallReports recorded so far (cycles + held-too-long +
    dispatch-under-lock)."""
    with _STATE.lock:
        return list(_STATE.violations)


def lock_graph() -> Dict[str, Set[str]]:
    with _STATE.lock:
        return {k: set(v) for k, v in _STATE.graph.items()}


def reset() -> None:
    """Clear the graph + violations (test isolation)."""
    with _STATE.lock:
        _STATE.graph.clear()
        _STATE.edge_site.clear()
        _STATE.violations.clear()
