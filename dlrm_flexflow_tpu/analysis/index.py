"""Package AST index: modules, classes, locks, threads, and a call graph.

flexcheck's passes need cross-file context — which attribute is a lock,
which class owns it, which function a call resolves to — so one indexing
walk builds that here and the rule passes stay small. Resolution is
deliberately conservative: an attribute or method name resolves across
classes only when it is UNIQUE in the scanned package; anything
ambiguous resolves to nothing rather than to a guess (a false deadlock
report would teach people to ignore the analyzer).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def dotted(node: ast.AST) -> str:
    """'a.b.c' for nested Attribute/Name chains; '' when not a chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_lock_factory(call: ast.AST) -> Optional[str]:
    """'lock'/'rlock'/'condition' when `call` constructs one, else None.
    Recognizes threading.Lock()/RLock()/Condition(), bare Lock() from
    `from threading import Lock`, and the sanitizer's make_lock(...)."""
    if not isinstance(call, ast.Call):
        return None
    d = dotted(call.func)
    leaf = d.rsplit(".", 1)[-1]
    if leaf in LOCK_FACTORIES and (d == leaf or d.startswith("threading.")):
        return leaf.lower()
    if leaf == "make_lock":
        return "lock"
    return None


@dataclass
class LockDef:
    lock_id: str          # "ClassName.attr" or "module.attr"
    kind: str             # lock | rlock | condition
    file: str
    line: int


@dataclass
class ThreadSite:
    file: str
    line: int
    scope: str            # "Class.method" or function name
    cls: Optional[str]    # enclosing class name
    func: Optional[ast.FunctionDef]
    call: ast.Call
    stored_attr: Optional[str] = None   # self.<attr> it is assigned to
    stored_local: Optional[str] = None  # local var it is assigned to


@dataclass
class FuncInfo:
    qualname: str         # "file.py:Class.method" or "file.py:func"
    file: str
    cls: Optional[str]
    name: str
    node: ast.FunctionDef


@dataclass
class PackageIndex:
    root: str
    modules: Dict[str, ast.Module] = field(default_factory=dict)
    classes: Dict[str, Tuple[str, ast.ClassDef]] = field(
        default_factory=dict)           # class name -> (file, node)
    # (class, attr) -> LockDef, plus property aliases resolving to the
    # same LockDef (model._host_lock -> FFModel._host_table_lock)
    class_locks: Dict[Tuple[str, str], LockDef] = field(
        default_factory=dict)
    module_locks: Dict[Tuple[str, str], LockDef] = field(
        default_factory=dict)           # (file, name) -> LockDef
    # attr name -> [LockDef] across all classes (for unique resolution)
    lock_attr_index: Dict[str, List[LockDef]] = field(default_factory=dict)
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    # method name -> [FuncInfo] (for unique cross-class resolution)
    method_index: Dict[str, List[FuncInfo]] = field(default_factory=dict)
    threads: List[ThreadSite] = field(default_factory=list)
    thread_subclasses: Set[str] = field(default_factory=set)
    # Thread subclasses owning a join() somewhere (self-joining workers)
    self_joining: Set[str] = field(default_factory=set)

    # --- lock resolution ----------------------------------------------
    def register_lock(self, cls: Optional[str], attr: str, kind: str,
                      file: str, line: int,
                      alias_of: Optional[LockDef] = None) -> None:
        if alias_of is not None:
            ld = alias_of
        elif cls is None:
            ld = self.module_locks.setdefault(
                (file, attr), LockDef(f"{file}.{attr}", kind, file, line))
        else:
            ld = self.class_locks.setdefault(
                (cls, attr), LockDef(f"{cls}.{attr}", kind, file, line))
        if cls is not None:
            self.class_locks.setdefault((cls, attr), ld)
        self.lock_attr_index.setdefault(attr, [])
        if ld not in self.lock_attr_index[attr]:
            self.lock_attr_index[attr].append(ld)

    def lock_for_attr(self, cls: Optional[str], attr: str
                      ) -> Optional[LockDef]:
        """Resolve `<obj>.<attr>` to a lock: exact class match first,
        then unique-across-package attr name."""
        if cls is not None and (cls, attr) in self.class_locks:
            return self.class_locks[(cls, attr)]
        cands = self.lock_attr_index.get(attr, [])
        if len(cands) == 1:
            return cands[0]
        return None

    def resolve_method(self, name: str, cls: Optional[str]
                       ) -> Optional[FuncInfo]:
        """`self.name()` resolves within cls; `obj.name()` resolves only
        when the method name is unique across the package."""
        if cls is not None:
            fi = self.funcs.get(f"{cls}.{name}")
            if fi is not None:
                return fi
        cands = [f for f in self.method_index.get(name, [])
                 if f.cls is not None]
        if len(cands) == 1:
            return cands[0]
        return None

    def resolve_call(self, call: ast.Call, cls: Optional[str],
                     file: str) -> Optional[FuncInfo]:
        f = call.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                return self.resolve_method(f.attr, cls)
            return self.resolve_method(f.attr, None)
        if isinstance(f, ast.Name):
            fi = self.funcs.get(f"{file}:{f.id}")
            if fi is not None:
                return fi
            cands = [x for x in self.method_index.get(f.id, [])
                     if x.cls is None]
            if len(cands) == 1:
                return cands[0]
        return None


def iter_py_files(root: str) -> List[str]:
    out = []
    if os.path.isfile(root):
        return [root]
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def _scan_lock_assigns(idx: PackageIndex, file: str,
                       cls: Optional[str], fn: ast.AST) -> None:
    """Register `self.x = Lock()` / `x = Lock()` (incl. chained
    `a = self.b = Lock()`) found anywhere under `fn`."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        kind = _is_lock_factory(node.value)
        if kind is None:
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self" and cls is not None):
                idx.register_lock(cls, tgt.attr, kind, file, node.lineno)
            elif isinstance(tgt, ast.Name) and cls is None:
                idx.register_lock(None, tgt.id, kind, file, node.lineno)


def _scan_lock_properties(idx: PackageIndex, file: str, cls: str,
                          fn: ast.FunctionDef) -> None:
    """A @property that creates-or-returns a lock attr aliases the
    property name to that lock (FFModel._host_lock pattern)."""
    is_prop = any(isinstance(d, ast.Name) and d.id == "property"
                  for d in fn.decorator_list)
    if not is_prop:
        return
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            kind = _is_lock_factory(node.value)
            if kind is None:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    idx.register_lock(cls, tgt.attr, kind, file,
                                      node.lineno)
                    ld = idx.class_locks[(cls, tgt.attr)]
                    idx.register_lock(cls, fn.name, kind, file,
                                      fn.lineno, alias_of=ld)


def _thread_bases(node: ast.ClassDef) -> bool:
    for b in node.bases:
        d = dotted(b)
        if d in ("threading.Thread", "Thread"):
            return True
    return False


def _scan_threads(idx: PackageIndex, file: str, cls: Optional[str],
                  scope: str, fn: Optional[ast.FunctionDef],
                  body_owner: ast.AST) -> None:
    for node in ast.walk(body_owner):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and node is not body_owner:
            continue   # nested scopes scanned with their own scope name
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        is_thread = d in ("threading.Thread", "Thread")
        is_super_init = (d == "super.__init__" or (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "__init__"
            and isinstance(node.func.value, ast.Call)
            and dotted(node.func.value.func) == "super"))
        if not is_thread and not (is_super_init and cls is not None
                                  and cls in idx.thread_subclasses):
            continue
        site = ThreadSite(file=file, line=node.lineno, scope=scope,
                          cls=cls, func=fn, call=node)
        if is_super_init:
            site.stored_attr = "<self>"   # the instance IS the thread
        idx.threads.append(site)


def build_index(root: str) -> PackageIndex:
    root_abs = os.path.abspath(root)
    # a single-file root (fixture snippets, `flexcheck some_file.py`)
    # keys its module by basename
    base = os.path.dirname(root_abs) if os.path.isfile(root_abs) \
        else root_abs
    idx = PackageIndex(root=base)
    files = iter_py_files(root_abs)
    trees: Dict[str, ast.Module] = {}
    for path in files:
        rel = os.path.relpath(path, base)
        try:
            with open(path, encoding="utf-8") as f:
                trees[rel] = ast.parse(f.read(), filename=rel)
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    idx.modules = trees

    # pass 1: classes, Thread subclasses, functions
    for rel, tree in trees.items():
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                idx.classes[node.name] = (rel, node)
                if _thread_bases(node):
                    idx.thread_subclasses.add(node.name)
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        fi = FuncInfo(f"{node.name}.{item.name}", rel,
                                      node.name, item.name, item)
                        idx.funcs[fi.qualname] = fi
                        idx.method_index.setdefault(item.name,
                                                    []).append(fi)
            elif isinstance(node, ast.FunctionDef):
                fi = FuncInfo(f"{rel}:{node.name}", rel, None,
                              node.name, node)
                idx.funcs[fi.qualname] = fi
                idx.method_index.setdefault(node.name, []).append(fi)

    # Thread subclasses that join themselves (a close()/stop() calling
    # self.join) count as self-managing workers
    for cname in idx.thread_subclasses:
        _, cnode = idx.classes[cname]
        for node in ast.walk(cnode):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                idx.self_joining.add(cname)

    # pass 2: locks + thread construction sites
    for rel, tree in trees.items():
        _scan_lock_assigns(idx, rel, None, tree)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        _scan_lock_assigns(idx, rel, node.name, item)
                        _scan_lock_properties(idx, rel, node.name, item)
            elif isinstance(node, ast.FunctionDef):
                _scan_lock_assigns(idx, rel, None, node)

    # pass 3: thread sites (needs thread_subclasses from pass 1), with
    # nested defs scanned under their own scope names
    def scan_scope(rel: str, cls: Optional[str], scope: str,
                   fn: Optional[ast.FunctionDef], owner: ast.AST) -> None:
        _scan_threads(idx, rel, cls, scope, fn, owner)
        for child in ast.iter_child_nodes(owner):
            if isinstance(child, ast.FunctionDef) and child is not owner:
                scan_scope(rel, cls, f"{scope}.{child.name}", child, child)
            elif not isinstance(child, (ast.ClassDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(child):
                    if isinstance(sub, ast.FunctionDef):
                        scan_scope(rel, cls, f"{scope}.{sub.name}",
                                   sub, sub)

    for rel, tree in trees.items():
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        scan_scope(rel, node.name,
                                   f"{node.name}.{item.name}", item, item)
            elif isinstance(node, ast.FunctionDef):
                scan_scope(rel, None, node.name, node, node)

    # attach storage info to thread sites (self.attr = Thread(...) or
    # t = Thread(...); optionally self.attr = t later in the same func)
    for site in idx.threads:
        if site.func is None or site.stored_attr:
            continue
        for node in ast.walk(site.func):
            if isinstance(node, ast.Assign) and node.value is site.call:
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        site.stored_attr = tgt.attr
                    elif isinstance(tgt, ast.Name):
                        site.stored_local = tgt.id
        if site.stored_local and not site.stored_attr:
            for node in ast.walk(site.func):
                if isinstance(node, ast.Assign):
                    v = node.value
                    if (isinstance(v, ast.Name)
                            and v.id == site.stored_local):
                        for tgt in node.targets:
                            if (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                site.stored_attr = tgt.attr
    return idx
