"""Suppression baseline: known findings carried with a justification.

The baseline is the analyzer's escape hatch for findings that are
understood and deliberate (an abandoned-by-design probe thread, file IO
that IS the critical section of a manifest lock). Every entry MUST carry
a non-empty justification — an unjustified suppression is itself an
error, so the file cannot silently rot into a mute button.

Keys are line-number free (see ``findings.Finding.key``): a suppression
survives unrelated edits but dies with the symbol it names, so a fixed
finding leaves a stale entry behind that ``--prune-baseline`` removes.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from .findings import Finding

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


class BaselineError(ValueError):
    """Malformed baseline file (bad JSON, missing justification)."""


def load_baseline(path: str) -> Dict[str, str]:
    """key -> justification. Missing file = empty baseline."""
    if not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise BaselineError(f"unreadable baseline {path!r}: {e}") from None
    out: Dict[str, str] = {}
    for i, entry in enumerate(doc.get("suppressions", [])):
        key = entry.get("key")
        just = (entry.get("justification") or "").strip()
        if not key:
            raise BaselineError(
                f"{path}: suppression #{i} has no 'key'")
        if not just:
            raise BaselineError(
                f"{path}: suppression {key!r} has no justification — "
                f"every baselined finding must say WHY it is acceptable")
        out[key] = just
    return out


def save_baseline(path: str, entries: Dict[str, str]) -> None:
    doc = {"version": 1,
           "suppressions": [{"key": k, "justification": v}
                            for k, v in sorted(entries.items())]}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def split_by_baseline(findings: List[Finding], baseline: Dict[str, str]
                      ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(fresh, suppressed, stale-keys): stale keys are baseline entries
    matching nothing — fixed findings whose suppression should go."""
    fresh, suppressed = [], []
    seen = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            seen.add(f.key)
        else:
            fresh.append(f)
    stale = [k for k in baseline if k not in seen]
    return fresh, suppressed, stale
