"""flexcheck rule passes over the package AST index.

Four families (see ``findings.RULES``): thread lifecycle (FLX1xx), lock
discipline (FLX2xx), JAX hazards (FLX3xx), env parsing (FLX4xx). Every
pass takes the shared :class:`~.index.PackageIndex` and appends
:class:`~.findings.Finding`\\ s; none of them imports jax — the analyzer
must run in a bare CI venv.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding, make_finding
from .index import FuncInfo, PackageIndex, dotted

# locks whose critical sections must never block: the serving dispatch
# path, checkpoint manifests, host-table gathers, deployment swaps
CRITICAL_LOCK_RE = re.compile(r"swap|dispatch|manifest|deploy|host")

# calls considered blocking inside a critical section
BLOCKING_ATTRS = {"block_until_ready", "result", "join", "sleep",
                  "fsync", "replace", "unlink", "listdir", "device_put",
                  "load", "save", "savez", "dump"}
BLOCKING_DOTTED = {"time.sleep", "jax.device_put", "np.load", "numpy.load",
                   "json.load", "json.dump", "os.fsync", "os.replace",
                   "os.unlink", "os.listdir", "subprocess.run",
                   "subprocess.check_call", "shutil.copy",
                   "jax.block_until_ready"}
BLOCKING_NAMES = {"open", "read_with_retries", "device_put"}

# module-level jax calls that force backend init / device work on import
IMPORT_TIME_JAX = {"jax.device_put", "jax.devices", "jax.local_devices",
                   "jax.block_until_ready"}


# ---------------------------------------------------------------------
# shared walking helpers
# ---------------------------------------------------------------------
def _with_lock_ids(item: ast.withitem, idx: PackageIndex,
                   cls: Optional[str], file: str,
                   local_types: Dict[str, str]) -> Optional[str]:
    """Lock id a `with X:` item acquires, or None when X is no known
    lock. X may be self.attr, obj.attr, a bare name, or a local alias."""
    expr = item.context_expr
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            owner = cls if base.id == "self" else local_types.get(base.id)
            ld = idx.lock_for_attr(owner, expr.attr)
            return ld.lock_id if ld else None
    elif isinstance(expr, ast.Name):
        ld = idx.module_locks.get((file, expr.id))
        if ld is not None:
            return ld.lock_id
        # local alias: `lk = self._lock` style — resolved by the caller
        # seeding local_types with "<lockid>" markers
        alias = local_types.get("#lock:" + expr.id)
        return alias
    return None


def _local_info(fn: ast.FunctionDef, idx: PackageIndex,
                cls: Optional[str]) -> Dict[str, str]:
    """Best-effort local var typing: `x = ClassName(...)` and lock
    aliases `lk = self._lock` → "#lock:lk" marker entries."""
    types: Dict[str, str] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        v = node.value
        if isinstance(v, ast.Call):
            d = dotted(v.func)
            leaf = d.rsplit(".", 1)[-1]
            if leaf in idx.classes:
                types[tgt.id] = leaf
        elif isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name):
            owner = cls if v.value.id == "self" else types.get(v.value.id)
            ld = idx.lock_for_attr(owner, v.attr)
            if ld is not None:
                types["#lock:" + tgt.id] = ld.lock_id
    return types


def _first_name_literal(node: ast.AST) -> Optional[str]:
    """Leading literal text of a name expression (handles f-strings)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            return first.value
    return None


# ---------------------------------------------------------------------
# FLX101/102/103 — thread lifecycle
# ---------------------------------------------------------------------
def check_threads(idx: PackageIndex, findings: List[Finding]) -> None:
    for site in idx.threads:
        kw = {k.arg: k.value for k in site.call.keywords if k.arg}
        # name: required, and any literal prefix must be "ff-"
        name = kw.get("name")
        if name is None and site.stored_attr == "<self>":
            # Thread subclass __init__ may take the name positionally
            name = next(iter(site.call.args), None)
        tok = site.stored_attr or site.stored_local or "thread"
        if name is None:
            findings.append(make_finding(
                "FLX101", site.file, site.line,
                "thread created without name=: stall reports and stack "
                "dumps cannot identify this worker (name it 'ff-...')",
                scope=site.scope, token=tok))
        else:
            lit = _first_name_literal(name)
            if lit is not None and not lit.startswith("ff-"):
                findings.append(make_finding(
                    "FLX101", site.file, site.line,
                    f"thread name {lit!r} does not follow the 'ff-*' "
                    f"convention the watchdog troubleshooting table "
                    f"keys on", scope=site.scope, token=tok))
        daemon = kw.get("daemon")
        if not (isinstance(daemon, ast.Constant) and daemon.value is True):
            findings.append(make_finding(
                "FLX102", site.file, site.line,
                "thread not daemon=True: a wedged worker would block "
                "interpreter shutdown (watchdogs abandon daemons safely)",
                scope=site.scope, token=tok))
        _check_join(idx, site, findings, tok)


def _is_self_attr(node: ast.AST, attr: str) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self" and node.attr == attr)


def _joins_attr(tree: ast.AST, attr: str) -> bool:
    """True when the tree joins (or delegates close/stop to) self.attr,
    directly or via a local alias `t = self.attr` / getattr(self, 'attr')
    / a snapshot copy `ts = list(self.attr)` / a loop variable
    `for t in self.attr: t.join()`."""
    aliases = {None}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            src = None
            if _is_self_attr(v, attr):
                src = True
            elif (isinstance(v, ast.Call) and dotted(v.func) == "getattr"
                  and len(v.args) >= 2
                  and isinstance(v.args[0], ast.Name)
                  and v.args[0].id == "self"
                  and isinstance(v.args[1], ast.Constant)
                  and v.args[1].value == attr):
                src = True
            elif (isinstance(v, ast.Call)
                  and dotted(v.func) in ("list", "tuple", "sorted")
                  and len(v.args) == 1
                  and _is_self_attr(v.args[0], attr)):
                # snapshot copy taken under a lock before the joins
                src = True
            if src:
                aliases.add(node.targets[0].id)
    for node in ast.walk(tree):
        # loop variables over the attr (or an alias of it) inherit it:
        # `for t in self._threads: t.join()`
        if (isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and (_is_self_attr(node.iter, attr)
                     or (isinstance(node.iter, ast.Name)
                         and node.iter.id in aliases))):
            aliases.add(node.target.id)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in ("join", "close", "stop", "shutdown",
                                  "wait"):
            continue
        v = node.func.value
        if (isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name)
                and v.value.id == "self" and v.attr == attr):
            return True
        if isinstance(v, ast.Name) and v.id in aliases:
            return True
    return False


def _check_join(idx: PackageIndex, site, findings: List[Finding],
                tok: str) -> None:
    if site.stored_attr == "<self>":
        if site.cls in idx.self_joining:
            return
        findings.append(make_finding(
            "FLX103", site.file, site.line,
            f"Thread subclass {site.cls} never joins itself (no "
            f"close()/stop() calling self.join) — leaked worker",
            scope=site.scope, token=tok))
        return
    if site.stored_attr and site.cls:
        _, cnode = idx.classes[site.cls]
        if _joins_attr(cnode, site.stored_attr):
            return
        findings.append(make_finding(
            "FLX103", site.file, site.line,
            f"thread stored on self.{site.stored_attr} is never joined "
            f"on any close()/shutdown() path of {site.cls}",
            scope=site.scope, token=tok))
        return
    # purely local thread: must be joined (or handed to a self-joining
    # owner) inside the same function
    fn = site.func
    if fn is None:
        findings.append(make_finding(
            "FLX103", site.file, site.line,
            "module-level thread is never joined", scope=site.scope,
            token=tok))
        return
    var = site.stored_local
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and isinstance(node.func.value, ast.Name)
                and (var is None or node.func.value.id == var)):
            return
    if var is not None and site.cls:
        # handed to a self-owned registry (`self._threads.append(t)`)
        # whose members a close path joins — the per-connection worker
        # pattern
        _, cnode = idx.classes[site.cls]
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and isinstance(node.func.value, ast.Attribute)
                    and isinstance(node.func.value.value, ast.Name)
                    and node.func.value.value.id == "self"
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id == var
                    and _joins_attr(cnode, node.func.value.attr)):
                return
    findings.append(make_finding(
        "FLX103", site.file, site.line,
        f"local thread {var or '<anonymous>'} is never joined in "
        f"{site.scope} — the worker outlives the call that spawned it",
        scope=site.scope, token=tok))


# ---------------------------------------------------------------------
# FLX105 — sockets/listeners stored on self must close on a close path
# ---------------------------------------------------------------------
SOCKET_CREATORS = {"socket.socket", "socket.create_server",
                   "socket.create_connection"}


def check_sockets(idx: PackageIndex, findings: List[Finding]) -> None:
    """FLX105: ``self.X = socket.create_server(...)`` (or ``.socket()``/
    ``.create_connection()``) in a class with no close()/shutdown()/
    ``__exit__`` path that closes ``self.X``. A leaked client socket is
    one fd per connection; a leaked LISTENER keeps the port bound until
    interpreter exit — the next server boot gets EADDRINUSE."""
    for cls, (rel, cnode) in idx.classes.items():
        for node in ast.walk(cnode):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            v = node.value
            if not (isinstance(v, ast.Call)
                    and dotted(v.func) in SOCKET_CREATORS):
                continue
            if _joins_attr(cnode, tgt.attr):
                continue
            kind = ("listener"
                    if dotted(v.func) == "socket.create_server"
                    else "socket")
            findings.append(make_finding(
                "FLX105", rel, node.lineno,
                f"{kind} stored on self.{tgt.attr} is never closed on "
                f"any close()/shutdown()/__exit__ path of {cls} — "
                f"leaked fd"
                + (", and the bound port stays taken (EADDRINUSE on "
                   "the next boot)" if kind == "listener" else ""),
                scope=cls, token=tgt.attr))


# ---------------------------------------------------------------------
# FLX104 — policy-loop threads must be stop-signalled before the join
# ---------------------------------------------------------------------
def _loop_target_name(call: ast.Call) -> Optional[str]:
    """The thread's target method name when it looks like a long-lived
    policy/health loop (``target=self._policy_loop`` — the ``*_loop``
    naming every such worker in this package follows)."""
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    target = kw.get("target")
    if isinstance(target, ast.Attribute) and target.attr.endswith("_loop"):
        return target.attr
    if isinstance(target, ast.Name) and target.id.endswith("_loop"):
        return target.id
    return None


def _sets_event_before_join(cnode: ast.ClassDef, attr: str) -> bool:
    """True when some method of the class that joins self.<attr> (or an
    alias, or delegates via close/stop) also calls ``<something>.set()``
    — the stop-Event signal that lets a waiting loop exit immediately
    instead of sleeping out its interval (or never exiting at all)."""
    for node in ast.walk(cnode):
        if not isinstance(node, ast.FunctionDef):
            continue
        if not _joins_attr(node, attr):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "set"):
                return True
    return False


def check_policy_loops(idx: PackageIndex,
                       findings: List[Finding]) -> None:
    """FLX104: a thread whose target is a ``*_loop`` method (the
    autoscaler's policy loop, the router's health loop, pollers) runs
    ``while not stop.wait(interval)``-shaped bodies. Joining such a
    thread WITHOUT setting its stop event first blocks close() for a
    full sleep interval at best and forever at worst — every close path
    that joins the loop thread must ``.set()`` a stop Event. Reuses the
    FLX101-103 thread index; fires only on threads stored on self (a
    local loop thread is FLX103's business)."""
    for site in idx.threads:
        loop = _loop_target_name(site.call)
        if loop is None or not site.stored_attr or not site.cls:
            continue
        _, cnode = idx.classes[site.cls]
        if not _joins_attr(cnode, site.stored_attr):
            continue   # unjoined is FLX103's finding, not a double
        if _sets_event_before_join(cnode, site.stored_attr):
            continue
        findings.append(make_finding(
            "FLX104", site.file, site.line,
            f"policy thread {loop}() (self.{site.stored_attr}) is "
            f"joined on close without a stop Event .set(): the join "
            f"waits out the loop's full sleep interval, or hangs on a "
            f"loop that never checks a flag",
            scope=site.scope, token=site.stored_attr))


# ---------------------------------------------------------------------
# FLX109 — unbounded latency/size sample lists
# ---------------------------------------------------------------------
# attribute names that smell like a measurement window: latency/size
# samples a long-lived server appends per request/step. Deliberately
# narrow — a work queue or a pending-install list is someone's bounded-
# by-protocol state, not a sample window.
SAMPLE_ATTR_RE = re.compile(
    r"(^|_)(lat|lats|latency|latencies|sample|samples|ms|bytes|sizes|"
    r"times|durations|p99|p50)($|_)")

# constructors that ARE the bound: the obs reservoir and any
# deque(maxlen=...)-shaped ring
_BOUNDED_CTORS = {"Reservoir", "latency_reservoir"}


def _self_attr_of(node: ast.AST) -> Optional[str]:
    """``self.X`` -> "X" (None for anything else)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def check_sample_lists(idx: PackageIndex,
                       findings: List[Finding]) -> None:
    """FLX109: ``self.X.append(sample)`` where X smells like a
    latency/size window and NOTHING in the class bounds it — no
    ``deque(maxlen=...)``/``Reservoir`` construction, no ``del
    self.X[:-N]`` / ``self.X = self.X[-N:]`` rotation, no
    ``pop``/``popleft``/``clear`` drain. A serving process appending
    per-request samples to a plain list leaks until OOM; the fix is the
    bounded ``obs.metrics.Reservoir`` every stats() window now uses."""
    for file, tree in idx.modules.items():
        for cnode in ast.walk(tree):
            if not isinstance(cnode, ast.ClassDef):
                continue
            appends: Dict[str, int] = {}
            bounded: Set[str] = set()
            for node in ast.walk(cnode):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    attr = _self_attr_of(node.func.value)
                    if attr is None:
                        continue
                    if node.func.attr == "append":
                        appends.setdefault(attr, node.lineno)
                    elif node.func.attr in ("pop", "popleft", "clear"):
                        bounded.add(attr)
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        attr = _self_attr_of(tgt)
                        if attr is None:
                            continue
                        v = node.value
                        if isinstance(v, ast.Call):
                            leaf = dotted(v.func).rsplit(".", 1)[-1]
                            if leaf in _BOUNDED_CTORS:
                                bounded.add(attr)
                            elif leaf == "deque" and any(
                                    k.arg == "maxlen"
                                    for k in v.keywords):
                                bounded.add(attr)
                        elif (isinstance(v, ast.Subscript)
                              and _self_attr_of(v.value) == attr
                              and isinstance(v.slice, ast.Slice)):
                            bounded.add(attr)   # self.X = self.X[-N:]
                elif isinstance(node, ast.Delete):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Subscript)
                                and isinstance(tgt.slice, ast.Slice)):
                            attr = _self_attr_of(tgt.value)
                            if attr is not None:
                                bounded.add(attr)   # del self.X[:-N]
            for attr, line in sorted(appends.items()):
                if not SAMPLE_ATTR_RE.search(attr.lower()):
                    continue
                if attr in bounded:
                    continue
                findings.append(make_finding(
                    "FLX109", file, line,
                    f"self.{attr} collects samples via append() with no "
                    f"bound or rotation in {cnode.name}: a long-lived "
                    f"process grows it without limit — use obs.metrics."
                    f"Reservoir / deque(maxlen=...) or rotate with "
                    f"del self.{attr}[:-N]",
                    scope=cnode.name, token=attr))


# ---------------------------------------------------------------------
# FLX201 — attribute written both inside and outside lock scopes
# ---------------------------------------------------------------------
_INIT_METHODS = {"__init__", "__post_init__", "__new__"}


def check_racy_attributes(idx: PackageIndex,
                          findings: List[Finding]) -> None:
    for cname, (file, cnode) in idx.classes.items():
        locked: Dict[str, int] = {}
        unlocked: Dict[str, Tuple[int, str]] = {}

        def visit(node: ast.AST, held: bool, meth: str) -> None:
            if isinstance(node, ast.With):
                acquires = any(
                    _with_lock_ids(item, idx, cname, file, {})
                    for item in node.items)
                for child in node.body:
                    visit(child, held or acquires, meth)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in node.body:   # worker closures: same rules
                    visit(child, False, meth)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for tgt in tgts:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        if held:
                            locked.setdefault(tgt.attr, node.lineno)
                        elif meth not in _INIT_METHODS:
                            unlocked.setdefault(tgt.attr,
                                                (node.lineno, meth))
            for child in ast.iter_child_nodes(node):
                visit(child, held, meth)

        for item in cnode.body:
            if isinstance(item, ast.FunctionDef):
                for child in item.body:
                    visit(child, False, item.name)
        for attr in sorted(set(locked) & set(unlocked)):
            line, meth = unlocked[attr]
            findings.append(make_finding(
                "FLX201", file, line,
                f"{cname}.{attr} is written under a lock (line "
                f"{locked[attr]}) but also without one in {meth}() — "
                f"racing writers can tear/lose updates",
                scope=f"{cname}.{meth}", token=attr))


# ---------------------------------------------------------------------
# FLX202/203 — lock-order graph + blocking-under-lock
# ---------------------------------------------------------------------
def _direct_blocking_calls(fn: ast.FunctionDef
                           ) -> List[Tuple[str, int]]:
    out = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        leaf = d.rsplit(".", 1)[-1]
        if (d in BLOCKING_DOTTED or d in BLOCKING_NAMES
                or (isinstance(node.func, ast.Attribute)
                    and leaf in BLOCKING_ATTRS)):
            out.append((d or leaf, node.lineno))
    return out


class LockWalker:
    """Per-function walk tracking the held-lock stack; feeds both the
    lock-order graph and the blocking-under-lock rule."""

    def __init__(self, idx: PackageIndex):
        self.idx = idx
        # lock-order edges: (lockA, lockB) -> (file, line, scope)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self.blocking: List[Finding] = []
        self._lockset_memo: Dict[str, Set[str]] = {}

    # transitive set of locks a function may acquire
    def lockset(self, fi: FuncInfo, stack: Tuple[str, ...] = ()
                ) -> Set[str]:
        if fi.qualname in self._lockset_memo:
            return self._lockset_memo[fi.qualname]
        if fi.qualname in stack:
            return set()
        out: Set[str] = set()
        locals_ = _local_info(fi.node, self.idx, fi.cls)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    lid = _with_lock_ids(item, self.idx, fi.cls, fi.file,
                                         locals_)
                    if lid:
                        out.add(lid)
            elif isinstance(node, ast.Call):
                callee = self.idx.resolve_call(node, fi.cls, fi.file)
                if callee is not None and callee.qualname != fi.qualname:
                    out |= self.lockset(callee,
                                        stack + (fi.qualname,))
        self._lockset_memo[fi.qualname] = out
        return out

    def walk_function(self, fi: FuncInfo) -> None:
        locals_ = _local_info(fi.node, self.idx, fi.cls)
        self._walk(fi, fi.node.body, (), locals_)

    def _walk(self, fi: FuncInfo, body, held: Tuple[str, ...],
              locals_: Dict[str, str]) -> None:
        for node in body:
            self._visit(fi, node, held, locals_)

    def _visit(self, fi: FuncInfo, node: ast.AST,
               held: Tuple[str, ...], locals_: Dict[str, str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def bodies run later, not under the current locks —
            # walked separately with an empty stack
            nested = FuncInfo(f"{fi.qualname}.{node.name}", fi.file,
                              fi.cls, node.name, node)
            self._walk(nested, node.body, (), locals_)
            return
        if isinstance(node, ast.With):
            acquired = []
            cond_objs = []
            for item in node.items:
                lid = _with_lock_ids(item, self.idx, fi.cls, fi.file,
                                     locals_)
                if lid:
                    acquired.append((lid, item, node.lineno))
                    cond_objs.append(dotted(item.context_expr))
            for lid, _, line in acquired:
                for h in held:
                    if h != lid:
                        self.edges.setdefault(
                            (h, lid), (fi.file, line, fi.qualname))
            new_held = held + tuple(lid for lid, _, _ in acquired)
            for child in node.body:
                self._visit(fi, child, new_held, locals_)
            return
        if isinstance(node, ast.Call):
            self._check_call(fi, node, held, locals_)
        for child in ast.iter_child_nodes(node):
            self._visit(fi, child, held, locals_)

    def _check_call(self, fi: FuncInfo, node: ast.Call,
                    held: Tuple[str, ...],
                    locals_: Dict[str, str]) -> None:
        if not held:
            return
        d = dotted(node.func)
        leaf = d.rsplit(".", 1)[-1]
        # condition self-wait releases the lock — never blocking
        if leaf == "wait":
            owner = d.rsplit(".", 1)[0] if "." in d else ""
            lid = None
            if owner:
                parts = owner.split(".")
                if parts[0] == "self" and len(parts) == 2 and fi.cls:
                    ld = self.idx.lock_for_attr(fi.cls, parts[1])
                    lid = ld.lock_id if ld else None
            if lid in held:
                return
        critical = [h for h in held
                    if CRITICAL_LOCK_RE.search(h.rsplit(".", 1)[-1])]
        if not critical:
            # still propagate edges through callees for the order graph
            callee = self.idx.resolve_call(node, fi.cls, fi.file)
            if callee is not None:
                for m in self.lockset(callee):
                    for h in held:
                        if h != m:
                            self.edges.setdefault(
                                (h, m), (fi.file, node.lineno,
                                         fi.qualname))
            return
        blocking = (d in BLOCKING_DOTTED or d in BLOCKING_NAMES
                    or (isinstance(node.func, ast.Attribute)
                        and leaf in BLOCKING_ATTRS))
        if blocking:
            self.blocking.append(make_finding(
                "FLX203", fi.file, node.lineno,
                f"{d or leaf}() while holding {', '.join(critical)} — "
                f"blocks every thread contending for the lock",
                scope=fi.qualname, token=f"{critical[-1]}:{d or leaf}"))
            return
        callee = self.idx.resolve_call(node, fi.cls, fi.file)
        if callee is not None:
            for what, line in _direct_blocking_calls(callee.node):
                self.blocking.append(make_finding(
                    "FLX203", fi.file, node.lineno,
                    f"call to {callee.qualname}() runs {what}() while "
                    f"holding {', '.join(critical)}",
                    scope=fi.qualname,
                    token=f"{critical[-1]}:{callee.name}.{what}"))
                break   # one finding per call site, not per io op
            for m in self.lockset(callee):
                for h in held:
                    if h != m:
                        self.edges.setdefault(
                            (h, m), (fi.file, node.lineno, fi.qualname))


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int, str]]
                 ) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: List[List[str]] = []
    seen_keys: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            visited: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                rot = min(range(len(path)),
                          key=lambda i: path[i])
                canon = tuple(path[rot:] + path[:rot])
                if canon not in seen_keys:
                    seen_keys.add(canon)
                    cycles.append(list(canon))
            elif nxt not in visited and nxt > start:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    # also catch 2-cycles A<->B (path len 2 handled above via len>1)
    for a, b in edges:
        if (b, a) in edges and (min(a, b), max(a, b)) not in seen_keys:
            seen_keys.add((min(a, b), max(a, b)))
            cycles.append([min(a, b), max(a, b)])
    return cycles


def check_locks(idx: PackageIndex, findings: List[Finding]) -> None:
    walker = LockWalker(idx)
    for fi in list(idx.funcs.values()):
        walker.walk_function(fi)
    findings.extend(walker.blocking)
    for cyc in _find_cycles(walker.edges):
        sites = []
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            site = walker.edges.get((a, b))
            if site:
                sites.append(f"{a}->{b} at {site[0]}:{site[1]}")
        file, line, scope = next(
            (walker.edges[(a, b)] for i, a in enumerate(cyc)
             for b in [cyc[(i + 1) % len(cyc)]]
             if (a, b) in walker.edges), ("<package>", 0, ""))
        findings.append(make_finding(
            "FLX202", file, line,
            "lock-order cycle (deadlock hazard): "
            + " ; ".join(sites), scope=scope,
            token="|".join(cyc)))


# ---------------------------------------------------------------------
# FLX204 — manifest/delta files written without temp + os.replace
# ---------------------------------------------------------------------
_MANIFEST_PATH_RE = re.compile(r"manifest|delta", re.IGNORECASE)
_TEMP_PATH_RE = re.compile(r"\btmp\b|\.tmp|temp", re.IGNORECASE)
_WRITE_MODES = {"w", "wt", "wb", "w+", "wb+", "w+b"}


def check_manifest_atomicity(idx: PackageIndex,
                             findings: List[Finding]) -> None:
    """Chain manifests and delta snapshots are the crash-consistency
    spine of the continual train->serve loop: a bare ``open(path, "w")``
    on one of them publishes a torn file to any concurrent reader when
    the writer dies mid-write. Every such write must go through a temp
    file in the same directory + ``os.replace`` (the checkpoint
    module's ``_write_manifest``/``_write_npz_atomic`` discipline —
    their ``open(tmp, ...)`` is exactly the sanctioned pattern and is
    not flagged)."""
    for rel, tree in idx.modules.items():
        for fn in [n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef)]:
            for node in ast.walk(fn):
                if (not isinstance(node, ast.Call)
                        or dotted(node.func) != "open"
                        or not node.args):
                    continue
                mode = None
                if len(node.args) >= 2 and isinstance(node.args[1],
                                                      ast.Constant):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value,
                                                       ast.Constant):
                        mode = kw.value.value
                if not isinstance(mode, str) \
                        or mode not in _WRITE_MODES:
                    continue
                try:
                    target = ast.unparse(node.args[0])
                except Exception:   # pragma: no cover - unparse safety
                    continue
                if not _MANIFEST_PATH_RE.search(target):
                    continue
                if _TEMP_PATH_RE.search(target):
                    continue   # the sanctioned temp-file half
                findings.append(make_finding(
                    "FLX204", rel, node.lineno,
                    f"open({target}, {mode!r}) writes a manifest/delta "
                    f"path in place: a crash mid-write publishes a torn "
                    f"file to concurrent readers — write a .tmp-<pid> "
                    f"sibling and os.replace() it",
                    scope=fn.name, token=target[:40]))


# ---------------------------------------------------------------------
# FLX301/302/303/304 — JAX hazards
# ---------------------------------------------------------------------
def check_jax_hazards(idx: PackageIndex,
                      findings: List[Finding]) -> None:
    for rel, tree in idx.modules.items():
        _check_import_time_jax(rel, tree, findings)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                _check_exec_cache_key(rel, node, findings)
        _check_scan_rules(idx, rel, tree, findings)


def _check_import_time_jax(rel: str, tree: ast.Module,
                           findings: List[Finding]) -> None:
    def scan(body, scope):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.ClassDef):
                scan(stmt.body, f"{scope or ''}{stmt.name}")
                continue
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    break
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if (d.startswith("jnp.") or d.startswith("jax.numpy.")
                        or d in IMPORT_TIME_JAX):
                    findings.append(make_finding(
                        "FLX302", rel, node.lineno,
                        f"{d}() runs at import time: forces JAX backend "
                        f"init + device dispatch before main() configures "
                        f"anything", scope=scope or "<module>", token=d))

    scan(tree.body, "")


def _check_exec_cache_key(rel: str, node: ast.Assign,
                          findings: List[Finding]) -> None:
    v = node.value
    compiled = (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "compile")
    if not compiled:
        return
    for tgt in node.targets:
        if not isinstance(tgt, ast.Subscript):
            continue
        base = dotted(tgt.value).rsplit(".", 1)[-1]
        if not re.search(r"exec|cache", base, re.I):
            continue
        if isinstance(tgt.slice, ast.Constant):
            findings.append(make_finding(
                "FLX301", rel, node.lineno,
                f"compiled executable stored in {base!r} under constant "
                f"key {tgt.slice.value!r}: different batch shapes would "
                f"silently reuse one executable — key on the shape "
                f"signature", scope="", token=base))


def _scan_call_bodies(tree: ast.Module) -> List[Tuple[ast.FunctionDef,
                                                      ast.Call]]:
    """(body_fn, scan_call) for lax.scan/fori/while calls whose body is
    a locally-defined function."""
    defs: Dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs[node.name] = node
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        if not d.endswith(("lax.scan", "lax.fori_loop", "lax.while_loop")):
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            body = defs.get(node.args[0].id)
            if body is not None:
                out.append((body, node))
    return out


def _check_scan_rules(idx: PackageIndex, rel: str, tree: ast.Module,
                      findings: List[Finding]) -> None:
    # FLX304: Python branches on traced params inside scan bodies
    for body, call in _scan_call_bodies(tree):
        params = {a.arg for a in body.args.args}
        for node in ast.walk(body):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            names = {n.id for n in ast.walk(node.test)
                     if isinstance(n, ast.Name)}
            traced = names & params
            if traced:
                findings.append(make_finding(
                    "FLX304", rel, node.lineno,
                    f"Python {'if' if isinstance(node, ast.If) else 'while'}"
                    f" on traced value(s) {sorted(traced)} inside scan "
                    f"body {body.name}(): raises at trace time or "
                    f"silently bakes one branch in",
                    scope=body.name, token=",".join(sorted(traced))))
    # FLX303: train-shaped functions containing lax.scan must be jitted
    # with donated carries
    scan_owners: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and any(
                isinstance(c, ast.Call)
                and dotted(c.func).endswith("lax.scan")
                for c in ast.walk(node)):
            if re.search(r"train|superstep|step", node.name):
                scan_owners.add(node.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if dotted(node.func) not in ("jax.jit", "jit"):
            continue
        if not (node.args and isinstance(node.args[0], ast.Name)
                and node.args[0].id in scan_owners):
            continue
        scan_owners.discard(node.args[0].id)   # jitted: check kwargs
        kws = {k.arg for k in node.keywords}
        if "donate_argnums" not in kws and "donate_argnames" not in kws:
            findings.append(make_finding(
                "FLX303", rel, node.lineno,
                f"jax.jit({node.args[0].id}) fuses a lax.scan train body "
                f"without donate_argnums: the scanned carries "
                f"double-buffer params+opt state every superstep",
                scope="", token=node.args[0].id))


# ---------------------------------------------------------------------
# FLX401 — unchecked env parsing
# ---------------------------------------------------------------------
def _env_sourced_names(fn: ast.FunctionDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if _is_env_expr(node.value):
                out.add(node.targets[0].id)
    return out


def _is_env_expr(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        d = dotted(sub) if isinstance(sub, (ast.Attribute, ast.Name)) \
            else ""
        if d.startswith("os.environ") or d == "os.getenv":
            return True
        if isinstance(sub, ast.Call) and dotted(sub.func) in (
                "os.environ.get", "os.getenv"):
            return True
    return False


def _guarded_by_valueerror(node: ast.AST,
                           parents: Dict[ast.AST, ast.AST]) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Try):
            for h in cur.handlers:
                names = []
                t = h.type
                if isinstance(t, ast.Tuple):
                    names = [dotted(e) for e in t.elts]
                elif t is not None:
                    names = [dotted(t)]
                if any(n in ("ValueError", "Exception", "TypeError")
                       for n in names):
                    return True
        cur = parents.get(cur)
    return False


def check_env_parsing(idx: PackageIndex,
                      findings: List[Finding]) -> None:
    for rel, tree in idx.modules.items():
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for fn in [n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef)]:
            if "env" in fn.name and fn.name.startswith("_env"):
                continue   # the sanctioned parse helpers
            env_vars = _env_sourced_names(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if dotted(node.func) not in ("int", "float"):
                    continue
                if not node.args:
                    continue
                arg = node.args[0]
                from_env = _is_env_expr(arg) or any(
                    isinstance(n, ast.Name) and n.id in env_vars
                    for n in ast.walk(arg))
                if not from_env:
                    continue
                if _guarded_by_valueerror(node, parents):
                    continue
                findings.append(make_finding(
                    "FLX401", rel, node.lineno,
                    f"{dotted(node.func)}() on an os.environ value in "
                    f"{fn.name}() without a ValueError guard: a typo'd "
                    f"env var becomes an unhandled crash (or silent "
                    f"mis-parse) with no variable name in the error",
                    scope=fn.name, token=ast.unparse(arg)[:40]))


ALL_PASSES = (check_threads, check_policy_loops, check_sockets,
              check_sample_lists, check_racy_attributes, check_locks,
              check_manifest_atomicity, check_jax_hazards,
              check_env_parsing)
