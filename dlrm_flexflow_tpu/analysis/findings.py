"""Finding model shared by every flexcheck pass.

A finding is one `file:line rule-id severity message` diagnostic. Its
``key`` deliberately excludes the line number: suppression baselines must
survive unrelated edits above the finding, so the key is built from the
rule, the file, and the enclosing scope/symbol instead.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

# severity ladder (``--fail-on`` compares by index)
SEVERITIES = ("info", "low", "medium", "high")


def severity_at_least(sev: str, floor: str) -> bool:
    return SEVERITIES.index(sev) >= SEVERITIES.index(floor)


# rule-id registry: id -> (name, default severity, one-line doc). The
# README's reference table and the CLI's --list-rules are generated from
# this, so the code and the docs cannot drift apart.
RULES = {
    # --- thread lifecycle ---------------------------------------------
    "FLX101": ("thread-unnamed", "high",
               "threading.Thread without a name= starting with 'ff-' "
               "(stall reports and stack dumps must name the worker)"),
    "FLX102": ("thread-not-daemon", "high",
               "threading.Thread without daemon=True (a wedged worker "
               "must never block interpreter shutdown)"),
    "FLX103": ("thread-unjoined", "high",
               "thread is never joined/drained on any close()/shutdown() "
               "path (leaked worker; racy teardown)"),
    "FLX104": ("policy-loop-no-stop-signal", "high",
               "a *_loop policy/health thread (autoscaler, router "
               "health, watcher) is joined without a stop Event being "
               "set on any close path — the join waits out a full "
               "sleep interval, or forever on a non-waiting loop"),
    "FLX105": ("socket-not-closed", "high",
               "a socket/listener created and stored on self is never "
               "closed on any close()/shutdown()/__exit__ path of the "
               "class — a leaked fd per connection, and a bound "
               "listener port that never frees"),
    "FLX109": ("unbounded-sample-list", "medium",
               "latency/size samples appended to a self.* list with no "
               "bound or rotation anywhere in the class: a long-lived "
               "server grows it forever — use a bounded window "
               "(obs.metrics.Reservoir / deque(maxlen=...)) or rotate "
               "(del x[:-N])"),
    # --- lock discipline ----------------------------------------------
    "FLX201": ("racy-attribute", "medium",
               "attribute written both inside and outside `with <lock>` "
               "scopes of the same class (torn read/lost update race)"),
    "FLX202": ("lock-order-cycle", "high",
               "cycle in the static lock-order graph (deadlock hazard: "
               "two threads can acquire the cycle in opposite order)"),
    "FLX203": ("blocking-under-lock", "high",
               "blocking call (device_put/block_until_ready/file IO/"
               "sleep/.result()/.join()) while holding a dispatch/"
               "manifest/host-table lock"),
    "FLX204": ("manifest-write-not-atomic", "high",
               "manifest/delta file opened for writing directly (bare "
               "open(path, 'w')): a crash mid-write publishes a torn "
               "file — write a temp file and os.replace() it"),
    # --- JAX hazards ---------------------------------------------------
    "FLX301": ("exec-cache-const-key", "high",
               "compiled-executable cache stored under a constant key "
               "(must key on the batch/shape signature)"),
    "FLX302": ("import-time-jax", "high",
               "jnp./jax dispatch at module import time (forces backend "
               "init + device work on import)"),
    "FLX303": ("scan-no-donate", "medium",
               "lax.scan train body jitted without donate_argnums "
               "(carries double-buffer; superstep memory doubles)"),
    "FLX304": ("traced-python-branch", "medium",
               "Python if/while on a traced value inside a scan/jit body "
               "(TracerBoolConversionError or silent retrace)"),
    # --- env parsing ---------------------------------------------------
    "FLX401": ("env-parse-unchecked", "medium",
               "int()/float() directly on an os.environ value without a "
               "ValueError guard naming the variable"),
    # --- SPMD plan verification (analysis/shardcheck.py) ----------------
    "FLX501": ("implicit-reshard", "medium",
               "producer/consumer sharding degrees disagree: GSPMD "
               "legally inserts a resharding collective at this op "
               "boundary (high when the moved tensor is table-scale)"),
    "FLX502": ("replicated-table-update", "high",
               "table-scale parameter replicated under data-parallel "
               "updates: every step moves a table-scale gradient "
               "collective (the bench_shard-measured 66x vs row-shard)"),
    "FLX503": ("hbm-over-cap", "high",
               "per-device residency (params + optimizer state + live "
               "activations) exceeds the HBM capacity cap (--hbm-gb)"),
    "FLX504": ("param-degree-misuse", "high",
               "strategy requests param_degree row sharding the op "
               "cannot execute (no configure_row_shard support, "
               "non-factorizing degree, rows/batch indivisible) — "
               "compile() silently falls back to replicated rows"),
    "FLX505": ("elastic-clamp-hazard", "medium",
               "plan cannot project onto the survivor mesh: "
               "clamp_strategies would shed row shards into replication "
               "or exceed the survivor's HBM"),
    "FLX506": ("plan-cache-mesh-mismatch", "high",
               "a cached MCMC plan's recorded mesh signature does not "
               "match the topology it would be served for (or its "
               "degrees cannot assign on that mesh) — a warm-start hit "
               "on the wrong topology is a silent correctness hazard"),
    "FLX507": ("serving-plan-overreplicated", "high",
               "a SERVING deployment replicates table-scale params "
               "across ranker replicas (or its shard row-ranges fail "
               "to tile a table exactly): the fleet pays tables x "
               "replicas of memory — or a gap/overlap serves wrong "
               "rows — where a row-sharded lookup tier stores each "
               "table once"),
    "FLX508": ("quant-policy-mismatch", "high",
               "a strategy file's quantized-storage policy (quant_dtype"
               "/quant_update) disagrees with the policy a checkpoint "
               "manifest records its snapshots under — serving int8 "
               "rows through an fp32-planned deployment (or vice "
               "versa) mis-prices every byte term 4x and breaks the "
               "payload codec at the first delta apply"),
    "FLX509": ("lookup-rtt-budget-infeasible", "high",
               "the per-seam wire RTT budget cannot meet the serve "
               "SLO: a ranker's shard-fanout lookup is as slow as its "
               "slowest shard, and a request that survives the "
               "configured transient retries pays RTT x (1+retries) "
               "plus exponential backoff SERIALLY — when that floor "
               "spends the --serve-slo-ms budget before ranker compute "
               "even starts, the topology cannot make SLO at any load"),
    # --- lowered-HLO audit (analysis/hlo_audit.py) ----------------------
    "FLX511": ("hlo-table-collective", "high",
               "lowered HLO moves a table-scale buffer through an "
               "all-gather/all-reduce/reduce-scatter (an implicit "
               "reshard or replicated-table gradient sync)"),
    "FLX512": ("hlo-missed-donation", "medium",
               "large entry parameter is not input-output aliased "
               "(missed donation: the buffer double-allocates)"),
    "FLX513": ("hlo-collective-drift", "medium",
               "measured collective bytes in the lowered HLO drift "
               "beyond tolerance from the cost model's prediction "
               "(the search is pricing a different program)"),
    "FLX514": ("serialized-exchange", "medium",
               "a row-shard exchange whose transfer time exceeds the "
               "step's exposed-compute window runs with overlap off: "
               "the collective blocks the compute stream end-to-end "
               "where the pipelined exchange would hide under it "
               "(high when the exchange dwarfs the window)"),
    "FLX515": ("interaction-materialized", "medium",
               "the lowered HLO materializes the (B, F, F) pairwise-dot "
               "interaction tensor in HBM (unfused gather→bmm→tril "
               "chain where the fused Pallas kernel keeps it in VMEM)"),
    "FLX516": ("retrieval-index-overreplicated", "medium",
               "a retrieval MIPS index is replicated per ranker instead "
               "of riding the sharded embedding tier: every ranker pays "
               "the full codes+scales residency (high when the combined "
               "ranker + index bytes exceed the --hbm-gb budget — the "
               "cascade cannot boot) where the sharded index stores "
               "each row once and answers local top-k in place"),
}


@dataclass(frozen=True)
class Finding:
    rule: str          # "FLX203"
    severity: str      # info|low|medium|high
    file: str          # path relative to the scanned root
    line: int
    message: str
    scope: str = ""    # "Class.method", "function", or "<module>"
    token: str = ""    # stable discriminator (lock/thread/attr name)

    @property
    def name(self) -> str:
        return RULES[self.rule][0]

    @property
    def key(self) -> str:
        """Line-number-free suppression key."""
        return f"{self.rule}:{self.file}:{self.scope}:{self.token}"

    def render(self) -> str:
        return (f"{self.file}:{self.line} {self.rule} {self.severity} "
                f"[{self.name}] {self.message}")


def make_finding(rule: str, file: str, line: int, message: str,
                 scope: str = "", token: str = "",
                 severity: str = "") -> Finding:
    return Finding(rule=rule, severity=severity or RULES[rule][1],
                   file=file, line=line, message=message, scope=scope,
                   token=token)


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings,
                  key=lambda f: (-SEVERITIES.index(f.severity), f.file,
                                 f.line, f.rule))
