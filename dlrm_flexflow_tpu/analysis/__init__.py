"""flexcheck: concurrency + JAX-hazard analysis for dlrm_flexflow_tpu.

Two halves:

- **Static passes** (``python -m dlrm_flexflow_tpu.analysis`` or the
  ``flexcheck`` console script): AST + call-graph rules over the package
  — thread lifecycle, lock discipline (races, lock-order cycles,
  blocking under dispatch/manifest locks), JAX hazards (import-time
  dispatch, executable-cache keys, scan donation, traced branches) and
  env-parsing hygiene. Findings print as ``file:line rule-id severity``
  and gate CI via ``--fail-on high`` against the checked-in
  ``analysis/baseline.json`` suppression file (every entry justified).
- **Runtime sanitizer** (:mod:`.sanitizer`, opt-in via ``FF_SANITIZE=1``):
  named-lock proxies that record the live lock-acquisition graph,
  detect order cycles and held-too-long locks, and assert no JAX
  dispatch happens under a no-dispatch lock — reporting through the
  watchdog's :class:`~..utils.watchdog.StallReport` machinery.

This ``__init__`` stays import-light: the production modules import
:func:`make_lock` from :mod:`.sanitizer` on their hot paths, and pulling
the AST passes (or argparse) in with it would tax every ``import
dlrm_flexflow_tpu``.
"""

from __future__ import annotations

__all__ = ["run_analysis", "main", "sanitizer"]

from . import sanitizer  # noqa: E402  (import-light; hot-path dep)


def run_analysis(root=None):
    from .cli import run_analysis as _run
    return _run(root)


def main(argv=None) -> int:
    from .cli import main as _main
    return _main(argv)
