"""flexcheck CLI: run the static passes, apply the baseline, exit coded.

Usage::

    python -m dlrm_flexflow_tpu.analysis [PATH ...] \
        [--fail-on {high,medium,low,info,never}] [--baseline FILE]
        [--show-baselined] [--write-baseline] [--prune-baseline]
        [--list-rules]

Findings print as ``file:line RULE severity [name] message``. Exit code
1 when any non-baselined finding at or above ``--fail-on`` remains
(default: high), 2 on usage/baseline errors.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .baseline import (DEFAULT_BASELINE, BaselineError, load_baseline,
                       save_baseline, split_by_baseline)
from .findings import RULES, Finding, severity_at_least, sort_findings
from .index import build_index
from .rules import ALL_PASSES

_PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_analysis(root: Optional[str] = None) -> List[Finding]:
    """All findings (baseline NOT applied) for a file or package tree.
    Defaults to the installed ``dlrm_flexflow_tpu`` package itself."""
    idx = build_index(root or _PACKAGE_ROOT)
    findings: List[Finding] = []
    for p in ALL_PASSES:
        p(idx, findings)
    return sort_findings(findings)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="flexcheck",
        description="Concurrency + JAX-hazard static analyzer for "
                    "dlrm_flexflow_tpu")
    ap.add_argument("paths", nargs="*", default=[],
                    help=f"files/trees to scan (default: the installed "
                         f"package at {_PACKAGE_ROOT})")
    ap.add_argument("--fail-on", default="high",
                    choices=["high", "medium", "low", "info", "never"],
                    help="exit 1 when a non-baselined finding at or "
                         "above this severity remains (default: high)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression file (default: the package's "
                         "checked-in analysis/baseline.json)")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print suppressed findings with their "
                         "justifications")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding into the baseline "
                         "(justifications default to TODO — fill them "
                         "in, an empty justification fails the load)")
    ap.add_argument("--prune-baseline", action="store_true",
                    help="drop baseline entries that no longer match "
                         "any finding")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule-id reference table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (name, sev, doc) in sorted(RULES.items()):
            print(f"{rid}  {name:<24} {sev:<7} {doc}")
        return 0

    findings: List[Finding] = []
    for path in (args.paths or [_PACKAGE_ROOT]):
        findings.extend(run_analysis(path))
    findings = sort_findings(findings)

    try:
        baseline = load_baseline(args.baseline)
    except BaselineError as e:
        print(f"flexcheck: {e}", file=sys.stderr)
        return 2
    fresh, suppressed, stale = split_by_baseline(findings, baseline)

    if args.write_baseline:
        entries = dict(baseline) if not args.prune_baseline else {
            k: v for k, v in baseline.items()
            if k in {f.key for f in findings}}
        for f in fresh:
            entries.setdefault(f.key, "TODO: justify or fix")
        save_baseline(args.baseline, entries)
        print(f"flexcheck: wrote {len(entries)} suppression(s) to "
              f"{args.baseline}")
        return 0
    if args.prune_baseline and stale:
        save_baseline(args.baseline,
                      {k: v for k, v in baseline.items()
                       if k not in set(stale)})
        print(f"flexcheck: pruned {len(stale)} stale suppression(s)")

    for f in fresh:
        print(f.render())
    if args.show_baselined:
        for f in suppressed:
            print(f"{f.render()}  [baselined: {baseline[f.key]}]")
    for k in stale:
        print(f"flexcheck: stale baseline entry (fixed? prune it): {k}",
              file=sys.stderr)

    n_gate = [f for f in fresh
              if args.fail_on != "never"
              and severity_at_least(f.severity, args.fail_on)]
    print(f"flexcheck: {len(fresh)} finding(s) "
          f"({len(n_gate)} at/above --fail-on {args.fail_on}), "
          f"{len(suppressed)} baselined, {len(stale)} stale "
          f"suppression(s)")
    return 1 if n_gate else 0
