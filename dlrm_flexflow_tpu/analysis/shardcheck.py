"""shardcheck: static SPMD plan verifier (the FLX5xx rules).

flexcheck (PR 7) gave locks and threads static verification; this module
gives the same treatment to SOAP strategy plans — the paper's thesis is
that the plan IS the performance contract, and the worst failure mode of
the pod-scale strategy space (PR 8) is *silent*: GSPMD legally inserts a
full-table all-gather or resharding copy when producer/consumer
shardings disagree, and the run is merely 66x slower (the exact gap
bench_shard.py measured between replicated and row-sharded plans) or
OOMs at scale instead of erroring.

The verifier abstractly interprets a strategy map against a factorized
mesh — propagating (shape, per-dim degrees, mesh-axis assignment, bytes)
through the op graph with the SAME algorithms compile() uses
(`parallel.sharding.assign_indices`, `Simulator._clamp_strategies`) — so
what it flags is what GSPMD will do, not a parallel reimplementation's
guess. No jax Mesh (and no devices) are needed: a 64-device terabyte
plan verifies from a laptop.

Rules (registered in findings.RULES; suppressible via the shared
baseline machinery):

- FLX501 implicit-reshard: producer/consumer degree mismatch at an op
  boundary — GSPMD inserts a resharding collective there. High severity
  when the moved tensor is table-scale.
- FLX502 replicated-table-update: a table-scale parameter replicated
  under data-parallel outputs — every step pays a table-scale gradient
  collective (GSPMD gathers the update set per replica).
- FLX503 hbm-over-cap: per-device residency over the ``--hbm-gb`` cap
  (the accounting is `search.simulator.hbm_footprint_report`, shared
  with the MCMC search's feasibility check).
- FLX504 param-degree-misuse: the plan requests row sharding the op
  cannot execute; compile() would degrade to replicated rows with only
  a log warning (`ops.embedding.row_shard_structural_reason` is the
  shared rule set).
- FLX505 elastic-clamp-hazard: `search.replan.clamp_report` projects
  the plan onto a survivor mesh and the projection sheds row shards
  into replication (or cannot fit).
- FLX506 plan-cache-mesh-mismatch: an entry in the persistent plan
  cache (``utils/warmcache.PlanCache`` — what elastic
  ``recover()``/``expand()`` warm-start from) records a device count or
  axis factorization that disagrees with its own key, or carries
  degrees that cannot assign on the recorded mesh. The runtime cache
  rejects such entries too; the static audit (``--plan-cache DIR``)
  finds them before a recovery is on the clock.
- FLX508 quant-policy-mismatch: a strategy file's quantized-storage
  policy (``quant_dtype``/``quant_update``, quant/) disagrees with the
  policy a checkpoint manifest records its snapshots under
  (``--manifest DIR`` / :func:`verify_quant_policies`) — byte terms
  mis-priced ~4x, quantized payloads undecodable against the plan.
- FLX507 serving-plan-overreplicated: the SERVING deployment audited
  the same way (``--serving-replicas N [--serving-shards M]`` /
  :func:`verify_serving_plan`) — table-scale params replicated across
  ranker replicas where a row-sharded lookup tier
  (``serve/shardtier.py``) would store each table once, and shard
  row-ranges that fail to tile a table exactly (gap/overlap/short —
  the owner math itself, ``parallel.alltoall.shard_row_ranges``, can
  never produce this; a hand-edited plan can).
- FLX509 lookup-rtt-budget-infeasible: with ``--serve-slo-ms`` set, the
  per-seam wire RTT budget is audited — a ranker's shard fanout is as
  slow as its slowest shard, and a request surviving the configured
  transient retries pays ``rtt x (1 + retries)`` plus exponential
  backoff serially (``--serving-rtt-ms``, defaulting to the
  transport's measured floor); a floor past the SLO means no load
  level can make it.
- FLX516 retrieval-index-overreplicated: a retrieval MIPS index
  (``retrieve/index.py``) replicated per ranker instead of riding the
  sharded embedding tier (``--retrieve-index-rows N
  [--retrieve-index-dim D --retrieve-index-quant DT
  --retrieve-index-sharded]``) — every ranker pays the full
  codes+scales residency; high severity when the combined ranker +
  index bytes break the ``--hbm-gb`` budget.

The lowered-HLO half of the PR lives in :mod:`.hlo_audit` (FLX51x).
"""

from __future__ import annotations

import argparse
import math
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding, make_finding, severity_at_least, sort_findings

# plan-finding suppressions live in their own baseline file (same
# machinery as flexcheck's analysis/baseline.json, separate namespace:
# plan keys are keyed by strategy FILE, and flexcheck's stale-entry
# nagging must not see them as dead AST suppressions)
DEFAULT_PLAN_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "shardcheck_baseline.json")

# a collective/reshard moving at least this many bytes per step is worth
# a medium finding even when no table gives a relative scale
RESHARD_WARN_BYTES = 1 << 20
# absolute floor for "table-scale": tables below this never make a
# collective high-severity (tiny test models reshard kilobytes legally)
TABLE_SCALE_MIN_BYTES = 1 << 20
# fraction of the largest table that counts as "table-scale" traffic
TABLE_SCALE_FRAC = 0.25


def table_scale_threshold(model,
                          table_scale_bytes: Optional[float] = None
                          ) -> Optional[float]:
    """Bytes above which a moved buffer counts as table-scale: a quarter
    of the model's largest embedding table (fp32), floored at 1 MiB.
    None when the model has no tables and no explicit threshold —
    table-scale rules stay silent then."""
    if table_scale_bytes is not None:
        return float(table_scale_bytes)
    tables = [op.param_bytes() for op in model.ops
              if hasattr(op, "host_lookup") and op.param_defs()]
    if not tables:
        return None
    return max(float(TABLE_SCALE_MIN_BYTES),
               TABLE_SCALE_FRAC * max(tables))


def default_topology(model, ndev: int
                     ) -> List[Tuple[str, int]]:
    """[(kind, size), ...] for the target mesh: the compiled mesh's axis
    names when one is attached and matches (axes named dcn* ride DCN),
    else the structural factorization make_mesh would build — the same
    fallback the simulator uses, so both price the same axes."""
    mesh = getattr(model, "mesh", None)
    if mesh is not None and mesh.size == ndev:
        return [("dcn" if str(a).startswith("dcn") else "ici",
                 int(mesh.shape[a])) for a in mesh.axis_names]
    from ..parallel.mesh import structural_axis_sizes
    return [("ici", s) for s in structural_axis_sizes(ndev)]


def resolve_plan(model, strategies, ndev: int):
    """Expand a loaded strategy map onto the model's ops exactly like
    compile() does: reference-style generic keys (embedding{i}/linear/
    concat/mse_loss) resolve onto real ops, everything unnamed gets its
    default data-parallel config. Mutates ``model.strategies`` (verifier
    models are throwaway graph builds)."""
    from ..core.op import InputOp
    model.strategies = dict(strategies or {})
    model._resolve_generic_strategy_keys(ndev)
    resolved = dict(model.strategies)
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        resolved.setdefault(op.name, op.default_parallel_config(ndev))
    return resolved


def _fmt_bytes(b: float) -> str:
    if b >= 1e9:
        return f"{b / 1e9:.2f} GB"
    if b >= 1e6:
        return f"{b / 1e6:.1f} MB"
    return f"{b / 1e3:.0f} KB"


def verify_plan(model, strategies, ndev: Optional[int] = None,
                topology: Optional[Sequence[Tuple[str, int]]] = None,
                *, hbm_bytes: Optional[float] = None,
                survivor_ndev: Optional[int] = None,
                table_scale_bytes: Optional[float] = None,
                path: str = "<plan>",
                resolve: bool = True) -> List[Finding]:
    """Statically verify `strategies` for `model` on an `ndev` mesh.

    Returns findings (baseline NOT applied); the caller gates them like
    any other flexcheck pass. `model` needs only the built graph —
    compile() must NOT have been called for verification to be honest
    about what a fresh compile of this plan would do (a compiled model's
    mesh is still consulted for axis kinds when it matches ndev).
    """
    from ..core.op import InputOp
    from ..search.cost_model import CostModel
    from ..search.simulator import Simulator, hbm_footprint_report
    from ..parallel.sharding import assign_indices

    if ndev is None:
        mesh = getattr(model, "mesh", None)
        ndev = int(mesh.size) if mesh is not None else 1
    topo = list(topology) if topology is not None else \
        default_topology(model, ndev)
    axis_sizes = [s for _, s in topo]
    cost = CostModel(compute_dtype=model.config.jnp_compute_dtype)
    resolved = resolve_plan(model, strategies, ndev) if resolve \
        else dict(strategies)
    sim = Simulator(model, cost, topology=topo)
    eff = sim._clamp_strategies(resolved, ndev)
    tscale = table_scale_threshold(model, table_scale_bytes)
    findings: List[Finding] = []
    host_res = set(getattr(model, "_host_resident_ops", set()) or set())
    for name, pc in resolved.items():
        if pc.device_type == "CPU" or "ZCM" in pc.memory_types:
            host_res.add(name)

    ops = [op for op in model.ops if not isinstance(op, InputOp)]
    by_name = {op.name: op for op in model.ops}

    def _assign(degrees):
        return assign_indices(list(degrees), axis_sizes)

    # --- FLX501: implicit reshard boundaries ---------------------------
    for op in ops:
        if getattr(op, "raw_degree_semantics", False) \
                or op.name in host_res:
            continue
        dst = eff.get(op.name)
        if dst is None:
            continue
        da = _assign(dst.degrees)
        for t in op.inputs:
            src_op = t.owner_op
            if src_op is None or isinstance(src_op, InputOp):
                continue
            if getattr(src_op, "raw_degree_semantics", False) \
                    or src_op.name in host_res:
                continue
            src = eff.get(src_op.name)
            if src is None:
                continue
            sa = _assign(src.degrees)
            if sa is None or da is None:
                continue
            nd = max(len(sa), len(da))
            sa_p = list(sa) + [()] * (nd - len(sa))
            da_p = list(da) + [()] * (nd - len(da))
            involved = set()
            for s, d in zip(sa_p, da_p):
                involved |= set(s) ^ set(d)
            if not involved:
                continue
            parts = max(src.num_parts, dst.num_parts, 1)
            moved = cost.tensor_bytes(t) * (1.0 - 1.0 / parts)
            if moved <= 0:
                continue
            kinds = sorted({topo[i][0] for i in involved})
            sev = "info"
            if moved >= RESHARD_WARN_BYTES:
                sev = "medium"
            if tscale is not None and moved >= tscale:
                sev = "high"
            findings.append(make_finding(
                "FLX501", path, 0,
                f"implicit reshard between {src_op.name!r} "
                f"(degrees {src.degrees}) and {op.name!r} "
                f"(degrees {dst.degrees}): GSPMD moves "
                f"~{_fmt_bytes(moved)} of {t.name!r} over "
                f"{'/'.join(kinds)} every step",
                scope=op.name, token=f"{src_op.name}->{op.name}",
                severity=sev))

    # --- FLX502: replicated table under data-parallel updates ----------
    for op in ops:
        if not (hasattr(op, "host_lookup") and op.param_defs()):
            continue
        if op.name in host_res:
            continue
        pc = eff.get(op.name)
        if pc is None:
            continue
        pd = max(getattr(pc, "param_degree", 1), 1)
        replicas = pc.degrees[0] if pc.degrees else 1
        if pd > 1 or replicas <= 1:
            continue
        full = float(op.param_bytes())
        defs = op.param_defs()
        import numpy as _np
        shard = sum(
            math.prod(s)
            * float(_np.dtype(defs[p].dtype).itemsize if p in defs else 4)
            for p, s in op.param_shard_shapes(pc, ndev).items())
        if shard < full:          # table/width sharding holds real shards
            continue
        if tscale is None or full < tscale:
            continue
        findings.append(make_finding(
            "FLX502", path, 0,
            f"{op.name!r} replicates a {_fmt_bytes(full)} table across "
            f"{replicas} data-parallel replicas: every step moves a "
            f"table-scale gradient collective (bench_shard measured "
            f"66x vs row sharding) — set param_degree or shard the "
            f"table dim", scope=op.name, token="replicated-table"))

    # --- FLX503: per-device HBM footprint over the cap -----------------
    if hbm_bytes is not None:
        report = hbm_footprint_report(model, cost, eff, ndev)
        total = sum(report.values())
        if total > 0.9 * float(hbm_bytes):
            top = sorted(report.items(), key=lambda kv: -kv[1])[:3]
            tops = ", ".join(f"{k}={_fmt_bytes(v)}" for k, v in top)
            findings.append(make_finding(
                "FLX503", path, 0,
                f"per-device residency {_fmt_bytes(total)} exceeds 90% "
                f"of the {_fmt_bytes(float(hbm_bytes))} HBM cap on the "
                f"{ndev}-device mesh (largest: {tops})",
                scope="<plan>", token=f"hbm-{ndev}dev"))

    # --- FLX504: param_degree the op cannot execute --------------------
    from ..ops.embedding import row_shard_structural_reason
    for name, pc in resolved.items():
        pd = getattr(pc, "param_degree", 1)
        if pd <= 1:
            continue
        op = by_name.get(name)
        if op is None:
            continue
        if name in host_res:
            reason = "host-resident/offloaded tables cannot row-shard " \
                     "in HBM"
        else:
            reason = row_shard_structural_reason(op, pc, axis_sizes)
        if reason is None:
            continue
        findings.append(make_finding(
            "FLX504", path, 0,
            f"{name!r} requests param_degree={pd} row sharding but "
            f"{reason}; compile() silently replicates the table (a "
            f">HBM table then OOMs, a smaller one trains 66x slower)",
            scope=name, token=f"pd{pd}"))

    # --- FLX514: serialized exchange a pipelined plan would hide -------
    # A row-shard exchange with overlap off blocks the compute stream
    # end-to-end (the fused all_to_all occupies every participating
    # device). When the step's exposed-compute window — every other
    # op's fwd+bwd work, which has no data dependence on THIS op's
    # exchange — is at least exchange-sized, overlap=True would hide it
    # (cost_model.exposed_exchange_time); running serial then leaves the
    # whole transfer on the critical path.
    from ..parallel.sharding import param_axis_indices
    import jax.numpy as jnp
    itemsize = jnp.dtype(cost.compute_dtype).itemsize
    compute_of = {}
    for op in ops:
        opc = eff.get(op.name)
        if opc is None or op.name in host_res:
            continue
        compute_of[op.name] = (
            cost.op_compute_time(op, opc, backward=False)
            + cost.op_compute_time(op, opc, backward=True))
    window_all = sum(compute_of.values())
    for name, pc in resolved.items():
        pd = max(getattr(pc, "param_degree", 1), 1)
        if pd <= 1 or getattr(pc, "overlap", False) or name in host_res:
            continue
        op = by_name.get(name)
        if op is None or not hasattr(op, "alltoall_payload_bytes"):
            continue
        if row_shard_structural_reason(op, pc, axis_sizes) is not None:
            continue            # FLX504 already owns the broken case
        pidx = param_axis_indices(pd, axis_sizes)
        axes = [topo[i] for i in (pidx or ())]
        if not axes:
            continue
        req_b, rows_b, grad_b = op.alltoall_payload_bytes(
            ndev, itemsize, pc=pc)
        exch = sum(cost.alltoall_time_axes(b, axes)
                   for b in (req_b, rows_b, grad_b))
        window = window_all - compute_of.get(name, 0.0)
        if exch <= 0 or exch <= window:
            continue
        kinds = "/".join(sorted({k for k, _ in axes}))
        findings.append(make_finding(
            "FLX514", path, 0,
            f"{name!r} exchanges {_fmt_bytes(req_b + rows_b + grad_b)}"
            f"/device/step over {kinds} serially: transfer "
            f"~{exch * 1e3:.2f} ms exceeds the {window * 1e3:.2f} ms "
            f"exposed-compute window, so the collective blocks the "
            f"compute stream end-to-end — set overlap=True to pipeline "
            f"the exchange under the dense MLPs",
            scope=name, token="serialized-exchange",
            severity="high" if exch >= 2.0 * max(window, 1e-12)
            else "medium"))

    # --- FLX505: elastic clamp hazards ---------------------------------
    if survivor_ndev is not None and survivor_ndev >= 1 \
            and survivor_ndev < ndev:
        from ..search.replan import clamp_report
        for op_name, reason, fatal in clamp_report(
                model, resolved, survivor_ndev, hbm_bytes=hbm_bytes):
            findings.append(make_finding(
                "FLX505", path, 0,
                f"elastic projection onto {survivor_ndev} survivor "
                f"device(s): {op_name!r} {reason}",
                scope=op_name, token=f"surv{survivor_ndev}",
                severity="high" if fatal else "medium"))

    return sort_findings(findings)


# --------------------------------------------------------------------------
# FLX507: serving-plan audit (the read path gets the training treatment)
# --------------------------------------------------------------------------
def verify_serving_plan(model, replicas: int,
                        serving_plan: Optional[Dict] = None,
                        *, ranker_holds_tables: Optional[bool] = None,
                        hbm_bytes: Optional[float] = None,
                        table_scale_bytes: Optional[float] = None,
                        serve_slo_ms: Optional[float] = None,
                        serving_rtt_ms: Optional[float] = None,
                        lookup_retries: int = 2,
                        backoff_ms: float = 5.0,
                        retrieve_index: Optional[Dict] = None,
                        path: str = "<serving>") -> List[Finding]:
    """Audit a SERVING deployment the way :func:`verify_plan` audits a
    training plan — statically, no devices needed.

    ``serving_plan`` is ``EmbeddingShardSet.serving_plan()`` (or the
    same dict hand-built for a planned deployment): shard count and the
    per-op flat-row ``ranges``. Two hazards are flagged under FLX507:

    - **over-replication** — table-scale params resident per RANKER.
      With a shard set configured that means the rankers never released
      their tables (the split bought nothing); without one it is the
      pre-split fleet paying tables x replicas (the ROADMAP-1 ceiling:
      a DLRM-Terabyte model cannot board at all). ``hbm_bytes`` turns
      the finding into a hard infeasibility when the per-ranker
      residency exceeds the budget.
    - **bad tiling** — shard row-ranges that gap, overlap, or fall
      short of a table's flat row space. The owner math
      (``parallel.alltoall.shard_row_ranges``) can never produce this;
      a hand-edited or version-skewed plan can, and a gap serves
      default rows for ids nobody owns while an overlap double-serves
      (and double-publishes) rows.

    ``retrieve_index`` (or the ``retrieve_index`` entry a cascade's
    ``serving_plan()`` reports) describes a retrieval MIPS index —
    ``{"rows": ..., "dim": ..., "quant": ..., "sharded": ...}``. An
    index NOT riding the sharded tier replicates its codes+scales into
    every ranker and is flagged under **FLX516** (high when the combined
    per-ranker residency breaks the ``hbm_bytes`` budget).

    With ``serve_slo_ms`` set a third hazard is flagged under
    **FLX509** — an RTT budget the topology cannot meet. ``serving_rtt_ms``
    is the per-hop wire RTT floor on the lookup seam (when omitted, the
    transport's measured p50 floor is used if this process has sent
    wire traffic); a ranker's shard fanout is as slow as its slowest
    shard, and a request that survives ``lookup_retries`` transient
    failures pays ``rtt x (1 + retries)`` plus the exponential
    ``backoff_ms`` chain serially. When that floor spends the SLO
    before ranker compute even starts, no load level makes SLO.
    """
    from ..serve.shardtier import serving_footprint
    findings: List[Finding] = []
    tscale = table_scale_threshold(model, table_scale_bytes)
    nshards = int(serving_plan.get("nshards", 0)) if serving_plan else 0
    if serving_plan and ranker_holds_tables is None:
        ranker_holds_tables = serving_plan.get("ranker_holds_tables")

    # --- tiling: ranges must cover each table exactly ------------------
    for op_name, ranges in ((serving_plan or {}).get("ranges")
                            or {}).items():
        total = ((serving_plan or {}).get("flat_rows")
                 or {}).get(op_name)
        cur = 0
        for slot, (lo, hi) in enumerate(
                sorted((tuple(r) for r in ranges), key=lambda r: r[0])):
            if lo > cur:
                findings.append(make_finding(
                    "FLX507", path, 0,
                    f"shard ranges for {op_name!r} leave a GAP: rows "
                    f"[{cur}, {lo}) belong to no shard — lookups there "
                    f"can only ever degrade to default rows",
                    scope=op_name, token=f"gap-{cur}"))
            elif lo < cur:
                findings.append(make_finding(
                    "FLX507", path, 0,
                    f"shard ranges for {op_name!r} OVERLAP: rows "
                    f"[{lo}, {cur}) have two owners — double-served "
                    f"lookups and a torn version vector on publish",
                    scope=op_name, token=f"overlap-{lo}"))
            cur = max(cur, hi)
        if total is not None and cur != total:
            findings.append(make_finding(
                "FLX507", path, 0,
                f"shard ranges for {op_name!r} tile [0, {cur}) but the "
                f"table has {total} flat rows — "
                f"{'missing tail' if cur < total else 'ranges overrun'}",
                scope=op_name, token="extent"))

    # --- over-replication across rankers -------------------------------
    fp = serving_footprint(model, replicas, nshards,
                           ranker_holds_tables=ranker_holds_tables)
    table_scale = tscale is not None and fp["table_bytes"] >= tscale
    if nshards > 0 and fp["ranker_bytes"] > fp["dense_bytes"] \
            and table_scale:
        findings.append(make_finding(
            "FLX507", path, 0,
            f"a {nshards}-shard lookup tier is configured but each of "
            f"the {replicas} ranker(s) still holds "
            f"{_fmt_bytes(fp['table_bytes'])} of tables — release them "
            f"(EmbeddingShardSet.release_ranker_tables); the split "
            f"bought nothing", scope="<serving>",
            token="ranker-holds-tables"))
    elif nshards <= 0 and replicas > 1 and table_scale:
        findings.append(make_finding(
            "FLX507", path, 0,
            f"{replicas} serving replicas each hold "
            f"{_fmt_bytes(fp['table_bytes'])} of tables "
            f"({_fmt_bytes(fp['fleet_table_bytes'])} fleet-wide) — "
            f"row-shard the lookup tier (--serve-shards) so tables are "
            f"stored once, divided", scope="<serving>",
            token="replicated-serving",
            severity="high" if (hbm_bytes is not None
                               and fp["ranker_bytes"] > hbm_bytes)
            else "medium"))
    if hbm_bytes is not None and fp["ranker_bytes"] > float(hbm_bytes):
        findings.append(make_finding(
            "FLX507", path, 0,
            f"per-ranker residency {_fmt_bytes(fp['ranker_bytes'])} "
            f"exceeds the {_fmt_bytes(float(hbm_bytes))} budget — this "
            f"deployment cannot boot"
            + ("" if nshards > 0 else
               " (a sharded tier would hold "
               f"{_fmt_bytes(fp['dense_bytes'])}/ranker)"),
            scope="<serving>", token="ranker-hbm"))

    # --- FLX516: retrieval index riding (or not) the sharded tier ------
    if retrieve_index is None and serving_plan:
        retrieve_index = serving_plan.get("retrieve_index")
    if retrieve_index:
        rows = int(retrieve_index.get("rows", 0))
        dim = int(retrieve_index.get("dim", 0))
        quant = str(retrieve_index.get("quant", "int8"))
        code_bytes = {"int8": 1, "fp8": 1, "fp16": 2,
                      "fp32": 4}.get(quant, 1)
        # codes + one fp32 scale per row — what QuantTable.nbytes counts
        index_bytes = rows * dim * code_bytes + rows * 4
        if not retrieve_index.get("sharded") and rows > 0:
            over_hbm = (hbm_bytes is not None
                        and fp["ranker_bytes"] + index_bytes
                        > float(hbm_bytes))
            findings.append(make_finding(
                "FLX516", path, 0,
                f"the retrieval index ({rows} x {dim} {quant}, "
                f"{_fmt_bytes(float(index_bytes))}) is replicated into "
                f"each of the {replicas} ranker(s) — "
                f"{_fmt_bytes(float(index_bytes * max(replicas, 1)))} "
                f"fleet-wide"
                + (f"; per-ranker residency "
                   f"{_fmt_bytes(fp['ranker_bytes'] + index_bytes)} "
                   f"breaks the {_fmt_bytes(float(hbm_bytes))} budget — "
                   f"the cascade cannot boot" if over_hbm else "")
                + " — attach it to the sharded tier "
                "(ShardedMIPSIndex.build on the EmbeddingShardSet) so "
                "each row is stored once and scored in place",
                scope="<serving>", token="retrieve-index",
                severity="high" if over_hbm else "medium"))

    # --- FLX509: per-seam RTT budget vs the serve SLO ------------------
    if serve_slo_ms is not None and float(serve_slo_ms) > 0 \
            and nshards > 0:
        rtt, measured = serving_rtt_ms, False
        if rtt is None:
            try:
                from ..serve.transport import measured_rtt_floor
                rtt = measured_rtt_floor("lookup")
                measured = rtt is not None
            except ImportError:  # pragma: no cover - bare CI venv
                rtt = None
        if rtt is not None and float(rtt) > 0:
            retries = max(int(lookup_retries), 0)
            # the retry chain is SERIAL: every transient burn pays a
            # full RTT plus its slot of the exponential backoff; the
            # shard fanout is parallel but waits on its slowest member,
            # so the per-shard worst case IS the request's floor
            worst_ms = (float(rtt) * (1 + retries)
                        + float(backoff_ms) * ((1 << retries) - 1))
            src = ("transport-measured p50 floor" if measured
                   else "--serving-rtt-ms")
            if worst_ms >= float(serve_slo_ms):
                findings.append(make_finding(
                    "FLX509", path, 0,
                    f"lookup RTT budget infeasible: {nshards}-shard "
                    f"fanout at {float(rtt):.2f} ms/hop ({src}) with "
                    f"{retries} transient retr{'y' if retries == 1 else 'ies'} "
                    f"floors a surviving request at {worst_ms:.2f} ms — "
                    f"past the {float(serve_slo_ms):.0f} ms SLO before "
                    f"ranker compute starts; cut retries/backoff, move "
                    f"shards closer, or raise the SLO",
                    scope="<serving>", token="rtt-budget"))
            elif worst_ms >= 0.5 * float(serve_slo_ms):
                findings.append(make_finding(
                    "FLX509", path, 0,
                    f"lookup RTT headroom is thin: the worst surviving "
                    f"request spends {worst_ms:.2f} ms of the "
                    f"{float(serve_slo_ms):.0f} ms SLO on the wire "
                    f"({float(rtt):.2f} ms/hop {src}, {retries} "
                    f"retries) — under {0.5 * float(serve_slo_ms):.0f} "
                    f"ms is left for batching + ranker compute",
                    scope="<serving>", token="rtt-headroom",
                    severity="medium"))
    return sort_findings(findings)


# --------------------------------------------------------------------------
# CLI: verify bundled/user strategy files against their target models
# --------------------------------------------------------------------------

_FNAME_PATTERNS = [
    # bundled searched plans: dlrm_kaggle_8dev_dcn_2host_roofline.pb
    (re.compile(r"dlrm_kaggle_(\d+)dev(_dcn_(\d+)host)?"), "dlrm_kaggle"),
    (re.compile(r"dlrm_terabyte_(\d+)dev(_dcn(\d+)x\d+)?"),
     "dlrm_terabyte"),
    (re.compile(r"inception_v3_(\d+)dev(_dcn_(\d+)host)?"),
     "inception_v3"),
    # reference-style generated plans: dlrm_strategy_8embs_8gpus.pb
    (re.compile(r"dlrm_strategy_(\d+)embs?_(\d+)gpus"), "dlrm_ref"),
    (re.compile(r"dlrm_strategy_(\d+)nEmb_1cpu_1gpu"), "dlrm_ref_hetero"),
]


def infer_target(path: str
                 ) -> Optional[Tuple[str, int, Optional[int]]]:
    """(model_name, ndev, dcn_slices) from a strategy filename, or None
    when the name matches no bundled convention."""
    base = os.path.basename(path)
    for pat, name in _FNAME_PATTERNS:
        m = pat.search(base)
        if not m:
            continue
        if name == "dlrm_ref":
            return (f"dlrm_ref{m.group(1)}", int(m.group(2)), None)
        if name == "dlrm_ref_hetero":
            return (f"dlrm_ref{m.group(1)}", 2, None)
        dcn = int(m.group(3)) if len(m.groups()) >= 3 and m.group(3) \
            else None
        return (name, int(m.group(1)), dcn)
    return None


def build_target_model(name: str, ndev: int,
                       batch: Optional[int] = None):
    """Build the (uncompiled) op graph a bundled strategy file targets.
    Table sizes are the REAL workload's — byte thresholds must see the
    true scale even though no array is ever allocated."""
    from ..config import FFConfig
    from ..core.model import FFModel
    batch = batch if batch else 64 * max(ndev, 1)
    if name.startswith("dlrm"):
        from ..models.dlrm import DLRMConfig, build_dlrm
        if name == "dlrm_kaggle":
            dcfg = DLRMConfig.criteo_kaggle()
        elif name == "dlrm_terabyte":
            dcfg = DLRMConfig.terabyte()
        elif name == "dlrm_random":
            dcfg = DLRMConfig.random_benchmark()
        elif name.startswith("dlrm_ref"):
            # the reference's run_random shape generalized to N tables
            # (its generated strategies key embedding{i}/linear/concat)
            n = int(name[len("dlrm_ref"):] or 8)
            dcfg = DLRMConfig(embedding_size=[1000000] * n,
                              sparse_feature_size=64,
                              mlp_bot=[64, 512, 512, 64],
                              mlp_top=[64 * (n + 1), 1024, 1024, 1])
        else:
            raise ValueError(f"unknown model target {name!r}")
        model = FFModel(FFConfig(batch_size=batch))
        build_dlrm(model, dcfg)
        return model
    if name == "inception_v3":
        from ..models.inception import build_inception_v3
        model = FFModel(FFConfig(batch_size=batch))
        build_inception_v3(model, num_classes=1000)
        return model
    raise ValueError(f"unknown model target {name!r}")


def verify_file(path: str, model_name: Optional[str] = None,
                ndev: Optional[int] = None,
                batch: Optional[int] = None,
                hbm_bytes: Optional[float] = None,
                survivor_ndev: Optional[int] = None,
                topology: Optional[Sequence[Tuple[str, int]]] = None
                ) -> List[Finding]:
    """Load + structurally validate a strategy file, build its target
    model, and run the plan verifier. Load-time validation failures
    (StrategyValidationError) become a single high FLX504 finding so one
    corrupt file cannot crash a whole sweep."""
    from ..parallel.strategy_io import (StrategyValidationError,
                                        load_strategies)
    inferred = infer_target(path)
    if model_name is None or ndev is None:
        if inferred is None:
            raise ValueError(
                f"{path}: cannot infer target model/mesh from the "
                f"filename — pass --model and --ndev")
        model_name = model_name or inferred[0]
        ndev = ndev or inferred[1]
        if topology is None and inferred[2]:
            slices = inferred[2]
            if ndev % slices == 0 and slices > 1:
                from ..parallel.mesh import structural_axis_sizes
                topology = ([("dcn", slices)]
                            + [("ici", s) for s in
                               structural_axis_sizes(ndev // slices)])
    model = build_target_model(model_name, ndev, batch=batch)
    rel = os.path.basename(path)
    try:
        strategies = load_strategies(
            path, num_devices=ndev,
            known_ops={op.name for op in model.ops})
    except StrategyValidationError as e:
        return [make_finding("FLX504", rel, 0,
                             f"load-time validation failed: {e}",
                             scope=e.op, token="load")]
    return verify_plan(model, strategies, ndev, topology=topology,
                       hbm_bytes=hbm_bytes, survivor_ndev=survivor_ndev,
                       path=rel)


# --------------------------------------------------------------------------
# FLX508: strategy quant policy vs checkpoint-manifest quant policy
# --------------------------------------------------------------------------
def _manifest_quant(manifest_path: str) -> Tuple[Dict[str, Dict], str]:
    """Load the quant-policy record of the NEWEST manifest entry.
    Accepts a checkpoint directory or a manifest.json path. Returns
    ({op: {"dtype", "update_rule"}}, display name)."""
    import json
    path = manifest_path
    if os.path.isdir(path):
        path = os.path.join(path, "manifest.json")
    with open(path) as f:
        manifest = json.load(f)
    entries = manifest.get("entries") or []
    if not entries:
        return {}, os.path.basename(path)
    newest = max(entries, key=lambda e: e.get("step", -1))
    return (dict((newest.get("mesh") or {}).get("quant") or {}),
            os.path.basename(path))


def verify_quant_policies(strategies, manifest_quant: Dict[str, Dict],
                          *, default_dtype: str = "fp32",
                          default_update: str = "master_weight",
                          path: str = "<plan>") -> List[Finding]:
    """FLX508: the strategy's per-op quantized-storage policy must agree
    with what the checkpoint manifest says its snapshots were written
    under. A disagreement is silent until the worst moment: every byte
    term (HBM footprint, exchange payloads, delta sizes) is mis-priced
    ~4x, and the first quantized delta payload applied to an
    fp32-planned serving table (or vice versa) is garbage rows.

    ``manifest_quant`` is the manifest's ``mesh.quant`` record
    ({op: {"dtype", "update_rule"}} — :func:`_manifest_quant` loads it);
    ``default_dtype``/``default_update`` fill strategy entries that are
    silent (the model-wide --emb-dtype default the deployment runs
    with)."""
    findings: List[Finding] = []
    names = set(manifest_quant) | set(strategies)
    for name in sorted(names):
        pc = strategies.get(name)
        s_dt = (getattr(pc, "quant_dtype", "") or default_dtype) \
            if pc is not None else default_dtype
        s_up = (getattr(pc, "quant_update", "") or default_update) \
            if pc is not None else default_update
        rec = manifest_quant.get(name) or {}
        m_dt = rec.get("dtype", "fp32")
        m_up = rec.get("update_rule", "master_weight")
        if name not in manifest_quant and pc is not None \
                and not getattr(pc, "quant_dtype", ""):
            # neither side says anything about this op — nothing to
            # disagree on (non-table ops land here)
            continue
        if s_dt != m_dt:
            findings.append(make_finding(
                "FLX508", path, 0,
                f"{name!r}: strategy stores {s_dt} rows but the "
                f"manifest's snapshots were written under "
                f"quant dtype {m_dt} — every byte term is mis-priced "
                f"(~4x for int8/fp8 vs fp32) and quantized payloads "
                f"will not decode against this plan",
                scope=name, token=f"dtype:{s_dt}!={m_dt}"))
        elif s_up != m_up:
            findings.append(make_finding(
                "FLX508", path, 0,
                f"{name!r}: strategy update rule {s_up} disagrees with "
                f"the manifest's {m_up} — master-weight snapshots hold "
                f"the exact fp32 master, stochastic-rounding snapshots "
                f"hold quantized fixed points; restoring across the "
                f"rules silently changes training numerics",
                scope=name, token=f"update:{s_up}!={m_up}",
                severity="medium"))
    return findings


def audit_plan_cache(cache_dir: str) -> List[Finding]:
    """FLX506: re-verify every entry of a persistent plan cache
    (``utils/warmcache.PlanCache``) against the mesh its own key names.

    A cached plan is exactly as dangerous as a strategy file, plus one
    hazard files don't have: it is keyed by topology, and a warm-start
    hit whose RECORDED mesh disagrees with its key (corruption, a
    hand-edited plans.json, a cache directory copied between fleets)
    would reshard silently at best and replicate a >HBM table at worst.
    The runtime ``PlanCache.get`` rejects the same mismatches with a
    reason; this static audit sweeps the whole file before a recovery
    is on the clock — shardcheck warm-starts from the cache instead of
    re-deriving plans.

    Per entry: key ndev vs recorded ndev, key axes vs the structural
    factorization this package builds for that ndev, per-op degree
    assignability on that factorization, and decodability. Everything
    wrong becomes an FLX506 finding naming the entry."""
    from ..parallel.mesh import structural_axis_sizes
    from ..parallel.sharding import assignable
    from ..utils.warmcache import PLANS_FILE, PlanCache, _pc_from_json
    rel = PLANS_FILE
    findings: List[Finding] = []
    cache = PlanCache(cache_dir)
    entries = cache.entries()
    for key, entry in sorted(entries.items()):
        short = key.split("|", 1)[0]
        fields = dict(p.split("=", 1) for p in key.split("|")[1:]
                      if "=" in p)
        try:
            key_ndev = int(fields.get("ndev", ""))
        except ValueError:
            findings.append(make_finding(
                "FLX506", rel, 0,
                f"entry {short}...: key carries no parseable device "
                f"count ({key!r:.80})", scope=short, token=key[:60]))
            continue
        key_axes = [int(a) for a in fields.get("axes", "").split("x")
                    if a.isdigit()]
        structural = [int(a) for a in structural_axis_sizes(key_ndev)]
        ent_ndev = entry.get("ndev")
        if int(ent_ndev or -1) != key_ndev:
            findings.append(make_finding(
                "FLX506", rel, 0,
                f"entry {short}... records ndev={ent_ndev} but its key "
                f"names {key_ndev} device(s) — served on the wrong "
                f"topology this plan resharded silently",
                scope=short, token=f"ndev:{key[:40]}"))
            continue
        if key_axes != structural:
            findings.append(make_finding(
                "FLX506", rel, 0,
                f"entry {short}... key axes {key_axes} are not the "
                f"structural factorization {structural} this package "
                f"builds for {key_ndev} device(s)",
                scope=short, token=f"axes:{key[:40]}"))
            continue
        for op_name, d in sorted((entry.get("strategies") or {}).items()):
            try:
                pc = _pc_from_json(d)
            except (KeyError, TypeError, ValueError) as e:
                findings.append(make_finding(
                    "FLX506", rel, 0,
                    f"entry {short}... op {op_name!r} fails to decode "
                    f"({e})", scope=short, token=f"{op_name}:{key[:40]}"))
                continue
            if not assignable(pc.degrees, structural):
                findings.append(make_finding(
                    "FLX506", rel, 0,
                    f"entry {short}... op {op_name!r} degrees "
                    f"{list(pc.degrees)} cannot assign on the "
                    f"{key_ndev}-device mesh (axes {structural}) the "
                    f"entry is keyed for", scope=short,
                    token=f"{op_name}:{key[:40]}"))
    return findings


def _parse_axes(spec: str) -> List[Tuple[str, int]]:
    """--axes dcn:2,ici:4 -> [("dcn", 2), ("ici", 4)]."""
    out = []
    for part in spec.split(","):
        kind, _, size = part.partition(":")
        kind = kind.strip()
        if kind not in ("ici", "dcn") or not size.strip().isdigit():
            raise ValueError(
                f"bad --axes entry {part!r} (want kind:size with kind "
                f"ici|dcn, e.g. dcn:2,ici:4)")
        out.append((kind, int(size)))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    from .baseline import BaselineError, load_baseline, split_by_baseline
    from .findings import RULES
    ap = argparse.ArgumentParser(
        prog="shardcheck",
        description="Static SPMD plan verifier + lowered-HLO collective "
                    "auditor for dlrm_flexflow_tpu strategy files")
    ap.add_argument("paths", nargs="*",
                    help="strategy files (.pb/.json) to verify; bundled "
                         "filename conventions infer the target model "
                         "and mesh")
    ap.add_argument("--model", default=None,
                    help="target graph (dlrm_kaggle|dlrm_random|"
                         "dlrm_terabyte|dlrm_refN|inception_v3); "
                         "default: inferred from each filename")
    ap.add_argument("--ndev", type=int, default=None,
                    help="target device count (default: inferred)")
    ap.add_argument("--batch", type=int, default=None,
                    help="global batch size (default: 64 x ndev)")
    ap.add_argument("--axes", default=None,
                    help="mesh axes as kind:size[,kind:size...], e.g. "
                         "dcn:2,ici:4 (default: inferred/flat ici)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM capacity cap in GB for the "
                         "FLX503 footprint check (default: off)")
    ap.add_argument("--survivor-ndev", type=int, default=None,
                    help="also project the plan onto this many surviving "
                         "devices and report elastic-clamp hazards "
                         "(FLX505)")
    ap.add_argument("--plan-cache", default=None, metavar="DIR",
                    help="also audit every entry of the persistent plan "
                         "cache in DIR (utils/warmcache.PlanCache — "
                         "what elastic recover()/expand() warm-start "
                         "from) against its recorded mesh signature "
                         "(FLX506)")
    ap.add_argument("--manifest", default=None, metavar="PATH",
                    help="checkpoint directory (or manifest.json) whose "
                         "recorded quantized-storage policies every "
                         "strategy file must agree with (FLX508 "
                         "quant-policy-mismatch)")
    ap.add_argument("--emb-dtype", default="fp32", metavar="DT",
                    help="model-wide default quant dtype the deployment "
                         "runs with, for strategy entries that are "
                         "silent (FLX508; default fp32)")
    ap.add_argument("--audit", action="store_true",
                    help="additionally AOT-lower the train step on the "
                         "attached devices and audit the compiled HLO "
                         "(FLX511-513; needs >= ndev local devices)")
    ap.add_argument("--audit-tolerance", type=float, default=0.25,
                    help="relative drift tolerance for measured-vs-"
                         "predicted collective bytes (default 0.25)")
    ap.add_argument("--serving-replicas", type=int, default=None,
                    metavar="N",
                    help="also audit a SERVING deployment of N ranker "
                         "replicas for the target model (FLX507: "
                         "table-scale params replicated across rankers, "
                         "shard-range tiling)")
    ap.add_argument("--serving-shards", type=int, default=0,
                    metavar="M",
                    help="row-shard the serving lookup tier M ways in "
                         "the FLX507 audit (0 = replicated tables)")
    ap.add_argument("--serve-slo-ms", type=float, default=None,
                    metavar="MS",
                    help="per-request latency SLO the serving "
                         "deployment must meet — enables the FLX509 "
                         "per-seam RTT budget audit")
    ap.add_argument("--serving-rtt-ms", type=float, default=None,
                    metavar="MS",
                    help="per-hop wire RTT floor on the lookup seam "
                         "for FLX509 (default: the transport's "
                         "measured p50 floor, when this process has "
                         "sent wire traffic)")
    ap.add_argument("--serving-retries", type=int, default=2,
                    metavar="N",
                    help="transient-retry budget the wire client is "
                         "configured with (FLX509 prices the serial "
                         "retry chain; default 2 = WireClient default)")
    ap.add_argument("--retrieve-index-rows", type=int, default=None,
                    metavar="N",
                    help="also audit a retrieval MIPS index of N item "
                         "rows in the serving deployment (FLX516: "
                         "per-ranker replication of the codes+scales)")
    ap.add_argument("--retrieve-index-dim", type=int, default=128,
                    metavar="D",
                    help="retrieval index embedding width (FLX516; "
                         "default 128)")
    ap.add_argument("--retrieve-index-quant", default="int8",
                    choices=["int8", "fp8", "fp16", "fp32"],
                    help="retrieval index code dtype (FLX516 residency "
                         "pricing; default int8)")
    ap.add_argument("--retrieve-index-sharded", action="store_true",
                    help="the index rides the sharded embedding tier "
                         "(FLX516 passes: rows stored once, scored in "
                         "place)")
    ap.add_argument("--fail-on", default="high",
                    choices=["high", "medium", "low", "info", "never"])
    ap.add_argument("--baseline", default=DEFAULT_PLAN_BASELINE,
                    help="plan-finding suppression file (default: the "
                         "package's shardcheck_baseline.json)")
    ap.add_argument("--show-baselined", action="store_true")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the FLX5xx rule reference and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, (name, sev, doc) in sorted(RULES.items()):
            if rid.startswith("FLX5"):
                print(f"{rid}  {name:<26} {sev:<7} {doc}")
        return 0
    if not args.paths and not args.plan_cache \
            and args.serving_replicas is None:
        ap.error("no strategy files given (or use --plan-cache / "
                 "--serving-replicas / --list-rules)")

    topology = _parse_axes(args.axes) if args.axes else None
    hbm = args.hbm_gb * 1e9 if args.hbm_gb else None
    findings: List[Finding] = []
    if args.plan_cache:
        try:
            findings.extend(audit_plan_cache(args.plan_cache))
        except (ValueError, OSError) as e:
            print(f"shardcheck: plan-cache audit failed: {e}",
                  file=sys.stderr)
            return 2
    mquant = mname = None
    if args.manifest:
        try:
            mquant, mname = _manifest_quant(args.manifest)
        except (OSError, ValueError) as e:
            print(f"shardcheck: cannot read manifest "
                  f"{args.manifest}: {e}", file=sys.stderr)
            return 2
    for path in args.paths:
        try:
            findings.extend(verify_file(
                path, model_name=args.model, ndev=args.ndev,
                batch=args.batch, hbm_bytes=hbm,
                survivor_ndev=args.survivor_ndev, topology=topology))
            if mquant is not None:
                from ..parallel.strategy_io import load_strategies
                findings.extend(verify_quant_policies(
                    load_strategies(path), mquant,
                    default_dtype=args.emb_dtype,
                    path=f"{os.path.basename(path)}~{mname}"))
        except (ValueError, OSError) as e:
            print(f"shardcheck: {e}", file=sys.stderr)
            return 2
        if args.audit:
            from .hlo_audit import audit_file
            try:
                audit_findings, report = audit_file(
                    path, model_name=args.model, ndev=args.ndev,
                    batch=args.batch, tolerance=args.audit_tolerance)
                findings.extend(audit_findings)
                for k, v in sorted(report.items()):
                    print(f"shardcheck: audit {os.path.basename(path)} "
                          f"{k} = {v}")
            except (ValueError, OSError, RuntimeError) as e:
                print(f"shardcheck: audit skipped for {path}: {e}",
                      file=sys.stderr)
    if args.serving_replicas is not None:
        name = args.model
        if name is None and args.paths:
            tgt = infer_target(args.paths[0])
            name = tgt[0] if tgt else None
        if name is None:
            ap.error("--serving-replicas needs --model (or a strategy "
                     "filename the target is inferable from)")
        model = build_target_model(name, args.ndev or 1, args.batch)
        plan = None
        if args.serving_shards > 0:
            from ..parallel.alltoall import shard_row_ranges
            ranges, flat_rows = {}, {}
            for op in model.ops:
                if hasattr(op, "host_lookup") and op.param_defs():
                    pd = op.param_defs()["kernel"]
                    rows = 1
                    for s in pd.shape[:-1]:
                        rows *= int(s)
                    flat_rows[op.name] = rows
                    ranges[op.name] = shard_row_ranges(
                        rows, args.serving_shards)
            plan = {"nshards": args.serving_shards, "ranges": ranges,
                    "flat_rows": flat_rows,
                    "ranker_holds_tables": False}
        ridx = None
        if args.retrieve_index_rows is not None:
            ridx = {"rows": args.retrieve_index_rows,
                    "dim": args.retrieve_index_dim,
                    "quant": args.retrieve_index_quant,
                    "sharded": bool(args.retrieve_index_sharded)}
        findings.extend(verify_serving_plan(
            model, args.serving_replicas, plan, hbm_bytes=hbm,
            serve_slo_ms=args.serve_slo_ms,
            serving_rtt_ms=args.serving_rtt_ms,
            lookup_retries=args.serving_retries,
            retrieve_index=ridx,
            path=f"<serving:{name}>"))
    findings = sort_findings(findings)

    try:
        baseline = load_baseline(args.baseline)
    except BaselineError as e:
        print(f"shardcheck: {e}", file=sys.stderr)
        return 2
    fresh, suppressed, _stale = split_by_baseline(findings, baseline)
    for f in fresh:
        print(f.render())
    if args.show_baselined:
        for f in suppressed:
            print(f"{f.render()}  [baselined: {baseline[f.key]}]")
    gate = [f for f in fresh if args.fail_on != "never"
            and severity_at_least(f.severity, args.fail_on)]
    print(f"shardcheck: {len(fresh)} finding(s) ({len(gate)} at/above "
          f"--fail-on {args.fail_on}), {len(suppressed)} baselined")
    return 1 if gate else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
