"""``python -m dlrm_flexflow_tpu.analysis`` entry point."""

import sys

from .cli import main

sys.exit(main())
