"""Two-tower candidate-generation model (Covington et al.-style).

The retrieval half of the cascade: a USER tower (dense + sparse user
features through the existing MLP/attention ops) and an ITEM tower (one
embedding table + a small MLP) meet in a shared d-dim space where
relevance is an inner product — which is what makes serving a
maximum-inner-product search over the item corpus (retrieve/index.py).

Training uses in-batch sampled softmax: the (B, d) user and item
embeddings of one batch multiply into a (B, B) logit matrix where row b
treats item b as the positive and the other B-1 in-batch items as
sampled negatives — so the existing ``sparse_categorical_crossentropy``
loss with labels ``arange(B)`` IS the retrieval loss, and the whole
thing trains through the ordinary ``fit()`` path. The item table is a
plain ``Embedding`` op, so the existing SOAP machinery row-shards it at
scale exactly like a ranking table (``two_tower_strategy``).

One graph, three heads, shared op NAMES (``head=``):

  train : user+item inputs -> (B, B) in-batch logits (fit() this)
  user  : user inputs only -> (B, d) user embeddings (query encoder)
  item  : item ids only    -> (B, d) item embeddings (index builder)

Parameters move between heads by op name (``transfer_tower_params``) —
the serving heads are separately-compiled models that hot-swap the
trained weights in, the same way the serving engine swaps snapshots.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..core.model import FFModel
from ..core.initializers import UniformInitializer
from ..parallel.pconfig import StrategyMap


@dataclass
class TwoTowerConfig:
    """Shapes for both towers. ``dim`` is the shared output width — the
    MIPS scoring width; keep it a multiple of 128 when the Pallas
    scoring kernel should route (ops/pallas/topk_kernel.supports)."""

    n_items: int = 1000              # item vocabulary (index row count)
    dim: int = 32                    # shared tower-output width
    user_dense_dim: int = 8          # dense user feature width
    user_embedding_size: List[int] = field(
        default_factory=lambda: [100, 100])   # user sparse vocab sizes
    user_sparse_dim: int = 16        # per-feature user embedding width
    user_bag_size: int = 1
    user_mlp: List[int] = field(default_factory=lambda: [64])
    item_raw_dim: int = 32           # item embedding width before MLP
    item_mlp: List[int] = field(default_factory=lambda: [64])
    attention_heads: int = 0         # >0: self-attention over the user
                                     # feature sequence before the MLP

    @staticmethod
    def bench() -> "TwoTowerConfig":
        """The bench/recall config: lane-aligned dim so TPU runs route
        the Pallas kernel; CPU runs take the identical-math oracle."""
        return TwoTowerConfig(
            n_items=20000, dim=128, user_dense_dim=16,
            user_embedding_size=[5000, 2000, 500], user_sparse_dim=32,
            user_mlp=[256, 128], item_raw_dim=64, item_mlp=[128],
            attention_heads=4)


def _user_tower(model: FFModel, cfg: TwoTowerConfig, batch: int):
    """Dense + per-feature embeddings (+ optional self-attention over
    the feature sequence) -> MLP -> (B, dim). Op names are shared across
    heads so ``transfer_tower_params`` can match them."""
    from ..models.dlrm import create_mlp
    dense_in = model.create_tensor((batch, cfg.user_dense_dim),
                                   name="user_dense")
    T = len(cfg.user_embedding_size)
    sparse_in = model.create_tensor((batch, T, cfg.user_bag_size),
                                    dtype=jnp.int32, name="user_sparse")
    init = UniformInitializer(min_val=-0.05, max_val=0.05)
    cols = model.split(sparse_in, [1] * T, axis=1, name="user_split")
    embs = []
    for i, (rows, col) in enumerate(zip(cfg.user_embedding_size, cols)):
        idx2d = model.reshape(col, (batch, cfg.user_bag_size),
                              name=f"user_idx_{i}")
        embs.append(model.embedding(
            idx2d, rows, cfg.user_sparse_dim, aggr="sum",
            kernel_initializer=init, name=f"user_emb_{i}"))
    if cfg.attention_heads > 0 and T > 1:
        seq = model.concat(
            [model.reshape(e, (batch, 1, cfg.user_sparse_dim),
                           name=f"user_seq_{i}")
             for i, e in enumerate(embs)], axis=1, name="user_seq")
        att = model.multihead_attention(
            seq, num_heads=cfg.attention_heads, name="user_attn")
        feats = model.reshape(att, (batch, T * cfg.user_sparse_dim),
                              name="user_attn_flat")
    else:
        feats = model.concat(embs, axis=1, name="user_cat") if T > 1 \
            else embs[0]
    joined = model.concat([dense_in, feats], axis=1, name="user_join")
    width = cfg.user_dense_dim + T * cfg.user_sparse_dim
    hid = create_mlp(model, joined, [width] + cfg.user_mlp, prefix="user")
    # the projection into the shared space is LINEAR: a relu head would
    # clamp tower outputs non-negative and kill half the inner-product
    # dims at init (create_mlp activates every layer)
    return model.dense(hid, cfg.dim, activation=None,
                       name=f"user_dense_{len(cfg.user_mlp)}")


def _item_tower(model: FFModel, cfg: TwoTowerConfig, batch: int):
    """Item-id embedding -> MLP -> (B, dim)."""
    from ..models.dlrm import create_mlp
    ids_in = model.create_tensor((batch, 1), dtype=jnp.int32,
                                 name="item_ids")
    init = UniformInitializer(min_val=-0.05, max_val=0.05)
    raw = model.embedding(ids_in, cfg.n_items, cfg.item_raw_dim,
                          aggr="sum", kernel_initializer=init,
                          name="item_emb")
    hid = create_mlp(model, raw, [cfg.item_raw_dim] + cfg.item_mlp,
                     prefix="item")
    # linear head, same reason as the user tower
    return model.dense(hid, cfg.dim, activation=None,
                       name=f"item_dense_{len(cfg.item_mlp)}")


def build_two_tower(model: FFModel, cfg: TwoTowerConfig,
                    head: str = "train"
                    ) -> Tuple[Dict[str, tuple], "object"]:
    """Build one head of the two-tower graph on ``model``. Returns
    (input_specs, output_tensor) like ``build_dlrm``."""
    batch = model.config.batch_size
    T = len(cfg.user_embedding_size)
    user_inputs = {"user_dense": (batch, cfg.user_dense_dim),
                   "user_sparse": (batch, T, cfg.user_bag_size)}
    if head == "user":
        return dict(user_inputs), _user_tower(model, cfg, batch)
    if head == "item":
        return {"item_ids": (batch, 1)}, _item_tower(model, cfg, batch)
    if head != "train":
        raise ValueError(f"build_two_tower: unknown head {head!r} "
                         f"(train|user|item)")
    u = _user_tower(model, cfg, batch)
    v = _item_tower(model, cfg, batch)
    # (B, d) x (B, d) -> (B, B) in-batch logits: row b scores user b
    # against every in-batch item (diagonal = the positive)
    u3 = model.reshape(u, (1, batch, cfg.dim), name="logits_u3")
    v3 = model.reshape(v, (1, batch, cfg.dim), name="logits_v3")
    z = model.batch_matmul(u3, v3, trans_a=False, trans_b=True,
                           name="logits_bmm")
    logits = model.reshape(z, (batch, batch), name="logits")
    inputs = dict(user_inputs)
    inputs["item_ids"] = (batch, 1)
    return inputs, logits


def in_batch_labels(batch: int) -> np.ndarray:
    """Labels for the in-batch sampled softmax: row b's positive is
    column b."""
    return np.arange(batch, dtype=np.int32).reshape(batch, 1)


def synthetic_two_tower_batch(cfg: TwoTowerConfig, batch: int,
                              seed: int = 0, zipf_alpha: float = 0.0):
    """Synthetic (inputs, labels) for one train-head batch. Item ids
    draw zipf-skewed (real catalogs are) and the user features carry a
    deterministic signal correlated with the positive item so training
    actually moves recall."""
    from ..data.dataloader import zipf_indices
    rng = np.random.RandomState(seed)
    T = len(cfg.user_embedding_size)
    items = zipf_indices(rng, cfg.n_items, (batch, 1),
                         zipf_alpha).astype(np.int32)
    dense = rng.rand(batch, cfg.user_dense_dim).astype(np.float32)
    # plant signal: dense feature 0 tracks the positive item's id scale
    dense[:, 0] = items[:, 0].astype(np.float32) / float(cfg.n_items)
    sparse = np.stack(
        [(items[:, 0] * (t + 3)) % rows
         for t, rows in enumerate(cfg.user_embedding_size)],
        axis=1).astype(np.int32)[:, :, None]
    sparse = np.broadcast_to(
        sparse, (batch, T, cfg.user_bag_size)).copy()
    inputs = {"user_dense": dense, "user_sparse": sparse,
              "item_ids": items}
    return inputs, in_batch_labels(batch)


def two_tower_strategy(model: FFModel, num_devices: int,
                       row_shard: bool = False) -> StrategyMap:
    """SOAP strategy for any two-tower head: the embedding-table rules
    (row-shard at scale) and data-parallel defaults in ``dlrm_strategy``
    never read the DLRM config, so the same generator covers this
    graph."""
    from ..models.dlrm import dlrm_strategy
    return dlrm_strategy(model, None, num_devices, row_shard=row_shard)


def transfer_tower_params(src: FFModel, dst: FFModel) -> int:
    """Copy trained weights from one head to another BY OP NAME (the
    towers share names across heads), installing atomically through
    ``swap_params`` so a serving head hot-swaps like any snapshot.
    Returns the number of ops transferred."""
    moved = 0
    new_params = {op: dict(d) for op, d in dst.params.items()}
    for op_name, pdict in new_params.items():
        if op_name in (src.params or {}):
            for pname in pdict:
                if pname in src.params[op_name]:
                    pdict[pname] = src.params[op_name][pname]
            moved += 1
    new_host: Optional[Dict] = None
    if dst.host_params:
        new_host = {op: dict(d) for op, d in dst.host_params.items()}
        for op_name, pdict in new_host.items():
            if op_name in (src.host_params or {}):
                for pname in pdict:
                    if pname in src.host_params[op_name]:
                        pdict[pname] = np.array(
                            src.host_params[op_name][pname])
                moved += 1
    dst.swap_params(params=new_params, host_params=new_host)
    return moved


def item_embeddings(item_model: FFModel, cfg: TwoTowerConfig,
                    ids: Optional[np.ndarray] = None) -> np.ndarray:
    """Run the item head over ``ids`` (default: the whole catalog) in
    compiled-batch chunks -> (n, dim) fp32. This is what the index
    builder quantizes, and what a publish re-encodes for touched rows."""
    batch = item_model.config.batch_size
    if ids is None:
        ids = np.arange(cfg.n_items, dtype=np.int32)
    ids = np.asarray(ids, np.int32).reshape(-1)
    out = np.empty((ids.shape[0], cfg.dim), np.float32)
    for lo in range(0, ids.shape[0], batch):
        chunk = ids[lo:lo + batch]
        pad = batch - chunk.shape[0]
        padded = np.concatenate(
            [chunk, np.zeros(pad, np.int32)]) if pad else chunk
        res = np.asarray(item_model.forward_batch(
            {"item_ids": padded.reshape(-1, 1)}))
        out[lo:lo + chunk.shape[0]] = res[:chunk.shape[0]]
    return out
