"""Retrieval stage: two-tower candidate generation + sharded MIPS
top-k on the embedding substrate, cascaded into the existing ranker.

 - model.py   : the two-tower model (train/user/item heads), trained
                through the ordinary ``fit()`` path with in-batch
                sampled softmax
 - index.py   : the sharded MIPS index — int8 QuantTable codes on the
                EmbeddingShard substrate, exact heap-merge at the ranker
 - cascade.py : retrieve -> rank in one fleet behind one deadline budget
"""

from .cascade import (CascadeConfig, CascadeEngine, CascadePrediction,
                      dlrm_candidate_features)
from .index import (INDEX_DELTA_KEY, RetrievalResult, ShardedMIPSIndex,
                    merge_partials)
from .model import (TwoTowerConfig, build_two_tower, in_batch_labels,
                    item_embeddings, synthetic_two_tower_batch,
                    transfer_tower_params, two_tower_strategy)

__all__ = [
    "CascadeConfig", "CascadeEngine", "CascadePrediction",
    "dlrm_candidate_features",
    "INDEX_DELTA_KEY", "RetrievalResult", "ShardedMIPSIndex",
    "merge_partials",
    "TwoTowerConfig", "build_two_tower", "in_batch_labels",
    "item_embeddings", "synthetic_two_tower_batch",
    "transfer_tower_params", "two_tower_strategy",
]
