"""Sharded MIPS index over the embedding-shard substrate.

The index IS one more quantized table: item-tower output embeddings
stored as PR-14 ``QuantTable`` int8 codes + fp32 row scales, attached to
an :class:`~..serve.shardtier.EmbeddingShardSet` so each
``EmbeddingShard`` owns a contiguous row range and answers LOCAL top-k
over it (``EmbeddingShard.topk`` — the Pallas kernel on TPU, the
bit-identical oracle elsewhere). This buys, for free, everything the
ranking tables already have: per-shard delta chains (one publish
advances ranking AND retrieval from one manifest), version-vector
old-or-new-never-mixed, circuit breakers, warm-cache persistence.

**The merge is exact.** Every shard scores the same quantized query
codes with the same integer dot and the same fixed-order fp32 rescale,
so a row's score is identical wherever it lives; each shard's partial
is sorted (score desc, id asc) and the ranker k-way heap-merges them on
the same key. The result is therefore bitwise-identical to a
single-machine exact scan over the same codes — pinned by the golden
tests across shard counts {1, 2, 4}, ties and all.

**Degradation drops, never invents.** A dead shard's candidates are
simply absent from the merge: the answer is a correct top-k over the
rows that answered, flagged ``degraded`` with the dropped slots named —
candidates are never fabricated from defaults the way ranking rows
degrade (a made-up candidate id would be served downstream as real).
"""

from __future__ import annotations

import heapq
import time
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..ops.pallas.topk_kernel import (mips_topk_reference, quantize_query,
                                      topk_select_np)
from ..quant.store import QuantTable
from ..serve.shardtier import (EmbeddingShard, EmbeddingShardSet,
                               ShardReplica, ShardTierConfig)

# the delta-payload key template the index publishes under — the same
# "hostparams/<op>/kernel" namespace split_host_rows_by_shard routes
INDEX_DELTA_KEY = "hostparams/{op}/kernel"


class RetrievalResult(NamedTuple):
    """One merged retrieval answer. ``ids``/``scores`` are (B, k'),
    ordered (score desc, id asc) per row; ``versions`` is the per-shard
    version vector actually read; ``dropped_slots`` names the shards
    whose candidates are absent (degraded)."""

    ids: np.ndarray                 # (B, k') int64
    scores: np.ndarray              # (B, k') float32
    versions: Dict[int, int]
    degraded: bool
    dropped_slots: List[int]
    latency_ms: float


def merge_partials(scores_by_slot: Dict[int, np.ndarray],
                   ids_by_slot: Dict[int, np.ndarray],
                   k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Exact k-way heap-merge of per-shard sorted partials.

    Each partial row is sorted by (score desc, id asc) — i.e. ascending
    in the key ``(-score, id)`` — so ``heapq.merge`` on that key is the
    textbook exact merge: the first k popped are the global top-k in
    the same order a single-machine sort would produce. fp32 negation
    is exact, so the key order is bit-faithful to the scores."""
    slots = sorted(scores_by_slot)
    if not slots:
        return (np.empty((0, 0), np.int64), np.empty((0, 0), np.float32))
    B = scores_by_slot[slots[0]].shape[0]
    avail = sum(scores_by_slot[s].shape[1] for s in slots)
    kk = min(int(k), avail)
    out_i = np.empty((B, kk), np.int64)
    out_s = np.empty((B, kk), np.float32)
    for b in range(B):
        streams = [
            zip(-scores_by_slot[s][b], ids_by_slot[s][b],
                scores_by_slot[s][b])
            for s in slots]
        for j, (_neg, rid, sc) in enumerate(heapq.merge(*streams)):
            if j >= kk:
                break
            out_i[b, j] = rid
            out_s[b, j] = sc
    return out_i, out_s


class ShardedMIPSIndex:
    """The retrieval index: quantized item embeddings attached to a
    shard set, queried by quantize-once → per-shard local top-k →
    exact merge."""

    def __init__(self, shard_set: EmbeddingShardSet, op_name: str,
                 n_items: int, dim: int,
                 table: Optional[QuantTable] = None):
        self.shard_set = shard_set
        self.op_name = op_name
        self.n_items = int(n_items)
        self.dim = int(dim)
        # the full code table, kept (int8 — cheap) for the exact-scan
        # oracle and recall benches; None on memory-tight deployments
        self.table = table
        self.queries = 0
        self.degraded_queries = 0

    # --- construction ---------------------------------------------------
    @classmethod
    def build(cls, shard_set: EmbeddingShardSet,
              embeddings: np.ndarray, op_name: str = "retrieve_index",
              keep_table: bool = True) -> "ShardedMIPSIndex":
        """Quantize (n_items, d) fp32 item-tower outputs to int8 codes
        and attach them to ``shard_set`` as the retrieval index."""
        table = (embeddings if isinstance(embeddings, QuantTable)
                 else QuantTable.from_dense(
                     np.asarray(embeddings, np.float32), "int8"))
        if table.dtype != "int8":
            raise ValueError("the MIPS index scores int8 codes; build "
                             "the QuantTable with dtype='int8'")
        shard_set.attach_index(op_name, table)
        return cls(shard_set, op_name, table.shape[0], table.shape[1],
                   table=table if keep_table else None)

    @staticmethod
    def standalone_set(nshards: int,
                       config: Optional[ShardTierConfig] = None
                       ) -> EmbeddingShardSet:
        """An index-only shard set (no ranking tables behind it) — the
        ``--retrieve-shards`` deployment shape when the ranker fleet is
        not itself sharded. Attach the index with :meth:`build`."""
        config = config or ShardTierConfig(nshards=nshards)
        if config.nshards != nshards:
            config.nshards = nshards
        shards = [ShardReplica(EmbeddingShard(slot, slot, {}, {}))
                  for slot in range(nshards)]
        return EmbeddingShardSet(shards, config, {}, {}, {}, {}, {},
                                 fingerprint="retrieve-standalone")

    # --- the query path -------------------------------------------------
    def topk(self, user_emb: np.ndarray, k: int,
             deadline_s: Optional[float] = None,
             degrade: Optional[str] = None) -> RetrievalResult:
        """Top-k MIPS over the sharded index for a (B, d) fp32 query
        batch. The query is quantized ONCE; every shard scores the same
        codes, so the merged answer is exactly the single-machine scan
        over the rows that answered."""
        t0 = time.perf_counter()
        q_codes, q_scales = quantize_query(user_emb)
        if q_codes.shape[1] != self.dim:
            raise ValueError(
                f"query dim {q_codes.shape[1]} != index dim {self.dim}")
        parts = self.shard_set.topk_partials(
            q_codes, q_scales, int(k), deadline_s=deadline_s,
            degrade=degrade)
        ids, scores = merge_partials(parts.scores, parts.ids, int(k))
        if ids.shape[1] == 0 and q_codes.shape[0] and not parts.scores:
            ids = np.empty((q_codes.shape[0], 0), np.int64)
            scores = np.empty((q_codes.shape[0], 0), np.float32)
        self.queries += 1
        if parts.degraded:
            self.degraded_queries += 1
        return RetrievalResult(
            ids, scores, parts.versions, parts.degraded,
            parts.dropped_slots,
            1e3 * (time.perf_counter() - t0))

    def exact_scan(self, user_emb: np.ndarray, k: int
                   ) -> Tuple[np.ndarray, np.ndarray]:
        """Single-machine exact scan over the SAME quantized codes —
        the golden-test twin of :meth:`topk` (returns (scores, ids))."""
        if self.table is None:
            raise ValueError("exact_scan needs the kept code table "
                             "(build(keep_table=True))")
        q_codes, q_scales = quantize_query(user_emb)
        return mips_topk_reference(
            q_codes, q_scales, np.asarray(self.table.q),
            self.table.scales, int(k))

    def exact_scan_fp32(self, user_emb: np.ndarray,
                        item_emb: np.ndarray, k: int
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """fp32 exact scan over UNQUANTIZED item embeddings — the
        recall@k reference (what the int8 path is measured against)."""
        scores = (np.asarray(user_emb, np.float32)
                  @ np.asarray(item_emb, np.float32).T)
        ids = np.arange(item_emb.shape[0], dtype=np.int64)
        return topk_select_np(scores, ids, int(k))

    # --- freshness (one publish, both stages) ---------------------------
    def delta_key(self) -> str:
        return INDEX_DELTA_KEY.format(op=self.op_name)

    def augment_delta(self, payload: Dict[str, Any],
                      ids: np.ndarray, embeddings: np.ndarray
                      ) -> Dict[str, Any]:
        """Fold re-encoded item rows into a delta-publish payload so ONE
        publish advances ranking tables and the index together: the
        shard set routes the added ``hostparams/<op>/kernel`` entry
        through the same split/CRC/apply path as every table row, and
        the kept oracle table is updated in lockstep (the exact-scan
        twin must keep describing what the shards serve)."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        vals = np.asarray(embeddings, np.float32)
        if vals.shape != (ids.size, self.dim):
            raise ValueError(
                f"augment_delta: embeddings {vals.shape} != "
                f"({ids.size}, {self.dim})")
        rows = payload.setdefault("rows", {})
        rows[self.delta_key()] = (ids, vals)
        if self.table is not None:
            self.table.set_rows(ids, vals)
        return payload

    def stats(self) -> Dict[str, Any]:
        return {
            "op": self.op_name,
            "n_items": self.n_items,
            "dim": self.dim,
            "queries": self.queries,
            "degraded_queries": self.degraded_queries,
            "version_vector": self.shard_set.version_vector(),
        }
