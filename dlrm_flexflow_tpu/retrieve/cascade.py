"""Retrieve→rank cascade: one fleet, two stages, one deadline budget.

``CascadeEngine`` is the serving seam that turns "score THIS row" into
"answer this USER": encode the user, MIPS top-k over the sharded index
(retrieve/index.py), expand the k candidates into ranker rows, score
them through the EXISTING engine/router (dynamic batching, hedging,
circuit breakers — nothing re-implemented here), and re-rank.

Budgeting is per-stage feeding per-request: the retrieve stage gets
``min(retrieve_deadline_ms, what's left)``; the ranker gets the rest;
overrunning either raises the serving tier's own ``DeadlineExceeded``
(not a new exception type — cascade timeouts read like every other
serving timeout in logs and tests).

Degradation composes, it does not multiply: a dead index shard drops
its candidates (flagged, never fabricated — see retrieve/index.py), a
dead embedding shard under the RANKER degrades rows to defaults
(flagged by the Prediction), and the cascade's ``degraded`` is the OR.
Freshness composes the same way: ``retrieve_versions`` and
``rank_versions`` are both surfaced so a reader can pin exactly which
index and which tables answered.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import numpy as np

from ..serve.engine import DeadlineExceeded
from ..utils.watchdog import Deadline
from .index import ShardedMIPSIndex


@dataclass
class CascadeConfig:
    """Cascade knobs; ``from_config`` lifts the ``--retrieve-*``
    flags."""

    k: int = 100                     # candidates out of retrieval
    retrieve_deadline_ms: float = 25.0   # retrieve-stage budget
    deadline_ms: float = 0.0         # end-to-end budget; 0 = none

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"retrieve k must be >= 1, got {self.k}")
        if self.retrieve_deadline_ms < 0:
            raise ValueError("retrieve deadline must be >= 0")

    @staticmethod
    def from_config(cfg) -> "CascadeConfig":
        return CascadeConfig(
            k=int(getattr(cfg, "retrieve_k", 100)),
            retrieve_deadline_ms=float(
                getattr(cfg, "retrieve_deadline_ms", 25.0)),
            deadline_ms=float(getattr(cfg, "serve_deadline_ms", 0.0)))


class CascadePrediction(NamedTuple):
    """One answered user request: the re-ranked candidate ids and both
    stages' receipts (scores, version vectors, degradation, per-stage
    latency)."""

    ids: np.ndarray                  # (B, k') int64, ranker order
    scores: np.ndarray               # (B, k') fp32 ranker scores, desc
    retrieve_scores: np.ndarray      # (B, k') fp32 MIPS scores, aligned
    #                                  with ids (NOT retrieval order)
    retrieve_versions: Dict[int, int]
    rank_version: int
    rank_versions: Optional[Dict[int, int]]
    degraded: bool
    dropped_slots: List[int]
    latency_ms: float
    stage_ms: Dict[str, float]       # {"retrieve": ..., "rank": ...}


def dlrm_candidate_features(n_tables: int, table_rows: List[int],
                            candidate_slot: int = 0
                            ) -> Callable[[Dict[str, np.ndarray],
                                           np.ndarray],
                                          Dict[str, np.ndarray]]:
    """Default candidate expansion for a DLRM ranker: tile each user's
    'dense'/'sparse' row k times and write the candidate id into sparse
    slot ``candidate_slot`` (mod that table's vocabulary) — the (user,
    candidate) pair becomes one ordinary ranker row."""
    rows = int(table_rows[candidate_slot])

    def expand(features: Dict[str, np.ndarray], ids: np.ndarray
               ) -> Dict[str, np.ndarray]:
        B, k = ids.shape
        dense = np.repeat(np.asarray(features["dense"], np.float32),
                          k, axis=0)
        sparse = np.repeat(np.asarray(features["sparse"], np.int32),
                           k, axis=0).copy()
        sparse[:, candidate_slot, :] = (
            ids.reshape(B * k, 1) % rows).astype(np.int32)
        return {"dense": dense, "sparse": sparse}

    return expand


class CascadeEngine:
    """retrieve -> expand -> rank -> re-rank, behind one ``predict``.

    ``user_encoder`` maps the request's features to (B, d) fp32 user
    embeddings (typically the two-tower user head's ``forward_batch``);
    ``ranker`` is anything with the serving tier's
    ``predict(features, timeout=) -> Prediction`` shape — an
    InferenceEngine, a FleetRouter over a fleet, or a transport stub;
    ``candidate_features`` expands (user features, (B, k) ids) into the
    ranker's B*k-row feature dict (``dlrm_candidate_features`` for the
    stock DLRM graph)."""

    def __init__(self, index: ShardedMIPSIndex,
                 user_encoder: Callable[[Dict[str, np.ndarray]],
                                        np.ndarray],
                 ranker: Any,
                 candidate_features: Callable[[Dict[str, np.ndarray],
                                               np.ndarray],
                                              Dict[str, np.ndarray]],
                 config: Optional[CascadeConfig] = None):
        self.index = index
        self.user_encoder = user_encoder
        self.ranker = ranker
        self.candidate_features = candidate_features
        self.config = config or CascadeConfig()
        self.requests = 0
        self.degraded_requests = 0
        self.deadline_misses = 0

    def predict(self, features: Dict[str, np.ndarray],
                timeout: Optional[float] = None) -> CascadePrediction:
        """Answer one user batch end-to-end. ``timeout`` (seconds)
        overrides the configured end-to-end budget for this request."""
        t0 = time.perf_counter()
        budget_s = (timeout if timeout is not None
                    else (self.config.deadline_ms / 1e3
                          if self.config.deadline_ms > 0 else 0.0))
        dl = Deadline(budget_s)   # seconds <= 0 = never expires

        # --- stage 1: retrieve -----------------------------------------
        user_emb = np.asarray(self.user_encoder(features), np.float32)
        stage_budget = self.config.retrieve_deadline_ms / 1e3
        rem = dl.remaining()
        if rem != float("inf"):
            if rem <= 0:
                self.deadline_misses += 1
                raise DeadlineExceeded(dl.report(
                    worker="ff-cascade",
                    waiting_for="the retrieve stage to start",
                    detail="budget spent encoding the user"))
            stage_budget = min(stage_budget, rem)
        r = self.index.topk(user_emb, self.config.k,
                            deadline_s=stage_budget)
        t_retrieve = time.perf_counter()
        if r.ids.shape[1] == 0:
            self.requests += 1
            self.degraded_requests += 1
            return CascadePrediction(
                r.ids, np.empty_like(r.scores), r.scores, r.versions,
                -1, None, True, r.dropped_slots,
                1e3 * (time.perf_counter() - t0),
                {"retrieve": 1e3 * (t_retrieve - t0), "rank": 0.0})

        # --- stage 2: rank ---------------------------------------------
        rem = dl.remaining()
        if rem <= 0:
            self.deadline_misses += 1
            raise DeadlineExceeded(dl.report(
                worker="ff-cascade",
                waiting_for="ranker budget after the retrieve stage",
                detail=f"retrieve took {r.latency_ms:.1f}ms"))
        cand = self.candidate_features(features, r.ids)
        pred = self.ranker.predict(
            cand, timeout=None if rem == float("inf") else rem)
        t_rank = time.perf_counter()

        # --- re-rank: ranker scores decide the final order --------------
        B, k = r.ids.shape
        # a ranker head may emit >1 unit per row (a toy top MLP, a
        # multi-task head); unit 0 is the ranking score by convention
        flat = np.asarray(pred.scores, np.float32)
        flat = flat.reshape(B, k, -1)[:, :, 0]
        # (score desc, retrieval-rank asc) — a stable, deterministic
        # order even when the ranker ties
        order = np.lexsort((np.broadcast_to(np.arange(k), (B, k)),
                            -flat), axis=1)
        take = np.take_along_axis
        degraded = bool(r.degraded or pred.degraded)
        self.requests += 1
        if degraded:
            self.degraded_requests += 1
        return CascadePrediction(
            take(r.ids, order, 1), take(flat, order, 1),
            take(r.scores, order, 1), r.versions,
            pred.version, pred.versions, degraded, r.dropped_slots,
            1e3 * (time.perf_counter() - t0),
            {"retrieve": 1e3 * (t_retrieve - t0),
             "rank": 1e3 * (t_rank - t_retrieve)})

    def stats(self) -> Dict[str, Any]:
        return {
            "requests": self.requests,
            "degraded_requests": self.degraded_requests,
            "deadline_misses": self.deadline_misses,
            "index": self.index.stats(),
        }
