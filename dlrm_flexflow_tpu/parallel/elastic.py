"""Elastic-mesh recovery: keep training after device loss.

The paper's framework searches a SOAP parallelization for a FIXED machine
model; on real TPU fleets preemptions and chip failures shrink the
topology mid-run. Bamboo/Varuna-style elasticity is: detect the shrunken
topology (``parallel.distributed.MeshDegraded`` — heartbeat registry,
collective-deadline probe, fault injection), RE-PLAN parallelism for it
(``search.replan`` — constrained MCMC with a greedy clamp fallback),
reshard state, and continue. This module is the orchestration of those
pieces into one verb:

    report = recover(model, lost=dead_devices, manager=ckpt_mgr)

Recovery modes (``FFConfig.elastic`` / ``--elastic``):

- ``"off"``     — no recovery; MeshDegraded propagates (legacy behavior).
- ``"resume"``  — recompile onto the survivors, then restore the newest
  valid rolling snapshot through the manager. Exact: training repeats
  from the last checkpoint, so the post-recovery trajectory is
  bit-identical to a fresh job started on the shrunken mesh from the
  same snapshot (tests/test_elastic.py pins this).
- ``"inplace"`` — gather the CURRENT in-memory state to host, recompile,
  re-split onto the new mesh, continue from the current step. No
  checkpoint required and no lost steps, but single-controller only
  (the host gather reads every shard; a multi-host job whose dead peer
  held shards must use ``"resume"``). With ``host_tables_async`` the
  dropped step's host scatter may be lost (the documented one-step
  staleness also bounds recovery).

The reshard itself is simple by construction: snapshots are
host-gathered full arrays, so loading them through the model's freshly
compiled ``_param_sharding`` (plain ``device_put`` per parameter) IS the
gather-to-host → re-split per new partition degrees step; host-resident
tables are already mesh-agnostic numpy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .distributed import MeshDegraded
from .mesh import make_mesh
from .pconfig import StrategyMap
from ..utils.logging import get_logger

log_elastic = get_logger("elastic")


@dataclass
class RecoveryReport:
    """What one elastic recovery did, with timings for bench_elastic."""

    mode: str
    lost: List[Any]
    surviving: int
    strategies: StrategyMap
    step: int                       # step training continues from
    replan_s: float = 0.0
    reshard_s: float = 0.0
    total_s: float = 0.0
    searched: bool = False          # MCMC ran (vs greedy clamp only)
    greedy_fallback: bool = False
    # manifest entry for "resume" mode (carries loader_state so fit can
    # rewind its (epoch, batch) position); None for "inplace"
    entry: Optional[Dict[str, Any]] = field(default=None, repr=False)


def surviving_devices(mesh, lost: Sequence) -> List:
    """The mesh's devices minus the lost ones, in mesh order."""
    lost_ids = {id(d) for d in lost} | {str(d) for d in lost}
    return [d for d in mesh.devices.flat
            if id(d) not in lost_ids and str(d) not in lost_ids]


def recover(model, lost: Sequence = (), mode: Optional[str] = None,
            manager=None, budget: Optional[int] = None,
            seed: int = 0) -> RecoveryReport:
    """Re-plan + reshard `model` onto the devices surviving `lost`.

    Steps: quiesce background workers → re-search strategies for the
    surviving count (greedy fallback on failure/zero budget) → factorize
    a fresh mesh → recompile the step functions → reshard params/opt
    state/op state (from memory for ``inplace``, from the newest valid
    snapshot via `manager` for ``resume``). Raises MeshDegraded when no
    devices survive, ValueError on misuse (mode "off", resume without a
    manager or restorable snapshot).
    """
    t_start = time.perf_counter()
    cfg = getattr(model, "config", None)
    mode = mode or getattr(cfg, "elastic", "off")
    if mode not in ("resume", "inplace"):
        raise ValueError(
            f"elastic recovery needs mode 'resume' or 'inplace', got "
            f"{mode!r} (set FFConfig.elastic / --elastic)")
    if budget is None:
        budget = int(getattr(cfg, "elastic_search_budget", 100) or 0)
    if model.mesh is None:
        raise ValueError("recover() needs a compiled model (no mesh)")

    # 1. quiesce: abandon/drain background workers so nothing scatters
    #    into state we are about to replace (a wedged worker is exactly
    #    why we may be here — never block on it)
    if hasattr(model, "_host_abandon"):
        model._host_abandon()

    old_mesh = model.mesh
    survivors = surviving_devices(old_mesh, lost)
    if not survivors:
        raise MeshDegraded("no surviving devices to recover onto",
                           lost=list(lost))
    if len(survivors) == old_mesh.size and lost:
        log_elastic.warning(
            "lost devices %s were not part of the mesh; recovering "
            "anyway (mesh rebuild + reshard on the same %d devices)",
            [str(d) for d in lost], len(survivors))

    # 2. re-plan parallelism for the surviving count (deterministic for
    #    a fixed seed — the bit-identity contract depends on it)
    from ..search.replan import replan_strategies
    strategies, info = replan_strategies(
        model, len(survivors), old=model.strategies, budget=budget,
        seed=seed)

    # 3. inplace: gather current state to host BEFORE the recompile
    #    (device arrays stay valid either way — np.asarray reads any
    #    sharding — but gathering first keeps the invariant that a
    #    recompile failure leaves the model untouched)
    flat = None
    if mode == "inplace":
        from ..utils.checkpoint import _model_flat
        flat = _model_flat(model, copy_host=True)

    # 4. fresh factorized mesh over the survivors + recompile the step.
    #    compile() rebuilds shardings, host-residency sets, and the
    #    jitted train/eval steps; the executable cache is dropped.
    t_reshard = time.perf_counter()
    new_mesh = make_mesh(devices=survivors)
    model.compile(optimizer=model.optimizer, loss_type=model.loss_type,
                  metrics=model.metrics, mesh=new_mesh,
                  strategies=strategies,
                  final_tensor=model._preds_tensor)

    # 5. reshard state onto the new mesh
    entry = None
    if mode == "inplace":
        from ..utils.checkpoint import restore_from_flat
        restore_from_flat(model, flat, source="<elastic inplace>")
    else:
        if manager is None:
            raise ValueError(
                'elastic mode "resume" needs a CheckpointManager '
                "(fit(checkpoint_dir=...) provides one)")
        entry = manager.restore_latest(model)
        if entry is None:
            raise MeshDegraded(
                "no restorable snapshot for elastic resume (checkpoint "
                "directory empty or all snapshots invalid)",
                lost=list(lost))
    reshard_s = time.perf_counter() - t_reshard

    report = RecoveryReport(
        mode=mode, lost=list(lost), surviving=len(survivors),
        strategies=strategies, step=int(model._step),
        replan_s=float(info.get("replan_s", 0.0)),
        reshard_s=reshard_s,
        total_s=time.perf_counter() - t_start,
        searched=bool(info.get("searched", False)),
        greedy_fallback=bool(info.get("greedy_fallback", False)),
        entry=entry)
    log_elastic.warning(
        "elastic recovery (%s): %d -> %d devices, replan %.0f ms "
        "(%s), reshard %.0f ms, resuming at step %d",
        mode, old_mesh.size, len(survivors), 1e3 * report.replan_s,
        "searched" if report.searched else "greedy clamp",
        1e3 * report.reshard_s, report.step)
    return report
