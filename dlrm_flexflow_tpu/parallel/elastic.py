"""Elastic-mesh recovery: keep training after device loss.

The paper's framework searches a SOAP parallelization for a FIXED machine
model; on real TPU fleets preemptions and chip failures shrink the
topology mid-run. Bamboo/Varuna-style elasticity is: detect the shrunken
topology (``parallel.distributed.MeshDegraded`` — heartbeat registry,
collective-deadline probe, fault injection), RE-PLAN parallelism for it
(``search.replan`` — constrained MCMC with a greedy clamp fallback),
reshard state, and continue. This module is the orchestration of those
pieces into one verb:

    report = recover(model, lost=dead_devices, manager=ckpt_mgr)

Recovery modes (``FFConfig.elastic`` / ``--elastic``):

- ``"off"``     — no recovery; MeshDegraded propagates (legacy behavior).
- ``"resume"``  — recompile onto the survivors, then restore the newest
  valid rolling snapshot through the manager. Exact: training repeats
  from the last checkpoint, so the post-recovery trajectory is
  bit-identical to a fresh job started on the shrunken mesh from the
  same snapshot (tests/test_elastic.py pins this).
- ``"inplace"`` — gather the CURRENT in-memory state to host, recompile,
  re-split onto the new mesh, continue from the current step. No
  checkpoint required and no lost steps, but single-controller only
  (the host gather reads every shard; a multi-host job whose dead peer
  held shards must use ``"resume"``). With ``host_tables_async`` the
  dropped step's host scatter may be lost (the documented one-step
  staleness also bounds recovery).

The reshard itself is simple by construction: snapshots are
host-gathered full arrays, so loading them through the model's freshly
compiled ``_param_sharding`` (plain ``device_put`` per parameter) IS the
gather-to-host → re-split per new partition degrees step; host-resident
tables are already mesh-agnostic numpy.

Scale-UP (:func:`expand`) is the inverse verb: when lost capacity comes
BACK (``parallel.distributed.MeshReturned`` — registry heartbeats from a
re-admitted host, or the ``FF_FAULT_RETURN_DEVICE`` hook on CPU test
meshes), the model re-plans onto the GROWN device set
(``search.replan.expand_strategies`` — the clamp machinery in reverse,
warm-started from the remembered pre-shrink plan when one matches) and
reshards the same way. A shrink followed by an expand is bit-identical
to a fresh run on the large mesh from the same snapshot
(tests/test_elastic.py pins this).

Warm starts: both verbs consult the persistent plan + compile caches
(``utils/warmcache``, attached to the model by ``fit()`` when
``--compile-cache-dir`` is configured, or passed explicitly) so a
recovery on a previously-seen topology skips the MCMC search and the
first post-reshard dispatch loads its AOT executable instead of
recompiling — seconds of downtime become milliseconds
(benchmarks/bench_elastic.py measures both sides).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .distributed import MeshDegraded
from .mesh import make_mesh
from .pconfig import StrategyMap
from ..utils.logging import get_logger

log_elastic = get_logger("elastic")


@dataclass
class RecoveryReport:
    """What one elastic recovery (or expansion) did, with timings for
    bench_elastic."""

    mode: str
    lost: List[Any]
    surviving: int
    strategies: StrategyMap
    step: int                       # step training continues from
    replan_s: float = 0.0
    reshard_s: float = 0.0
    total_s: float = 0.0
    searched: bool = False          # MCMC ran (vs greedy clamp only)
    greedy_fallback: bool = False
    kind: str = "recover"           # "recover" (shrink) | "expand" (grow)
    plan_cache_hit: bool = False    # re-plan served from the PlanCache
    # manifest entry for "resume" mode (carries loader_state so fit can
    # rewind its (epoch, batch) position); None for "inplace"
    entry: Optional[Dict[str, Any]] = field(default=None, repr=False)


def surviving_devices(mesh, lost: Sequence) -> List:
    """The mesh's devices minus the lost ones, in mesh order."""
    lost_ids = {id(d) for d in lost} | {str(d) for d in lost}
    return [d for d in mesh.devices.flat
            if id(d) not in lost_ids and str(d) not in lost_ids]


def _resolve_mode(model, mode: Optional[str], verb: str) -> str:
    cfg = getattr(model, "config", None)
    mode = mode or getattr(cfg, "elastic", "off")
    if mode not in ("resume", "inplace"):
        raise ValueError(
            f"elastic {verb} needs mode 'resume' or 'inplace', got "
            f"{mode!r} (set FFConfig.elastic / --elastic)")
    return mode


def _resolve_budget(model, budget: Optional[int]) -> int:
    if budget is not None:
        return int(budget)
    cfg = getattr(model, "config", None)
    return int(getattr(cfg, "elastic_search_budget", 100) or 0)


def _reshard_onto(model, devices, strategies, mode: str, manager,
                  degraded_reason: Optional[Sequence] = None
                  ) -> tuple:
    """Shared shrink/grow reshard: (optionally) gather in-memory state,
    rebuild the mesh, recompile, restore. Returns (entry, reshard_s)."""
    # inplace: gather current state to host BEFORE the recompile
    # (device arrays stay valid either way — np.asarray reads any
    # sharding — but gathering first keeps the invariant that a
    # recompile failure leaves the model untouched)
    flat = None
    if mode == "inplace":
        from ..utils.checkpoint import _model_flat
        flat = _model_flat(model, copy_host=True)

    # fresh factorized mesh + recompile the step. compile() rebuilds
    # shardings, host-residency sets, and the jitted train/eval steps;
    # the in-memory executable cache is dropped (a persistent
    # CompileCache attached to the model survives, so the first
    # post-reshard dispatch warm-starts from disk).
    t_reshard = time.perf_counter()
    new_mesh = make_mesh(devices=list(devices))
    model.compile(optimizer=model.optimizer, loss_type=model.loss_type,
                  metrics=model.metrics, mesh=new_mesh,
                  strategies=strategies,
                  final_tensor=model._preds_tensor)

    entry = None
    if mode == "inplace":
        from ..utils.checkpoint import restore_from_flat
        restore_from_flat(model, flat, source="<elastic inplace>")
    else:
        if manager is None:
            raise ValueError(
                'elastic mode "resume" needs a CheckpointManager '
                "(fit(checkpoint_dir=...) provides one)")
        entry = manager.restore_latest(model)
        if entry is None:
            raise MeshDegraded(
                "no restorable snapshot for elastic resume (checkpoint "
                "directory empty or all snapshots invalid)",
                lost=list(degraded_reason or []))
    return entry, time.perf_counter() - t_reshard


def _remember_plan(model, mesh, strategies) -> None:
    """Record (size, strategies) so a later expand() back to this device
    count restores the exact pre-shrink intent — the round-trip
    (shrink at j, expand at k) then reproduces the original plan and
    stays bit-identical to a fresh large-mesh run."""
    hist = getattr(model, "_elastic_history", None)
    if hist is None:
        hist = model._elastic_history = []
    hist.append((int(mesh.size), dict(strategies or {})))


def _recall_plan(model, ndev: int) -> Optional[StrategyMap]:
    """The most recent remembered plan for exactly `ndev` devices."""
    for size, strategies in reversed(getattr(model, "_elastic_history",
                                             [])):
        if size == int(ndev):
            return dict(strategies)
    return None


def recover(model, lost: Sequence = (), mode: Optional[str] = None,
            manager=None, budget: Optional[int] = None,
            seed: int = 0, plan_cache=None) -> RecoveryReport:
    """Re-plan + reshard `model` onto the devices surviving `lost`.

    Steps: quiesce background workers → re-search strategies for the
    surviving count (greedy fallback on failure/zero budget; served from
    the attached/given PlanCache when the topology was seen before) →
    factorize a fresh mesh → recompile the step functions → reshard
    params/opt state/op state (from memory for ``inplace``, from the
    newest valid snapshot via `manager` for ``resume``). Raises
    MeshDegraded when no devices survive, ValueError on misuse (mode
    "off", resume without a manager or restorable snapshot).
    """
    t_start = time.perf_counter()
    mode = _resolve_mode(model, mode, "recovery")
    budget = _resolve_budget(model, budget)
    if plan_cache is None:
        plan_cache = getattr(model, "_plan_cache", None)
    if model.mesh is None:
        raise ValueError("recover() needs a compiled model (no mesh)")

    # 1. quiesce: abandon/drain background workers so nothing scatters
    #    into state we are about to replace (a wedged worker is exactly
    #    why we may be here — never block on it)
    if hasattr(model, "_host_abandon"):
        model._host_abandon()

    old_mesh = model.mesh
    survivors = surviving_devices(old_mesh, lost)
    if not survivors:
        raise MeshDegraded("no surviving devices to recover onto",
                           lost=list(lost))
    if len(survivors) == old_mesh.size and lost:
        log_elastic.warning(
            "lost devices %s were not part of the mesh; recovering "
            "anyway (mesh rebuild + reshard on the same %d devices)",
            [str(d) for d in lost], len(survivors))

    # 2. re-plan parallelism for the surviving count (deterministic for
    #    a fixed seed — the bit-identity contract depends on it), and
    #    remember the pre-shrink plan so a later expand() back to this
    #    size restores the exact intent
    from ..search.replan import replan_strategies
    _remember_plan(model, old_mesh, model.strategies)
    strategies, info = replan_strategies(
        model, len(survivors), old=model.strategies, budget=budget,
        seed=seed, plan_cache=plan_cache)

    entry, reshard_s = _reshard_onto(model, survivors, strategies, mode,
                                     manager, degraded_reason=lost)

    report = RecoveryReport(
        mode=mode, lost=list(lost), surviving=len(survivors),
        strategies=strategies, step=int(model._step),
        replan_s=float(info.get("replan_s", 0.0)),
        reshard_s=reshard_s,
        total_s=time.perf_counter() - t_start,
        searched=bool(info.get("searched", False)),
        greedy_fallback=bool(info.get("greedy_fallback", False)),
        kind="recover",
        plan_cache_hit=bool(info.get("plan_cache_hit", False)),
        entry=entry)
    log_elastic.warning(
        "elastic recovery (%s): %d -> %d devices, replan %.0f ms "
        "(%s), reshard %.0f ms, resuming at step %d",
        mode, old_mesh.size, len(survivors), 1e3 * report.replan_s,
        "plan cache" if report.plan_cache_hit
        else ("searched" if report.searched else "greedy clamp"),
        1e3 * report.reshard_s, report.step)
    return report


def _canonical_device_order(devices) -> List:
    """Stable full-mesh device order: by device id when every device has
    one (the order ``jax.devices()`` enumerates), else by string. A
    shrink that lost the middle of the mesh followed by an expand must
    rebuild the SAME mesh a fresh job on the full device set would —
    the bit-identity contract is over device order too."""
    if all(getattr(d, "id", None) is not None for d in devices):
        return sorted(devices, key=lambda d: int(d.id))
    return sorted(devices, key=str)


def expand(model, returned: Sequence = (), mode: Optional[str] = None,
           manager=None, budget: Optional[int] = None,
           seed: int = 0, plan_cache=None) -> RecoveryReport:
    """Grow `model` back onto its current devices PLUS `returned` — the
    inverse of :func:`recover` (ROADMAP item 4's missing half: a
    shrunken mesh no longer stays shrunk forever).

    Steps: quiesce → un-clamp strategies for the grown count
    (``search.replan.expand_strategies``, warm-started from the
    remembered pre-shrink plan when one matches the target size;
    ``ClampError`` with op + reason when growth would violate row-shard
    quanta) → fresh factorized mesh over the grown set in canonical
    device order → recompile → reshard (from memory for ``inplace``,
    from the newest valid snapshot for ``resume``). The result is
    bit-identical to a fresh run on the large mesh from the same
    snapshot (tests pin it). Raises :class:`MeshReturned`-flavored
    ValueError on misuse (no returned devices, devices already in the
    mesh), ValueError on mode "off".
    """
    t_start = time.perf_counter()
    mode = _resolve_mode(model, mode, "expansion")
    budget = _resolve_budget(model, budget)
    if plan_cache is None:
        plan_cache = getattr(model, "_plan_cache", None)
    if model.mesh is None:
        raise ValueError("expand() needs a compiled model (no mesh)")

    old_mesh = model.mesh
    cur = list(old_mesh.devices.flat)
    cur_ids = {id(d) for d in cur} | {str(d) for d in cur}
    fresh = [d for d in returned
             if id(d) not in cur_ids and str(d) not in cur_ids]
    if not fresh:
        raise ValueError(
            "expand() needs at least one returned device that is not "
            "already part of the mesh (got "
            f"{[str(d) for d in returned] or 'none'})")
    if len(fresh) < len(list(returned)):
        log_elastic.warning(
            "%d returned device(s) were already in the mesh; growing by "
            "the remaining %d", len(list(returned)) - len(fresh),
            len(fresh))
    grown = _canonical_device_order(cur + fresh)

    # quiesce exactly like recover: nothing may scatter into state that
    # is about to reshard
    if hasattr(model, "_host_abandon"):
        model._host_abandon()

    # re-plan for the grown count: the remembered pre-shrink plan for
    # this exact size is the intent (round-trip restores the original
    # plan); otherwise the running plan un-clamps / re-searches
    from ..search.replan import expand_strategies
    orig = _recall_plan(model, len(grown))
    strategies, info = expand_strategies(
        model, len(grown), old=model.strategies, orig=orig,
        budget=budget, seed=seed, plan_cache=plan_cache)

    entry, reshard_s = _reshard_onto(model, grown, strategies, mode,
                                     manager)

    report = RecoveryReport(
        mode=mode, lost=[], surviving=len(grown),
        strategies=strategies, step=int(model._step),
        replan_s=float(info.get("replan_s", 0.0)),
        reshard_s=reshard_s,
        total_s=time.perf_counter() - t_start,
        searched=bool(info.get("searched", False)),
        greedy_fallback=bool(info.get("greedy_fallback", False)),
        kind="expand",
        plan_cache_hit=bool(info.get("plan_cache_hit", False)),
        entry=entry)
    log_elastic.warning(
        "elastic expansion (%s): %d -> %d devices (%s plan%s), replan "
        "%.0f ms, reshard %.0f ms, resuming at step %d",
        mode, old_mesh.size, len(grown),
        "remembered pre-shrink" if orig is not None else "un-clamped",
        " via plan cache" if report.plan_cache_hit else "",
        1e3 * report.replan_s, 1e3 * report.reshard_s, report.step)
    return report


def replace_placement(model, sketches=None, strategies=None,
                      budget: Optional[int] = None, seed: int = 0,
                      plan_cache=None) -> RecoveryReport:
    """Re-place `model` for DRIFTED traffic on its CURRENT devices — the
    third elastic verb (``serve/replace.py`` drives it per replica when
    the live id sketch diverges from the searched histogram).

    Same machinery as :func:`recover`/:func:`expand` with neither shrink
    nor growth: quiesce → re-search hot/cold placement warm-started from
    the running plan with `sketches` (the live id distribution) attached
    (``search.replan.replace_strategies`` — its plan-cache key carries a
    sketch digest so the pre-drift entry cannot satisfy it) → rebuild
    the mesh over the SAME device set → recompile → restore the gathered
    in-memory state. Always ``"inplace"`` — there is no lost device to
    resume around, and the caller is typically a serving engine whose
    params came from a snapshot watcher, not a manager.

    Callers that already searched (one search fanned out to N replicas)
    pass `strategies` to skip the per-replica re-search; `sketches` is
    still attached so the post-swap cost model and any later publish see
    the distribution this placement was searched with.
    """
    t_start = time.perf_counter()
    budget = _resolve_budget(model, budget)
    if plan_cache is None:
        plan_cache = getattr(model, "_plan_cache", None)
    if model.mesh is None:
        raise ValueError(
            "replace_placement() needs a compiled model (no mesh)")

    if hasattr(model, "_host_abandon"):
        model._host_abandon()

    devices = list(model.mesh.devices.flat)
    info: Dict[str, float] = {}
    if strategies is None:
        from ..search.replan import replace_strategies
        strategies, info = replace_strategies(
            model, sketches=sketches, old=model.strategies,
            ndev=len(devices), budget=budget, seed=seed,
            plan_cache=plan_cache)
    elif sketches:
        model.attach_id_histograms(sketches)

    entry, reshard_s = _reshard_onto(model, devices, strategies,
                                     "inplace", None)

    report = RecoveryReport(
        mode="inplace", lost=[], surviving=len(devices),
        strategies=strategies, step=int(model._step),
        replan_s=float(info.get("replan_s", 0.0)),
        reshard_s=reshard_s,
        total_s=time.perf_counter() - t_start,
        searched=bool(info.get("searched", False)),
        greedy_fallback=bool(info.get("greedy_fallback", False)),
        kind="replace",
        plan_cache_hit=bool(info.get("plan_cache_hit", False)),
        entry=entry)
    log_elastic.warning(
        "online re-placement: %d devices unchanged, replan %.0f ms "
        "(%s), reshard %.0f ms, step %d",
        len(devices), 1e3 * report.replan_s,
        "caller-searched" if not info else (
            "plan cache" if report.plan_cache_hit
            else ("searched" if report.searched else "greedy clamp")),
        1e3 * report.reshard_s, report.step)
    return report
