"""Strategy file load/save.

Parity with the reference strategy serialization (reference:
src/runtime/strategy.proto:5-23 — proto2 `Strategy{ops[]: name, device_type,
dims[], device_ids[], memory_types[]}`; load/save in
src/runtime/strategy.cc:96-172, keyed by hash of op name).

Two on-disk formats, selected by extension:

- `.json` (default): same field names as the proto schema (dims → partition
  degrees, mesh axes implied by order) — human-diffable.
- `.pb`: the reference's binary proto2 wire format, encoded/decoded by a
  hand-rolled codec below (schema: message Op {required string name = 1;
  required DeviceType device_type = 2; repeated int32 dims = 3; repeated
  int32 device_ids = 4; repeated MemoryType memory_types = 5;} wrapped in
  message Strategy {repeated Op ops = 1;}). This reads the reference's
  prebuilt strategy files (src/runtime/dlrm_strategy_*.pb) and writes files
  its proto2 parser accepts — goldens stay interoperable. DeviceType GPU(0)
  maps to "TPU" here; CPU(1) stays "CPU" (the hetero host-offload case).

Dim-order note: the reference stores dims in Legion coordinate order, where
the SAMPLE dim is LAST (Op::get_data_parallel_config sets
`dim[nDims-1] = num_parts`, model.cc:282-293; the generated DLRM strategies
write `dims = [1, gpu]` for data-parallel 2-D ops, dlrm_strategy.py). Our
ParallelConfig is sample-FIRST (pconfig.py), so the .pb codec reverses the
dims list on both load and save. JSON files are written sample-first.
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .pconfig import ParallelConfig, StrategyMap

# --- proto2 wire-format primitives ---------------------------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = v = 0
    while True:
        if i >= len(buf):
            raise ValueError("truncated varint")
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7
        if shift >= 70:
            raise ValueError("malformed varint")


# proto enum for the quantized-storage policy (field 9/10 below):
# 0 = unset (inherit the model default) — never written, so legacy
# files stay byte-identical
_QUANT_DTYPE_ENUM = {"": 0, "fp32": 1, "bf16": 2, "int8": 3, "fp8": 4}
_QUANT_DTYPE_NAME = {v: k for k, v in _QUANT_DTYPE_ENUM.items()}
_QUANT_UPDATE_ENUM = {"": 0, "master_weight": 1, "stochastic_rounding": 2}
_QUANT_UPDATE_NAME = {v: k for k, v in _QUANT_UPDATE_ENUM.items()}


def _encode_op(name: str, device_type: int, dims: List[int],
               device_ids: List[int],
               memory_types: List[int], param_dim: int = 1,
               hot_ppm: int = 0, exchange: int = 0,
               quant_dtype: int = 0, quant_update: int = 0,
               overlap: int = 0) -> bytes:
    msg = bytearray()
    nb = name.encode()
    msg += b"\x0a" + _varint(len(nb)) + nb          # 1: name (len-delim)
    msg += b"\x10" + _varint(device_type)           # 2: device_type varint
    for d in dims:                                  # 3: dims, unpacked
        msg += b"\x18" + _varint(d)
    for d in device_ids:                            # 4: device_ids
        msg += b"\x20" + _varint(d)
    for m in memory_types:                          # 5: memory_types
        msg += b"\x28" + _varint(m)
    if param_dim > 1:                               # 6: PARAM-axis degree
        # extension field: the reference's proto2 parser skips unknown
        # fields, so files stay readable by it; files without row
        # sharding stay byte-identical to the legacy encoding
        msg += b"\x30" + _varint(param_dim)
    if hot_ppm > 0:                                 # 7: hot rows, ppm
        # hybrid hot/cold placement fraction in parts-per-million (a
        # varint round-trips exactly; floats would need a fixed64)
        msg += b"\x38" + _varint(hot_ppm)
    if exchange > 0:                                # 8: exchange mode
        msg += b"\x40" + _varint(exchange)          # 1 = dedup
    if quant_dtype > 0:                             # 9: quantized storage
        # extension fields like 6-8: unknown to the reference's proto2
        # parser (skipped), omitted when unset so legacy files stay
        # byte-identical
        msg += b"\x48" + _varint(quant_dtype)
    if quant_update > 0:                            # 10: quant update rule
        msg += b"\x50" + _varint(quant_update)
    if overlap > 0:                                 # 11: pipelined exchange
        # extension field like 6-10: omitted when off, so legacy files
        # (and files without overlap) stay byte-identical
        msg += b"\x58" + _varint(overlap)
    return bytes(msg)


def _decode_message(buf: bytes):
    """Yield (field_number, wire_type, value) triples; packed repeated
    varints are handled by the caller."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            if i + ln > len(buf):
                raise ValueError("truncated length-delimited field")
            v = buf[i:i + ln]
            i += ln
        elif wt in (5, 1):
            ln = 4 if wt == 5 else 8
            if i + ln > len(buf):
                raise ValueError("truncated fixed-width field")
            v = buf[i:i + ln]
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _unpack_varints(payload: bytes) -> List[int]:
    out, i = [], 0
    while i < len(payload):
        v, i = _read_varint(payload, i)
        out.append(v)
    return out


def save_strategies_pb(path: str, strategies: StrategyMap) -> None:
    """Write the reference's binary format (reference
    save_strategies_to_file, src/runtime/strategy.cc:137-172)."""
    body = bytearray()
    for name, pc in sorted(strategies.items()):
        dt = 1 if pc.device_type == "CPU" else 0
        mts = [1 if m == "ZCM" else 0 for m in pc.memory_types]
        op = _encode_op(
            name, dt, list(reversed(pc.degrees)),
            list(pc.device_ids), mts,
            param_dim=getattr(pc, "param_degree", 1),
            hot_ppm=int(round(getattr(pc, "hot_fraction", 0.0) * 1e6)),
            exchange=1 if getattr(pc, "exchange",
                                  "dense") == "dedup" else 0,
            quant_dtype=_QUANT_DTYPE_ENUM[
                getattr(pc, "quant_dtype", "") or ""],
            quant_update=_QUANT_UPDATE_ENUM[
                getattr(pc, "quant_update", "") or ""],
            overlap=1 if getattr(pc, "overlap", False) else 0)
        body += b"\x0a" + _varint(len(op)) + op     # Strategy.ops = 1
    with open(path, "wb") as f:
        f.write(bytes(body))


def load_strategies_pb(path: str) -> StrategyMap:
    """Read the reference's binary format (reference
    load_strategies_from_file, src/runtime/strategy.cc:96-135)."""
    with open(path, "rb") as f:
        buf = f.read()
    try:
        return _decode_strategies(buf)
    except ValueError as e:
        raise ValueError(f"corrupt strategy file {path!r}: {e}") from None


def _decode_strategies(buf: bytes) -> StrategyMap:
    out: StrategyMap = {}
    for field, wt, v in _decode_message(buf):
        if field != 1 or wt != 2:
            continue
        name, dt, dims, dev_ids, mts, pd = "", 0, [], [], [], 1
        hot_ppm, exch, qdt, qup, ovl = 0, 0, 0, 0, 0
        for f2, wt2, v2 in _decode_message(v):
            if f2 == 1:
                name = v2.decode()
            elif f2 == 2:
                dt = v2
            elif f2 == 3:
                dims += _unpack_varints(v2) if wt2 == 2 else [v2]
            elif f2 == 4:
                dev_ids += _unpack_varints(v2) if wt2 == 2 else [v2]
            elif f2 == 5:
                mts += _unpack_varints(v2) if wt2 == 2 else [v2]
            elif f2 == 6:
                pd = v2                    # PARAM-axis (row-shard) degree
            elif f2 == 7:
                hot_ppm = v2               # hybrid hot fraction, ppm
            elif f2 == 8:
                exch = v2                  # exchange mode (1 = dedup)
            elif f2 == 9:
                qdt = v2                   # quantized storage dtype
            elif f2 == 10:
                qup = v2                   # quant update rule
            elif f2 == 11:
                ovl = v2                   # pipelined exchange (1 = on)
        if pd < 1:
            raise ValueError(
                f"op {name!r}: parameter-axis degree {pd} < 1")
        if not 0 <= hot_ppm < 1_000_000:
            raise ValueError(
                f"op {name!r}: hot fraction {hot_ppm} ppm out of "
                f"[0, 1e6)")
        if exch not in (0, 1):
            raise ValueError(
                f"op {name!r}: unknown exchange mode {exch}")
        if qdt not in _QUANT_DTYPE_NAME:
            raise ValueError(
                f"op {name!r}: unknown quant dtype enum {qdt}")
        if qup not in _QUANT_UPDATE_NAME:
            raise ValueError(
                f"op {name!r}: unknown quant update-rule enum {qup}")
        if ovl not in (0, 1):
            raise ValueError(
                f"op {name!r}: unknown overlap flag {ovl}")
        out[name] = ParallelConfig(
            tuple(reversed(dims)), device_type="CPU" if dt == 1 else "TPU",
            device_ids=tuple(dev_ids),
            memory_types=tuple("ZCM" if m == 1 else "FBM" for m in mts),
            param_degree=pd, hot_fraction=hot_ppm / 1e6,
            exchange="dedup" if exch == 1 else "dense",
            quant_dtype=_QUANT_DTYPE_NAME[qdt],
            quant_update=_QUANT_UPDATE_NAME[qup],
            overlap=bool(ovl))
    return out


# --- validation ------------------------------------------------------------

# the reference's shared generic keys (dlrm_strategy.py /
# dlrm_strategy_hetero.cc): "embedding{i}" per table plus one entry per
# op TYPE — legal in a strategy file even when no op carries the name
# verbatim (FFModel._resolve_generic_strategy_keys maps them)
_GENERIC_KEY_RE = re.compile(r"^(embedding\d+|embedding|linear|concat|"
                             r"mse_loss)$")

_VALID_DEVICE_TYPES = ("TPU", "CPU")
_VALID_MEMORY_TYPES = ("FBM", "ZCM")


class StrategyValidationError(ValueError):
    """A strategy file failed load-time validation. The message always
    names the file, the op, and the reason — the alternative is a
    downstream GSPMD/sharding error naming neither."""

    def __init__(self, path: str, op: str, reason: str):
        super().__init__(f"strategy file {path!r}, op {op!r}: {reason}")
        self.path = path
        self.op = op
        self.reason = reason


def validate_strategies(strategies: StrategyMap,
                        num_devices: Optional[int] = None,
                        axis_sizes: Optional[Sequence[int]] = None,
                        known_ops: Optional[Set[str]] = None,
                        path: str = "<memory>",
                        row_shard_ops: Optional[Set[str]] = None
                        ) -> StrategyMap:
    """Structural + mesh validation of a loaded strategy map.

    Always checked: op names are non-empty, degrees are a non-empty
    tuple of positive ints (ParallelConfig enforces positivity at
    construction), device/memory types are from the schema's
    vocabulary, and the skew-aware placement fields are coherent
    (hot_fraction / exchange="dedup" refine the ROW-SHARDED exchange,
    so both require param_degree > 1). With
    ``num_devices``/``axis_sizes``: each op's degrees must be jointly
    expressible over the factorized target mesh
    (``parallel.sharding.assign_indices`` — the exact feasibility rule
    compile() uses). With ``known_ops``: every op must name a model op
    (or a reference-style generic key like ``embedding3``/``linear``).
    With ``row_shard_ops`` (names of the model's row-shardable
    embedding ops): hot_fraction/exchange on any OTHER op is rejected —
    a hot/cold placement on a Linear is a corrupt or mis-keyed file,
    not a strategy.

    Returns the map unchanged so call sites can chain it; raises
    :class:`StrategyValidationError` (a ``ValueError``) with
    file + op + reason otherwise.
    """
    if axis_sizes is None and num_devices is not None:
        from .mesh import structural_axis_sizes
        axis_sizes = structural_axis_sizes(int(num_devices))
    for name, pc in strategies.items():
        frac = getattr(pc, "hot_fraction", 0.0)
        exch = getattr(pc, "exchange", "dense")
        pd0 = getattr(pc, "param_degree", 1)
        if frac > 0 and pd0 <= 1:
            raise StrategyValidationError(
                path, str(name),
                f"hot_fraction={frac:g} without row sharding "
                f"(param_degree must be > 1 — the hybrid placement "
                f"splits a row-sharded table into a replicated hot "
                f"head and a sharded cold tail)")
        if exch != "dense" and pd0 <= 1:
            raise StrategyValidationError(
                path, str(name),
                f"exchange={exch!r} without row sharding "
                f"(param_degree must be > 1 — there is no exchange "
                f"to dedup on a replicated table)")
        ovl = getattr(pc, "overlap", False)
        if ovl and pd0 <= 1:
            raise StrategyValidationError(
                path, str(name),
                "overlap=True without row sharding (param_degree must "
                "be > 1 — overlap pipelines the row-shard exchange, "
                "and a replicated table has no exchange to overlap)")
        if (frac > 0 or exch != "dense" or ovl) \
                and row_shard_ops is not None \
                and name not in row_shard_ops \
                and not _GENERIC_KEY_RE.match(str(name)):
            raise StrategyValidationError(
                path, str(name),
                f"hot_fraction/exchange/overlap set on an op with no "
                f"row-shard support (not one of the model's embedding "
                f"ops: {sorted(row_shard_ops)[:8]}...)")
        if getattr(pc, "quant_dtype", "") and row_shard_ops is not None \
                and name not in row_shard_ops \
                and not _GENERIC_KEY_RE.match(str(name)):
            # quantized row storage is a TABLE policy; on a Linear it is
            # a corrupt or mis-keyed file, not a strategy
            raise StrategyValidationError(
                path, str(name),
                f"quant_dtype={pc.quant_dtype!r} set on an op with no "
                f"embedding-table storage (not one of the model's "
                f"embedding ops: {sorted(row_shard_ops)[:8]}...)")
        if not name or not isinstance(name, str):
            raise StrategyValidationError(
                path, repr(name), "empty/non-string op name")
        if not pc.degrees:
            raise StrategyValidationError(
                path, name, "no partition degrees (empty dims)")
        if len(pc.degrees) > 6:
            raise StrategyValidationError(
                path, name,
                f"{len(pc.degrees)} partition dims — more than any "
                f"supported tensor rank (corrupt dims field?)")
        if pc.device_type not in _VALID_DEVICE_TYPES:
            raise StrategyValidationError(
                path, name,
                f"device_type {pc.device_type!r} not in "
                f"{_VALID_DEVICE_TYPES}")
        for m in pc.memory_types:
            if m not in _VALID_MEMORY_TYPES:
                raise StrategyValidationError(
                    path, name,
                    f"memory_type {m!r} not in {_VALID_MEMORY_TYPES}")
        if axis_sizes is not None:
            from .sharding import assignable
            ndev = 1
            for a in axis_sizes:
                ndev *= a
            if pc.num_parts > ndev:
                raise StrategyValidationError(
                    path, name,
                    f"degrees {pc.degrees} need {pc.num_parts} parts "
                    f"but the target mesh has {ndev} device(s)")
            if not assignable(pc.degrees, axis_sizes):
                raise StrategyValidationError(
                    path, name,
                    f"degrees {pc.degrees} do not factorize the target "
                    f"mesh axes {list(axis_sizes)} (no contiguous axis "
                    f"assignment multiplies to each degree)")
            pd = getattr(pc, "param_degree", 1)
            if pd > 1:
                if pd > ndev:
                    raise StrategyValidationError(
                        path, name,
                        f"parameter-axis degree {pd} (row shards) "
                        f"exceeds the target mesh's {ndev} device(s)")
                if not assignable((pd,), axis_sizes):
                    raise StrategyValidationError(
                        path, name,
                        f"parameter-axis degree {pd} does not factorize "
                        f"the target mesh axes {list(axis_sizes)} — row "
                        f"shards need a contiguous axis run multiplying "
                        f"to the degree")
        if known_ops is not None and name not in known_ops \
                and not _GENERIC_KEY_RE.match(name):
            preview = sorted(known_ops)[:8]
            raise StrategyValidationError(
                path, name,
                f"references no op of this model (known ops include "
                f"{preview}...) and is not a generic key "
                f"(embedding<i>/linear/concat/mse_loss)")
    return strategies


# --- public API ------------------------------------------------------------


def save_strategies(path: str, strategies: StrategyMap) -> None:
    if path.endswith(".pb"):
        save_strategies_pb(path, strategies)
        return
    ops = []
    for name, pc in sorted(strategies.items()):
        entry = {"name": name,
                 "device_type": pc.device_type,
                 "dims": list(pc.degrees),
                 "device_ids": list(pc.device_ids),
                 "memory_types": list(pc.memory_types)}
        if getattr(pc, "param_degree", 1) > 1:
            # row/PARAM-axis shard degree (omitted when 1 so legacy
            # files stay diff-identical)
            entry["param_dim"] = int(pc.param_degree)
        if getattr(pc, "hot_fraction", 0.0) > 0.0:
            entry["hot_frac"] = float(pc.hot_fraction)
        if getattr(pc, "exchange", "dense") != "dense":
            entry["exchange"] = pc.exchange
        if getattr(pc, "quant_dtype", ""):
            # quantized-storage policy (omitted when unset so legacy
            # files stay diff-identical)
            entry["quant_dtype"] = pc.quant_dtype
        if getattr(pc, "quant_update", ""):
            entry["quant_update"] = pc.quant_update
        if getattr(pc, "overlap", False):
            # pipelined row-shard exchange (omitted when off so legacy
            # files stay diff-identical)
            entry["overlap"] = True
        ops.append(entry)
    doc = {"ops": ops}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def load_strategies(path: str, num_devices: Optional[int] = None,
                    known_ops: Optional[Set[str]] = None,
                    row_shard_ops: Optional[Set[str]] = None
                    ) -> StrategyMap:
    """Load + validate a strategy file. Structural validation always
    runs; pass ``num_devices`` to also require every op's degrees to
    factorize the target mesh, and ``known_ops`` to require every entry
    to reference a real (or generic-keyed) op — malformed files fail
    HERE with file + op + reason instead of as a downstream GSPMD
    error."""
    if path.endswith(".pb"):
        out = load_strategies_pb(path)
    else:
        with open(path) as f:
            doc = json.load(f)
        out = {}
        for entry in doc["ops"]:
            try:
                out[entry["name"]] = ParallelConfig(
                    tuple(entry["dims"]),
                    device_type=entry.get("device_type", "TPU"),
                    device_ids=tuple(entry.get("device_ids", ())),
                    memory_types=tuple(entry.get("memory_types", ())),
                    param_degree=int(entry.get("param_dim", 1)),
                    hot_fraction=float(entry.get("hot_frac", 0.0)),
                    exchange=str(entry.get("exchange", "dense")),
                    quant_dtype=str(entry.get("quant_dtype", "")),
                    quant_update=str(entry.get("quant_update", "")),
                    overlap=bool(entry.get("overlap", False)))
            except (KeyError, TypeError, ValueError) as e:
                raise StrategyValidationError(
                    path, str(entry.get("name", "?")),
                    f"malformed entry: {e}") from None
    return validate_strategies(out, num_devices=num_devices,
                               known_ops=known_ops, path=path,
                               row_shard_ops=row_shard_ops)
