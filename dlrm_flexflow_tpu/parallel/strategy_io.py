"""Strategy file load/save.

Parity with the reference strategy serialization (reference:
src/runtime/strategy.proto:5-23 — proto2 `Strategy{ops[]: name, device_type,
dims[], device_ids[], memory_types[]}`; load/save in
src/runtime/strategy.cc:96-172, keyed by hash of op name).

Format here is JSON with the same field names as the proto schema (dims →
partition degrees, mesh axes implied by order), so strategies remain
human-diffable and round-trip exactly. `.pb`-style binary compat is not
needed on TPU — the reference's prebuilt .pb files encode GPU device ids
that have no meaning here.
"""

from __future__ import annotations

import json
from typing import Dict

from .pconfig import ParallelConfig, StrategyMap


def save_strategies(path: str, strategies: StrategyMap) -> None:
    doc = {"ops": [
        {"name": name,
         "device_type": pc.device_type,
         "dims": list(pc.degrees),
         "device_ids": list(pc.device_ids)}
        for name, pc in sorted(strategies.items())]}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def load_strategies(path: str) -> StrategyMap:
    with open(path) as f:
        doc = json.load(f)
    out: StrategyMap = {}
    for entry in doc["ops"]:
        out[entry["name"]] = ParallelConfig(
            tuple(entry["dims"]),
            device_type=entry.get("device_type", "TPU"),
            device_ids=tuple(entry.get("device_ids", ())))
    return out
