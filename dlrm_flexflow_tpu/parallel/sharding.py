"""ParallelConfig → GSPMD sharding translation.

This is the TPU replacement for the reference's mapper + partition machinery:
`create_disjoint_partition` equal-block partitions a tensor by the op's
ParallelConfig (reference: src/runtime/model.cc:555-592) and
`FFMapper::slice_task` routes each part to its device (mapper.cc:33-97).
Here the same intent compiles to a `NamedSharding` whose PartitionSpec
assigns each partitioned tensor dim a tuple of factorized mesh axes
(parallel/mesh.py); XLA/GSPMD then materializes the placement and inserts
any op-to-op resharding collectives that Legion's implicit DMA used to do
(reference: linear.cu:266-292 re-partitions inputs between ops).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec


def assignable(degrees: Sequence[int], axis_sizes: Sequence[int]) -> bool:
    """True when each degree maps to a consecutive run of unused axes in
    order — the pure-structure form of AxisAssigner.assign, usable before
    a jax Mesh exists (the search's fallback mesh factorizes num_devices
    exactly like parallel.mesh.make_mesh)."""
    cursor = 0
    for deg in degrees:
        if deg == 1:
            continue
        start = cursor
        while start < len(axis_sizes):
            p, j = 1, start
            while j < len(axis_sizes) and p < deg:
                p *= axis_sizes[j]
                j += 1
            if p == deg:
                cursor = j
                break
            start += 1
        else:
            return False
    return True


class AxisAssigner:
    """Maps partition degrees to tuples of mesh axes, consuming axes in mesh
    order so equal degrees on the same dim index always get the same axes."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.axis_names = list(mesh.axis_names)
        self.axis_sizes = [mesh.shape[a] for a in self.axis_names]

    def feasible_degrees(self) -> List[int]:
        """All degrees expressible as a product of a prefix-contiguous run of
        axes starting anywhere (what assign() below accepts), plus 1."""
        out = {1}
        n = len(self.axis_sizes)
        for i in range(n):
            p = 1
            for j in range(i, n):
                p *= self.axis_sizes[j]
                out.add(p)
        return sorted(out)

    def assign(self, degrees: Sequence[int]) -> List[Tuple[str, ...]]:
        """Assign each dim's degree a tuple of consecutive unused axes.

        Raises ValueError when a degree cannot be formed from the remaining
        axes (search proposals are filtered through feasible_degrees()).
        """
        result: List[Tuple[str, ...]] = []
        cursor = 0
        for deg in degrees:
            if deg == 1:
                result.append(())
                continue
            # find a consecutive run starting at or after cursor whose sizes
            # multiply to deg
            start = cursor
            while start < len(self.axis_sizes):
                p, j = 1, start
                while j < len(self.axis_sizes) and p < deg:
                    p *= self.axis_sizes[j]
                    j += 1
                if p == deg:
                    result.append(tuple(self.axis_names[start:j]))
                    cursor = j
                    break
                start += 1
            else:
                raise ValueError(
                    f"degree {deg} not expressible over mesh axes "
                    f"{list(zip(self.axis_names, self.axis_sizes))} "
                    f"(remaining from {cursor})")
        return result

    @staticmethod
    def axes_to_spec(axes_per_dim) -> PartitionSpec:
        """Normalize per-dim axis tuples to a canonical PartitionSpec:
        None for unsharded dims, scalar for singleton tuples, trailing
        Nones stripped."""
        norm = []
        for t in axes_per_dim:
            if not t:
                norm.append(None)
            elif len(t) == 1:
                norm.append(t[0])
            else:
                norm.append(tuple(t))
        while norm and norm[-1] is None:
            norm.pop()
        return PartitionSpec(*norm)

    def spec(self, degrees: Sequence[int]) -> PartitionSpec:
        return self.axes_to_spec(self.assign(degrees))

    def sharding(self, degrees: Sequence[int]) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(degrees))
