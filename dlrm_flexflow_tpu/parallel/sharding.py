"""ParallelConfig → GSPMD sharding translation.

This is the TPU replacement for the reference's mapper + partition machinery:
`create_disjoint_partition` equal-block partitions a tensor by the op's
ParallelConfig (reference: src/runtime/model.cc:555-592) and
`FFMapper::slice_task` routes each part to its device (mapper.cc:33-97).
Here the same intent compiles to a `NamedSharding` whose PartitionSpec
assigns each partitioned tensor dim a tuple of factorized mesh axes
(parallel/mesh.py); XLA/GSPMD then materializes the placement and inserts
any op-to-op resharding collectives that Legion's implicit DMA used to do
(reference: linear.cu:266-292 re-partitions inputs between ops).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec


def feasible_degrees_for(axis_sizes: Sequence[int]) -> List[int]:
    """All degrees expressible as a product of a contiguous run of axes
    (what assign_indices accepts), plus 1 — the pure-structure form of
    AxisAssigner.feasible_degrees, usable for a TARGET device count with
    no jax Mesh (offline strategy search from a smaller host)."""
    out = {1}
    n = len(axis_sizes)
    for i in range(n):
        p = 1
        for j in range(i, n):
            p *= axis_sizes[j]
            out.add(p)
    return sorted(out)


def assign_indices(degrees: Sequence[int], axis_sizes: Sequence[int]
                   ) -> "Optional[List[Tuple[int, ...]]]":
    """THE axis-consumption algorithm, by index: each degree takes a
    consecutive run of unused axes (searching forward from the last
    consumed one) whose sizes multiply to it; None when not jointly
    assignable. AxisAssigner.assign, the search's structural feasibility
    check, and the simulator's collective pricing all defer here so they
    can never disagree about which axes a config's collectives ride."""
    result: List[Tuple[int, ...]] = []
    cursor = 0
    for deg in degrees:
        if deg == 1:
            result.append(())
            continue
        start = cursor
        while start < len(axis_sizes):
            p, j = 1, start
            while j < len(axis_sizes) and p < deg:
                p *= axis_sizes[j]
                j += 1
            if p == deg:
                result.append(tuple(range(start, j)))
                cursor = j
                break
            start += 1
        else:
            return None
    return result


def assignable(degrees: Sequence[int], axis_sizes: Sequence[int]) -> bool:
    """True when assign_indices succeeds — usable before a jax Mesh exists
    (the search's fallback mesh factorizes num_devices exactly like
    parallel.mesh.make_mesh)."""
    return assign_indices(degrees, axis_sizes) is not None


def clamp_degrees(degrees: Sequence[int],
                  axis_sizes: Sequence[int]) -> Tuple[int, ...]:
    """Project a degree tuple onto a (typically smaller) factorized mesh
    — the per-op core of elastic re-planning (search/replan.py).

    Each degree drops to the largest feasible degree not exceeding it;
    if the result is not JOINTLY assignable (axes exhausted), parallelism
    is shed from the LAST dims first — inner model-parallel dims are the
    ones that cost collectives, while the leading sample dim is the
    cheapest parallelism to keep. Always returns a jointly-assignable
    tuple (all-1s in the worst case)."""
    feas = feasible_degrees_for(axis_sizes)
    degs = [max((f for f in feas if f <= d), default=1) for d in degrees]
    for i in range(len(degs) - 1, -1, -1):
        if assignable(degs, axis_sizes):
            break
        degs[i] = 1
    if not assignable(degs, axis_sizes):
        degs = [1] * len(degs)
    return tuple(degs)


def clamp_param_degree(param_degree: int,
                       axis_sizes: Sequence[int],
                       rows: Optional[int] = None,
                       pack: int = 1) -> int:
    """Project a PARAM-axis (row-shard) degree onto a factorized mesh:
    the largest feasible degree not exceeding the requested one. The
    per-op core of elastic re-planning for row-sharded embedding tables
    — a surviving 4-device mesh cannot hold 8 row shards, so the tables
    reshard 4-way rather than silently replicating.

    With ``rows``/``pack`` the result must also equal-block the table
    (rows divisible by degree x lane pack) — the same constraint
    configure_row_shard enforces at compile time, so the clamp can never
    emit a degree that would silently replicate there. Returns 1 when
    no degree > 1 survives; the CALLER decides whether replication is
    acceptable (search/replan.clamp_strategies rejects with op+reason
    when it is not)."""
    if param_degree <= 1:
        return 1
    feas = feasible_degrees_for(axis_sizes)
    return max((f for f in feas
                if f <= param_degree
                and (rows is None or rows % (f * max(pack, 1)) == 0)),
               default=1)


def param_axis_indices(param_degree: int,
                       axis_sizes: Sequence[int]
                       ) -> Optional[Tuple[int, ...]]:
    """Mesh-axis indices the PARAM (row-shard) degree consumes: the same
    leading-run consumption as assign_indices for a single degree, so
    the cost model prices the all-to-all on exactly the axes compile()
    row-shards over. None when the degree does not factorize the mesh."""
    idx = assign_indices((param_degree,), axis_sizes)
    return idx[0] if idx is not None else None


class AxisAssigner:
    """Maps partition degrees to tuples of mesh axes, consuming axes in mesh
    order so equal degrees on the same dim index always get the same axes."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.axis_names = list(mesh.axis_names)
        self.axis_sizes = [mesh.shape[a] for a in self.axis_names]

    def feasible_degrees(self) -> List[int]:
        """All degrees expressible as a product of a prefix-contiguous run of
        axes starting anywhere (what assign() below accepts), plus 1."""
        return feasible_degrees_for(self.axis_sizes)

    def assign(self, degrees: Sequence[int]) -> List[Tuple[str, ...]]:
        """Assign each dim's degree a tuple of consecutive unused axes
        (assign_indices, mapped to axis names).

        Raises ValueError when a degree cannot be formed from the remaining
        axes (search proposals are filtered through feasible_degrees()).
        """
        idx = assign_indices(degrees, self.axis_sizes)
        if idx is None:
            raise ValueError(
                f"degrees {tuple(degrees)} not jointly expressible over "
                f"mesh axes {list(zip(self.axis_names, self.axis_sizes))}")
        return [tuple(self.axis_names[i] for i in t) for t in idx]

    @staticmethod
    def axes_to_spec(axes_per_dim) -> PartitionSpec:
        """Normalize per-dim axis tuples to a canonical PartitionSpec:
        None for unsharded dims, scalar for singleton tuples, trailing
        Nones stripped."""
        norm = []
        for t in axes_per_dim:
            if not t:
                norm.append(None)
            elif len(t) == 1:
                norm.append(t[0])
            else:
                norm.append(tuple(t))
        while norm and norm[-1] is None:
            norm.pop()
        return PartitionSpec(*norm)

    def spec(self, degrees: Sequence[int]) -> PartitionSpec:
        return self.axes_to_spec(self.assign(degrees))

    def sharding(self, degrees: Sequence[int]) -> NamedSharding:
        return NamedSharding(self.mesh, self.spec(degrees))
