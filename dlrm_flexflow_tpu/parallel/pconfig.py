"""Per-operator parallelization configs (the SOAP search space on TPU).

Parity with the reference `ParallelConfig {device_type, nDims, dim[],
device_ids[]}` (reference: include/config.h:41-50) and the per-op strategy
map keyed by a hash of the op name (reference: src/runtime/strategy.cc:23-94).

TPU-native redesign: the reference maps every *point task* of an op's index
launch to an explicit GPU id (MPMD placement via the Legion mapper,
src/mapper/mapper.cc:33-97). Under GSPMD the whole program is SPMD over a
`jax.sharding.Mesh`; a ParallelConfig here records the partition degree of
each tensor dimension of the op's output (sample dim first — same dim order
the reference uses once its reversed Legion coordinates are normalized), and
compile() lowers it to a `PartitionSpec` over factorized mesh axes
(parallel/sharding.py). `device_ids` are retained only for strategy-file
round-tripping; XLA owns placement.

`device_type == "CPU"` marks host-offloaded ops (the reference's hetero
strategies put embeddings on CPUs, dlrm_strategy_hetero.cc:28-36).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Tuple

DEVICE_TPU = "TPU"   # reference: DeviceType::GPU (config.h:41)
DEVICE_CPU = "CPU"   # reference: DeviceType::CPU — host offload


@dataclass(frozen=True)
class ParallelConfig:
    """Partition degrees per output-tensor dim; degrees[0] is the sample dim
    for activations. Product of degrees = number of parallel parts."""

    degrees: Tuple[int, ...]
    device_type: str = DEVICE_TPU
    device_ids: Tuple[int, ...] = field(default=())
    # per-part memory placement (reference strategy.proto:11-14: FBM =
    # framebuffer/HBM, ZCM = zero-copy host memory); round-tripped through
    # strategy files and consulted by the hetero host-offload path
    memory_types: Tuple[str, ...] = field(default=())
    # PARAMETER-axis partition degree: how many row shards the op's
    # parameter (an embedding table's row space) splits into, independent
    # of the output degrees above. degrees describe the OUTPUT tensor and
    # cannot express "rows of the table sharded, output data-parallel" —
    # the pod-scale DLRM shape (Naumov 2019 / ZionEX 2022: row-sharded
    # tables + all-to-all lookup exchange). 1 = replicated/whole rows
    # (legacy behavior for every op that ignores it).
    param_degree: int = 1
    # skew-aware refinements of the row-sharded exchange (param_degree
    # > 1 only; both default to the legacy behavior so files and
    # strategies without them are unchanged):
    # - exchange "dedup": sort→unique the lookup ids before the
    #   all-to-all and pre-accumulate gradient rows per unique id before
    #   the return exchange, so exchanged bytes scale with DISTINCT ids
    #   rather than batch size (Neo/ZionEX dedup-before-exchange).
    # - hot_fraction f in (0, 1): frequency-aware hybrid placement — the
    #   top f of each table's rows (the low-numbered, hot ids) are
    #   REPLICATED on every device (local lookups, allreduce-style
    #   lockstep updates) while the cold tail stays row-sharded (FAE,
    #   Adnan 2021). 0 = every row routed.
    exchange: str = "dense"
    hot_fraction: float = 0.0
    # per-table quantized STORAGE policy (quant/policy.py): element
    # dtype of the stored rows ("" = inherit the model-wide
    # FFConfig.emb_dtype default; "fp32"/"bf16"/"int8"/"fp8" pin it per
    # table) and the update rule ("master_weight" keeps an exact fp32
    # master beside the optimizer state; "stochastic_rounding" re-
    # quantizes after every update). int8/fp8 rows carry one fp32 scale
    # per row; every byte-accounting site resolves sizes through
    # quant.effective_policy so search, shardcheck, and serving agree.
    quant_dtype: str = ""
    quant_update: str = ""
    # pipelined (double-buffered) row-shard exchange (param_degree > 1
    # only): the lookup/row/gradient all-to-alls decompose into chunked
    # ppermute/collective rounds so XLA's scheduler can hide them under
    # independent dense compute (the bottom MLP), instead of the fused
    # blocking all-to-all that serializes with the step. Bit-identical
    # to the serial exchange — the same per-peer blocks arrive, the
    # pipeline drains inside every step dispatch (no staleness). False
    # keeps the legacy fused collective.
    overlap: bool = False

    def __post_init__(self):
        object.__setattr__(self, "degrees", tuple(int(d) for d in self.degrees))
        for d in self.degrees:
            if d < 1:
                raise ValueError(f"invalid partition degree {d}")
        object.__setattr__(self, "param_degree", int(self.param_degree))
        if self.param_degree < 1:
            raise ValueError(
                f"invalid parameter-axis degree {self.param_degree}")
        if self.exchange not in ("dense", "dedup"):
            raise ValueError(
                f"invalid exchange mode {self.exchange!r} "
                f"(expected 'dense' or 'dedup')")
        object.__setattr__(self, "hot_fraction", float(self.hot_fraction))
        if not 0.0 <= self.hot_fraction < 1.0:
            raise ValueError(
                f"invalid hot_fraction {self.hot_fraction} "
                f"(expected 0 <= f < 1)")
        # vocab literals kept in sync with quant.policy.DTYPES /
        # UPDATE_RULES (pconfig stays import-cycle-free; the quant tests
        # pin the agreement)
        if self.quant_dtype not in ("", "fp32", "bf16", "int8", "fp8"):
            raise ValueError(
                f"invalid quant_dtype {self.quant_dtype!r} (expected "
                f"'', 'fp32', 'bf16', 'int8', or 'fp8')")
        if self.quant_update not in ("", "master_weight",
                                     "stochastic_rounding"):
            raise ValueError(
                f"invalid quant_update {self.quant_update!r} (expected "
                f"'', 'master_weight', or 'stochastic_rounding')")
        if self.quant_update and not self.quant_dtype:
            raise ValueError(
                f"quant_update={self.quant_update!r} without a "
                f"quant_dtype — the update rule refines a storage "
                f"dtype, it cannot stand alone")
        if not isinstance(self.overlap, (bool, int)):
            raise ValueError(
                f"invalid overlap flag {self.overlap!r} (expected a "
                f"bool)")
        object.__setattr__(self, "overlap", bool(self.overlap))

    @property
    def num_parts(self) -> int:
        n = 1
        for d in self.degrees:
            n *= d
        return n

    @staticmethod
    def data_parallel(ndims: int, num_devices: int) -> "ParallelConfig":
        """Reference Op::get_data_parallel_config (model.cc:282-293): all
        devices along the sample dim, every other dim unpartitioned."""
        degrees = [1] * ndims
        degrees[0] = num_devices
        return ParallelConfig(tuple(degrees),
                              device_ids=tuple(range(num_devices)))

    @staticmethod
    def replicated(ndims: int) -> "ParallelConfig":
        return ParallelConfig((1,) * ndims)


# A strategy is a map from op name ("<Type>_<guid>" or user name — the same
# key scheme as the reference, where op->name seeds the MappingTagID hash,
# strategy.cc:23-26) to its ParallelConfig.
StrategyMap = Dict[str, ParallelConfig]
