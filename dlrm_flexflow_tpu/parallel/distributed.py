"""Multi-host (multi-node) runtime: process init, hybrid ICI/DCN meshes,
host-local → global batch assembly.

Parity with the reference's multi-node stack (reference: GASNet under
Realm for inter-node transport, README.md:18-20; Legion control replication
+ DataParallelShardingFunctor routing index-task points across nodes,
model.cc:1384-1409; `--nodes` flag, model.cc:1366-1370; Summit launch
scripts examples/cpp/DLRM/run_summit*.sh).

TPU-native redesign: every host runs the SAME SPMD program
(jax.distributed.initialize + one global jax.sharding.Mesh over all
chips); in-slice traffic rides ICI, cross-slice traffic rides DCN. The
mesh puts the DCN (slice) axis FIRST so degree assignment
(parallel/sharding.py) consumes ICI axes for high-bandwidth inner
shardings and only spills onto the DCN axis for the outermost (data)
dim — the layout "How to Scale Your Model" prescribes for multi-slice.
Per-host input pipelines feed host-local shards that
`global_batch_from_host_local` assembles into one global array per input
(the analog of the reference's per-node zero-copy dataset residency +
per-point-task scatter, dlrm.cc:384-589).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import _prime_factors


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Initialize the multi-host runtime (reference: GASNet bootstrap via
    mpirun/jsrun in run_summit.sh). On Cloud TPU pods all arguments are
    auto-detected; elsewhere read the env (COORDINATOR_ADDRESS,
    NUM_PROCESSES, PROCESS_ID) or pass explicitly. No-op if already
    initialized or single-process."""
    # NB: must not touch any backend-initializing API (even
    # jax.process_count()) before jax.distributed.initialize
    try:
        from jax._src.distributed import global_state
        if global_state.client is not None:
            return  # already initialized
    except ImportError:
        pass
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        # single host, or TPU pod with full auto-detection
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError) as e:
            # could be "not a distributed environment" — but could also be
            # a genuine pod-bootstrap failure, which would silently
            # degrade to N independent single-host jobs. Surface it.
            import warnings
            warnings.warn(
                f"jax.distributed.initialize() auto-detection failed "
                f"({e}); continuing single-process. If this is a "
                f"multi-host launch, set COORDINATOR_ADDRESS/"
                f"NUM_PROCESSES/PROCESS_ID explicitly.")
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _slice_groups(devices: Sequence) -> Dict[int, list]:
    """Group devices by slice (DCN domain). TPU devices expose
    slice_index; hosts without it fall back to process_index; flat
    single-group otherwise."""
    groups: Dict[int, list] = {}
    for d in devices:
        key = getattr(d, "slice_index", None)
        if key is None:
            key = getattr(d, "process_index", 0)
        groups.setdefault(key, []).append(d)
    return groups


def make_multihost_mesh(devices: Optional[Sequence] = None,
                        num_slices: Optional[int] = None) -> Mesh:
    """Global mesh with the DCN (slice) axis first, factorized ICI axes
    after: axes ("dcn", "f0", "f1", ...).

    `num_slices` overrides slice detection (used for CPU-mesh testing
    where devices carry no slice_index; the virtual slice is the leading
    axis). With one slice this degenerates to parallel.mesh.make_mesh's
    layout plus a size-1 "dcn" axis, so strategies written against the
    multi-host mesh also compile single-slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_slices is None:
        groups = _slice_groups(devices)
        num_slices = len(groups)
        # stable order: by slice key, then device order within
        devices = [d for k in sorted(groups) for d in groups[k]]
    n = len(devices)
    if n % num_slices != 0:
        raise ValueError(f"{n} devices do not split into {num_slices} "
                         f"equal slices")
    per_slice = n // num_slices
    factors = sorted(_prime_factors(per_slice), reverse=True) or [1]
    names = ("dcn",) + tuple(f"f{i}" for i in range(len(factors)))
    arr = np.array(devices).reshape((num_slices,) + tuple(factors))
    return Mesh(arr, names)


def global_batch_from_host_local(batch: Dict[str, np.ndarray], mesh: Mesh,
                                 batch_axes: Optional[tuple] = None
                                 ) -> Dict[str, jax.Array]:
    """Assemble per-host shards into global, batch-sharded device arrays.

    Each process passes ITS slice of the global batch (global_batch =
    process_count × local_batch, concatenated in process order); returns
    arrays sharded over all mesh axes on dim 0. Works unchanged in
    single-process runs (where it equals a sharded device_put)."""
    axes = batch_axes if batch_axes is not None else tuple(mesh.axis_names)
    sharding = NamedSharding(mesh, PartitionSpec(axes))
    out = {}
    for name, local in batch.items():
        out[name] = jax.make_array_from_process_local_data(sharding, local)
    return out
