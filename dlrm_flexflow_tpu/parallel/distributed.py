"""Multi-host (multi-node) runtime: process init, hybrid ICI/DCN meshes,
host-local → global batch assembly.

Parity with the reference's multi-node stack (reference: GASNet under
Realm for inter-node transport, README.md:18-20; Legion control replication
+ DataParallelShardingFunctor routing index-task points across nodes,
model.cc:1384-1409; `--nodes` flag, model.cc:1366-1370; Summit launch
scripts examples/cpp/DLRM/run_summit*.sh).

TPU-native redesign: every host runs the SAME SPMD program
(jax.distributed.initialize + one global jax.sharding.Mesh over all
chips); in-slice traffic rides ICI, cross-slice traffic rides DCN. The
mesh puts the DCN (slice) axis FIRST so degree assignment
(parallel/sharding.py) consumes ICI axes for high-bandwidth inner
shardings and only spills onto the DCN axis for the outermost (data)
dim — the layout "How to Scale Your Model" prescribes for multi-slice.
Per-host input pipelines feed host-local shards that
`global_batch_from_host_local` assembles into one global array per input
(the analog of the reference's per-node zero-copy dataset residency +
per-point-task scatter, dlrm.cc:384-589).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import _prime_factors
from ..utils.logging import get_logger


def _env_int(key: str) -> int:
    """Strict env-var int: a malformed value names its variable instead
    of raising a bare ValueError frames away (flexcheck FLX401)."""
    raw = os.environ[key]
    try:
        return int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{key}={raw!r}: expected an integer") from None

log_dist = get_logger("distributed")


class MeshDegraded(RuntimeError):
    """The device mesh lost participants (preempted host, dead chip,
    stalled collective past its deadline).

    This is the TYPED surface of topology change: anything that used to
    hang (a collective waiting on a dead peer) or kill the job (a device
    enumeration shrinking mid-run) raises this instead, carrying enough
    structure for ``parallel.elastic.recover`` to re-plan onto the
    survivors. ``lost`` / ``surviving`` are device (or host-id) lists;
    either may be empty when the detection path only knows counts.
    """

    def __init__(self, reason: str, lost: Sequence = (),
                 surviving: Optional[Sequence] = None,
                 report=None):
        lost = list(lost)
        msg = f"mesh degraded: {reason}"
        if lost:
            msg += f" (lost {len(lost)}: {[str(d) for d in lost]})"
        super().__init__(msg)
        self.reason = reason
        self.lost = lost
        self.surviving = list(surviving) if surviving is not None else None
        self.report = report   # optional utils.watchdog.StallReport


class MeshReturned(RuntimeError):
    """Lost capacity came BACK (a preempted host re-admitted, a repaired
    chip re-enumerated). The typed inverse of :class:`MeshDegraded`:
    ``parallel.elastic.expand`` catches this and re-plans onto the grown
    device set instead of leaving a shrunken mesh shrunk forever.
    ``returned`` lists the device (or host-id) objects that came back;
    it may be empty when the detection path only knows a count."""

    def __init__(self, reason: str, returned: Sequence = ()):
        returned = list(returned)
        msg = f"mesh capacity returned: {reason}"
        if returned:
            msg += (f" (returned {len(returned)}: "
                    f"{[str(d) for d in returned]})")
        super().__init__(msg)
        self.reason = reason
        self.returned = returned


class ParticipantRegistry:
    """Heartbeat registry over the cluster's participants (hosts or
    devices).

    The reference's Legion runtime learns about node death from GASNet
    conduit errors; JAX SPMD has no such channel — a dead host just makes
    the next collective hang. This registry is the userspace substitute:
    every participant calls :meth:`heartbeat` periodically (the training
    loop does it once per step for its own host), and :meth:`check`
    raises :class:`MeshDegraded` naming every participant whose last
    heartbeat is older than the deadline. Thread-safe — workers heartbeat
    from their own threads.

    The registry also watches the OTHER direction: a heartbeat from a
    participant it has never seen (a new host joining the job), or from
    one it had written off as dead, marks that participant RETURNED.
    :meth:`take_returned` drains the returned set — the scale-UP analog
    of :meth:`check`, polled by the elastic layer to trigger
    ``parallel.elastic.expand``.
    """

    def __init__(self, participants: Sequence, deadline_s: float = 30.0):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        from ..analysis.sanitizer import make_lock
        self._lock = make_lock("ParticipantRegistry._lock")
        now = time.monotonic()
        self._last: Dict = {p: now for p in participants}
        self._returned: List = []

    @property
    def participants(self) -> List:
        with self._lock:
            return list(self._last)

    def heartbeat(self, participant) -> None:
        now = time.monotonic()
        with self._lock:
            prev = self._last.get(participant)
            if prev is None or now - prev > self.deadline_s:
                # a brand-new participant, or one that had missed its
                # deadline (mark_dead included): capacity came back
                if participant not in self._returned:
                    self._returned.append(participant)
            self._last[participant] = now

    def take_returned(self) -> List:
        """Participants that (re)joined since the last call — new ids
        and revived dead ones — in arrival order; drains the set. The
        caller decides whether to grow (``parallel.elastic.expand``)."""
        with self._lock:
            out, self._returned = self._returned, []
            return out

    def mark_dead(self, participant) -> None:
        """Force-expire a participant (external failure signal — e.g. a
        preemption notice — without waiting out the deadline)."""
        with self._lock:
            if participant in self._last:
                self._last[participant] = float("-inf")

    def dead(self, now: Optional[float] = None) -> List:
        now = time.monotonic() if now is None else now
        with self._lock:
            return [p for p, t in self._last.items()
                    if now - t > self.deadline_s]

    def check(self) -> None:
        """Raise :class:`MeshDegraded` when any participant missed its
        heartbeat deadline; no-op when everyone is live."""
        lost = self.dead()
        if lost:
            with self._lock:
                surviving = [p for p in self._last if p not in set(lost)]
            raise MeshDegraded(
                f"{len(lost)} participant(s) missed the "
                f"{self.deadline_s:.3g}s heartbeat deadline",
                lost=lost, surviving=surviving)


def probe_mesh(mesh: Mesh, deadline_s: float = 30.0) -> float:
    """Collective-deadline watchdog: run one tiny all-reduce over the
    mesh with a wall-clock deadline; return its latency in seconds.

    A dead or wedged host makes cross-host collectives block forever —
    the canonical "job hangs at 100% idle" failure. The probe runs the
    collective on a watchdog thread and waits with a timeout, so the
    CALLER gets a typed :class:`MeshDegraded` at the deadline instead of
    hanging (the probe thread is daemon and is abandoned; a genuinely
    dead mesh cannot be un-blocked from userspace).

    Fault injection: ``FF_FAULT_STALL_COLLECTIVE`` /
    ``FaultPlan.stall_s["collective"]`` stalls the probe once so the
    deadline path is test-driven on a healthy CPU mesh.
    """
    from ..utils import faults
    from ..utils.watchdog import StallReport

    done = threading.Event()
    result: list = []

    def _collective():
        try:
            faults.maybe_stall("collective")
            ones = jax.device_put(
                np.ones((mesh.size,), np.float32),
                NamedSharding(mesh, PartitionSpec(mesh.axis_names)))
            total = float(jax.jit(
                lambda x: x.sum(),
                out_shardings=NamedSharding(mesh, PartitionSpec()))(ones))
            result.append(total)
        except BaseException as e:   # surfaced below as degradation
            result.append(e)
        finally:
            done.set()

    t0 = time.monotonic()
    t = threading.Thread(target=_collective, daemon=True,
                         name="ff-mesh-probe")
    t.start()
    if not done.wait(deadline_s):
        report = StallReport(
            worker="ff-mesh-probe", waiting_for="mesh all-reduce",
            waited_s=time.monotonic() - t0, deadline_s=deadline_s,
            detail=f"mesh={dict(mesh.shape)}")
        raise MeshDegraded(
            f"collective did not complete within {deadline_s:.3g}s "
            f"(dead or stalled host)", report=report)
    out = result[0]
    if isinstance(out, BaseException):
        raise MeshDegraded(f"collective failed: {out}") from out
    if out != float(mesh.size):
        raise MeshDegraded(
            f"collective returned {out} from a {mesh.size}-device "
            f"all-reduce of ones (corrupt mesh state)")
    return time.monotonic() - t0


def _force_cpu_cluster(devices_per_process: int) -> None:
    """Configure THIS process as one rank of a multi-process CPU cluster:
    virtual host devices + cross-process CPU collectives (gloo). Stands in
    for the reference's GASNet transport when validating the multi-node
    path without a TPU pod (reference tests can only do this by grabbing
    real GPUs via SLURM, src/ops/tests/test_bootstrap.sh:2). Must run
    before any backend-initializing JAX call."""
    import jax
    from ..utils.testing import ensure_cpu_devices
    ensure_cpu_devices(devices_per_process)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           cpu_devices_per_process: Optional[int] = None
                           ) -> None:
    """Initialize the multi-host runtime (reference: GASNet bootstrap via
    mpirun/jsrun in run_summit.sh). On Cloud TPU pods all arguments are
    auto-detected; elsewhere read the env (COORDINATOR_ADDRESS,
    NUM_PROCESSES, PROCESS_ID) or pass explicitly. No-op if already
    initialized or single-process.

    `cpu_devices_per_process` (env: FF_CPU_DEVICES_PER_PROCESS) makes this
    rank a CPU-cluster member (virtual host devices + gloo collectives) so
    the full multi-process path — coordinator handshake, global mesh over
    non-addressable devices, cross-process collectives, host-local batch
    assembly — executes on one machine."""
    if cpu_devices_per_process is None and \
            "FF_CPU_DEVICES_PER_PROCESS" in os.environ:
        cpu_devices_per_process = _env_int("FF_CPU_DEVICES_PER_PROCESS")
    # NB: must not touch any backend-initializing API (even
    # jax.process_count()) before jax.distributed.initialize
    try:
        from jax._src.distributed import global_state
        if global_state.client is not None:
            return  # already initialized
    except ImportError:
        pass
    if cpu_devices_per_process:
        _force_cpu_cluster(cpu_devices_per_process)
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = _env_int("NUM_PROCESSES")
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = _env_int("PROCESS_ID")
    if coordinator_address is None and num_processes is None:
        # single host, or TPU pod with full auto-detection
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError) as e:
            # could be "not a distributed environment" — but could also be
            # a genuine pod-bootstrap failure, which would silently
            # degrade to N independent single-host jobs. Surface it.
            import warnings
            warnings.warn(
                f"jax.distributed.initialize() auto-detection failed "
                f"({e}); continuing single-process. If this is a "
                f"multi-host launch, set COORDINATOR_ADDRESS/"
                f"NUM_PROCESSES/PROCESS_ID explicitly.")
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _slice_groups(devices: Sequence) -> Dict[int, list]:
    """Group devices by DCN domain: slice on TPU pods, process elsewhere.
    Non-TPU backends can report slice_index == 0 for EVERY device even in
    a multi-process cluster (observed on the multi-process CPU backend),
    so when slice_index fails to distinguish while processes differ, the
    process is the DCN domain — exactly the reference's notion of a node
    (model.cc:1366-1370 `--nodes`)."""
    def group_by(key_fn):
        groups: Dict[int, list] = {}
        for d in devices:
            groups.setdefault(key_fn(d), []).append(d)
        return groups

    groups = group_by(lambda d: getattr(d, "slice_index", None)
                      if getattr(d, "slice_index", None) is not None
                      else getattr(d, "process_index", 0))
    if (len(groups) == 1
            and getattr(devices[0], "platform", "") != "tpu"):
        # NON-TPU only: a real single-slice multi-host pod genuinely IS
        # one DCN domain (its hosts share ICI) and must keep dcn=1 —
        # only a backend whose slice_index carries no information (the
        # multi-process CPU backend reports 0 everywhere) falls back to
        # process grouping
        by_proc = group_by(lambda d: getattr(d, "process_index", 0))
        if len(by_proc) > 1:
            return by_proc
    return groups


def make_multihost_mesh(devices: Optional[Sequence] = None,
                        num_slices: Optional[int] = None) -> Mesh:
    """Global mesh with the DCN (slice) axis first, factorized ICI axes
    after: axes ("dcn", "f0", "f1", ...).

    `num_slices` overrides slice detection (used for CPU-mesh testing
    where devices carry no slice_index; the virtual slice is the leading
    axis). With one slice this degenerates to parallel.mesh.make_mesh's
    layout plus a size-1 "dcn" axis, so strategies written against the
    multi-host mesh also compile single-slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_slices is None:
        groups = _slice_groups(devices)
        num_slices = len(groups)
        sizes = {k: len(g) for k, g in groups.items()}
        if len(set(sizes.values())) > 1:
            # uneven per-host device counts (a half-dead host after a
            # chip failure): reshaping would silently MIX hosts within a
            # slice row, putting DCN hops inside "ICI" axes — reject
            # loudly; elastic recovery drops to the survivors instead
            raise ValueError(
                f"uneven devices per DCN domain {sizes}: every "
                f"slice/host must contribute the same device count "
                f"(drop the degraded host's devices, or re-plan via "
                f"parallel.elastic on the surviving homogeneous set)")
        # stable order: by slice key, then device order within
        devices = [d for k in sorted(groups) for d in groups[k]]
    n = len(devices)
    if n % num_slices != 0:
        raise ValueError(f"{n} devices do not split into {num_slices} "
                         f"equal slices")
    per_slice = n // num_slices
    factors = sorted(_prime_factors(per_slice), reverse=True) or [1]
    names = ("dcn",) + tuple(f"f{i}" for i in range(len(factors)))
    arr = np.array(devices).reshape((num_slices,) + tuple(factors))
    return Mesh(arr, names)


def put_global(value, sharding: NamedSharding) -> jax.Array:
    """device_put that stays correct under multi-controller SPMD.

    Single-process: plain `jax.device_put`. Multi-process: a committed
    single-device array cannot be device_put to a sharding spanning
    non-addressable devices (cross-host reshard), so the value is staged
    through the host and the global array assembled from each process's
    addressable shards (every process computes the same full value — the
    init path seeds identically on all ranks, mirroring how every rank of
    the reference's control-replicated top-level task builds the same
    model, model.cc:1384-1409)."""
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    host = np.asarray(value)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


def host_local_slice(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """This process's contiguous slice of a global batch (process-order
    concatenation — the layout global_batch_from_host_local assembles
    back). Single place for the slicing contract and its divisibility
    check; single-process it returns the batch unchanged."""
    pc = jax.process_count()
    if pc <= 1:
        return batch
    pid = jax.process_index()
    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        if v.shape[0] % pc:
            raise ValueError(
                f"global batch dim {v.shape[0]} of {k!r} must divide "
                f"evenly over {pc} processes")
        per = v.shape[0] // pc
        out[k] = v[pid * per:(pid + 1) * per]
    return out


def global_batch_from_host_local(batch: Dict[str, np.ndarray], mesh: Mesh,
                                 batch_axes: Optional[tuple] = None
                                 ) -> Dict[str, jax.Array]:
    """Assemble per-host shards into global, batch-sharded device arrays.

    Each process passes ITS slice of the global batch (global_batch =
    process_count × local_batch, concatenated in process order); returns
    arrays sharded over all mesh axes on dim 0. Works unchanged in
    single-process runs (where it equals a sharded device_put)."""
    axes = batch_axes if batch_axes is not None else tuple(mesh.axis_names)
    sharding = NamedSharding(mesh, PartitionSpec(axes))
    out = {}
    for name, local in batch.items():
        out[name] = jax.make_array_from_process_local_data(sharding, local)
    return out
