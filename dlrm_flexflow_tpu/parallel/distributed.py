"""Multi-host (multi-node) runtime: process init, hybrid ICI/DCN meshes,
host-local → global batch assembly.

Parity with the reference's multi-node stack (reference: GASNet under
Realm for inter-node transport, README.md:18-20; Legion control replication
+ DataParallelShardingFunctor routing index-task points across nodes,
model.cc:1384-1409; `--nodes` flag, model.cc:1366-1370; Summit launch
scripts examples/cpp/DLRM/run_summit*.sh).

TPU-native redesign: every host runs the SAME SPMD program
(jax.distributed.initialize + one global jax.sharding.Mesh over all
chips); in-slice traffic rides ICI, cross-slice traffic rides DCN. The
mesh puts the DCN (slice) axis FIRST so degree assignment
(parallel/sharding.py) consumes ICI axes for high-bandwidth inner
shardings and only spills onto the DCN axis for the outermost (data)
dim — the layout "How to Scale Your Model" prescribes for multi-slice.
Per-host input pipelines feed host-local shards that
`global_batch_from_host_local` assembles into one global array per input
(the analog of the reference's per-node zero-copy dataset residency +
per-point-task scatter, dlrm.cc:384-589).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import _prime_factors


def _force_cpu_cluster(devices_per_process: int) -> None:
    """Configure THIS process as one rank of a multi-process CPU cluster:
    virtual host devices + cross-process CPU collectives (gloo). Stands in
    for the reference's GASNet transport when validating the multi-node
    path without a TPU pod (reference tests can only do this by grabbing
    real GPUs via SLURM, src/ops/tests/test_bootstrap.sh:2). Must run
    before any backend-initializing JAX call."""
    import jax
    from ..utils.testing import ensure_cpu_devices
    ensure_cpu_devices(devices_per_process)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           cpu_devices_per_process: Optional[int] = None
                           ) -> None:
    """Initialize the multi-host runtime (reference: GASNet bootstrap via
    mpirun/jsrun in run_summit.sh). On Cloud TPU pods all arguments are
    auto-detected; elsewhere read the env (COORDINATOR_ADDRESS,
    NUM_PROCESSES, PROCESS_ID) or pass explicitly. No-op if already
    initialized or single-process.

    `cpu_devices_per_process` (env: FF_CPU_DEVICES_PER_PROCESS) makes this
    rank a CPU-cluster member (virtual host devices + gloo collectives) so
    the full multi-process path — coordinator handshake, global mesh over
    non-addressable devices, cross-process collectives, host-local batch
    assembly — executes on one machine."""
    if cpu_devices_per_process is None and \
            "FF_CPU_DEVICES_PER_PROCESS" in os.environ:
        cpu_devices_per_process = int(
            os.environ["FF_CPU_DEVICES_PER_PROCESS"])
    # NB: must not touch any backend-initializing API (even
    # jax.process_count()) before jax.distributed.initialize
    try:
        from jax._src.distributed import global_state
        if global_state.client is not None:
            return  # already initialized
    except ImportError:
        pass
    if cpu_devices_per_process:
        _force_cpu_cluster(cpu_devices_per_process)
    coordinator_address = coordinator_address or os.environ.get(
        "COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])
    if coordinator_address is None and num_processes is None:
        # single host, or TPU pod with full auto-detection
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError) as e:
            # could be "not a distributed environment" — but could also be
            # a genuine pod-bootstrap failure, which would silently
            # degrade to N independent single-host jobs. Surface it.
            import warnings
            warnings.warn(
                f"jax.distributed.initialize() auto-detection failed "
                f"({e}); continuing single-process. If this is a "
                f"multi-host launch, set COORDINATOR_ADDRESS/"
                f"NUM_PROCESSES/PROCESS_ID explicitly.")
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def _slice_groups(devices: Sequence) -> Dict[int, list]:
    """Group devices by DCN domain: slice on TPU pods, process elsewhere.
    Non-TPU backends can report slice_index == 0 for EVERY device even in
    a multi-process cluster (observed on the multi-process CPU backend),
    so when slice_index fails to distinguish while processes differ, the
    process is the DCN domain — exactly the reference's notion of a node
    (model.cc:1366-1370 `--nodes`)."""
    def group_by(key_fn):
        groups: Dict[int, list] = {}
        for d in devices:
            groups.setdefault(key_fn(d), []).append(d)
        return groups

    groups = group_by(lambda d: getattr(d, "slice_index", None)
                      if getattr(d, "slice_index", None) is not None
                      else getattr(d, "process_index", 0))
    if (len(groups) == 1
            and getattr(devices[0], "platform", "") != "tpu"):
        # NON-TPU only: a real single-slice multi-host pod genuinely IS
        # one DCN domain (its hosts share ICI) and must keep dcn=1 —
        # only a backend whose slice_index carries no information (the
        # multi-process CPU backend reports 0 everywhere) falls back to
        # process grouping
        by_proc = group_by(lambda d: getattr(d, "process_index", 0))
        if len(by_proc) > 1:
            return by_proc
    return groups


def make_multihost_mesh(devices: Optional[Sequence] = None,
                        num_slices: Optional[int] = None) -> Mesh:
    """Global mesh with the DCN (slice) axis first, factorized ICI axes
    after: axes ("dcn", "f0", "f1", ...).

    `num_slices` overrides slice detection (used for CPU-mesh testing
    where devices carry no slice_index; the virtual slice is the leading
    axis). With one slice this degenerates to parallel.mesh.make_mesh's
    layout plus a size-1 "dcn" axis, so strategies written against the
    multi-host mesh also compile single-slice.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_slices is None:
        groups = _slice_groups(devices)
        num_slices = len(groups)
        # stable order: by slice key, then device order within
        devices = [d for k in sorted(groups) for d in groups[k]]
    n = len(devices)
    if n % num_slices != 0:
        raise ValueError(f"{n} devices do not split into {num_slices} "
                         f"equal slices")
    per_slice = n // num_slices
    factors = sorted(_prime_factors(per_slice), reverse=True) or [1]
    names = ("dcn",) + tuple(f"f{i}" for i in range(len(factors)))
    arr = np.array(devices).reshape((num_slices,) + tuple(factors))
    return Mesh(arr, names)


def put_global(value, sharding: NamedSharding) -> jax.Array:
    """device_put that stays correct under multi-controller SPMD.

    Single-process: plain `jax.device_put`. Multi-process: a committed
    single-device array cannot be device_put to a sharding spanning
    non-addressable devices (cross-host reshard), so the value is staged
    through the host and the global array assembled from each process's
    addressable shards (every process computes the same full value — the
    init path seeds identically on all ranks, mirroring how every rank of
    the reference's control-replicated top-level task builds the same
    model, model.cc:1384-1409)."""
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    host = np.asarray(value)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


def host_local_slice(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """This process's contiguous slice of a global batch (process-order
    concatenation — the layout global_batch_from_host_local assembles
    back). Single place for the slicing contract and its divisibility
    check; single-process it returns the batch unchanged."""
    pc = jax.process_count()
    if pc <= 1:
        return batch
    pid = jax.process_index()
    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        if v.shape[0] % pc:
            raise ValueError(
                f"global batch dim {v.shape[0]} of {k!r} must divide "
                f"evenly over {pc} processes")
        per = v.shape[0] // pc
        out[k] = v[pid * per:(pid + 1) * per]
    return out


def global_batch_from_host_local(batch: Dict[str, np.ndarray], mesh: Mesh,
                                 batch_axes: Optional[tuple] = None
                                 ) -> Dict[str, jax.Array]:
    """Assemble per-host shards into global, batch-sharded device arrays.

    Each process passes ITS slice of the global batch (global_batch =
    process_count × local_batch, concatenated in process order); returns
    arrays sharded over all mesh axes on dim 0. Works unchanged in
    single-process runs (where it equals a sharded device_put)."""
    axes = batch_axes if batch_axes is not None else tuple(mesh.axis_names)
    sharding = NamedSharding(mesh, PartitionSpec(axes))
    out = {}
    for name, local in batch.items():
        out[name] = jax.make_array_from_process_local_data(sharding, local)
    return out
