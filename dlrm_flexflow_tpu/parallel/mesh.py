"""Device mesh construction with factorized axes.

The reference enumerates physical GPUs/CPUs through the Legion machine model
and assigns point tasks to them in the mapper (reference:
src/mapper/mapper.cc:222-322). On TPU the analogous object is a
`jax.sharding.Mesh`. To let SOAP-style per-op configs pick *any*
power-of-two partition degree per tensor dim, we build the mesh with one
axis per prime factor of the device count (e.g. 8 devices → axes
f0,f1,f2 each of size 2). A partition degree d then maps to a tuple of
consecutive axes whose sizes multiply to d (parallel/sharding.py), and two
ops that shard the same logical dim with the same degree land on identical
device assignments — no spurious resharding.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh


def _prime_factors(n: int) -> List[int]:
    fs = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            fs.append(d)
            n //= d
        d += 1
    if n > 1:
        fs.append(n)
    return fs


def structural_axis_sizes(n: int) -> List[int]:
    """THE axis factorization make_mesh builds for n devices (largest
    prime factor first). Search feasibility, offline-target simulation,
    and mesh construction all defer here so a strategy planned for an
    n-device target matches the mesh compile() will build."""
    return sorted(_prime_factors(n), reverse=True) or [1]


def make_mesh(devices: Optional[Sequence] = None,
              num_devices: Optional[int] = None) -> Mesh:
    """Build a factorized mesh over `devices` (default: all jax devices).

    Axis names are "f0", "f1", ... ordered largest factor first so that
    low-index axes (consumed first by degree assignment) correspond to the
    most ICI-local device groups under the default device ordering.
    """
    if devices is None:
        devices = jax.devices()
        if num_devices is not None:
            if num_devices > len(devices):
                raise ValueError(
                    f"requested {num_devices} devices but only "
                    f"{len(devices)} are available (use "
                    f"utils.testing.ensure_cpu_devices to virtualize a "
                    f"larger CPU mesh for testing)")
            devices = devices[:num_devices]
    devices = list(devices)
    n = len(devices)
    factors = structural_axis_sizes(n)
    names = tuple(f"f{i}" for i in range(len(factors)))
    arr = np.array(devices).reshape(tuple(factors))
    return Mesh(arr, names)


def mesh_axis_sizes(mesh: Mesh) -> List[int]:
    return [mesh.shape[name] for name in mesh.axis_names]


def total_devices(mesh: Mesh) -> int:
    n = 1
    for s in mesh_axis_sizes(mesh):
        n *= s
    return n
