"""Row-sharded embedding tables with explicit all-to-all lookup routing.

The pod-scale DLRM shape (Naumov et al. 2019; Mudigere et al., ZionEX
2022): each device owns a ROW block of every embedding table and a slice
of the batch; per-sample lookups are routed to the owning shard and the
embedded rows routed back. The reference got this movement implicitly
from Legion DMA for whole-table placement (dlrm_strategy.cc:252-256);
`EmbeddingBagStacked`'s table-dim sharding reproduces that — but every
table must still fit one device. Row sharding (`ParallelConfig.
param_degree > 1`) is what removes that ceiling.

The exchange, per training step, under one `shard_map` over the mesh:

  forward   bucketize local lookups by owning shard (stable sort by
            owner + rank-in-bucket) → dense all-to-all of request row
            ids over the row axes → local gather on each owner →
            all-to-all of the embedded rows back → unpermute + bag
            aggregation. Output is batch-sharded over the whole mesh.
  backward  the same routing in reverse: gradient rows travel TO their
            owning shard (all-to-all), are put into one canonical
            global order, and scatter-add into the local row block —
            so the table gradient, and therefore the optimizer state,
            stays shard-local. No table-sized dense gradient and no
            cross-replica table all-reduce ever materializes.

Exactness contract (tests/test_rowshard.py pins it): forward outputs,
gradients, and optimizer updates are BIT-IDENTICAL to the
replicated-table baseline, for any row-shard degree and any mesh
factorization. Two mechanisms make that hold:

- the request buckets are filled in local flatten order and received in
  peer order, and batch blocks are assigned to devices in mesh order —
  so each row's duplicate updates arrive in global batch order;
- before applying, every owner re-sorts its received updates by the
  carried GLOBAL lookup position, making the scatter's duplicate-
  accumulation order independent of the routing topology.

Capacity: the dense exchange reserves `n_local` slots per peer (the
always-exact worst case — one owner could receive every local lookup).
A production TPU kernel would use a ragged exchange at ~n_local/P slots
per peer (this jax version predates `ragged_all_to_all`); the cost
model prices that balanced exchange, which is also what the padded
dense form approaches as indices spread uniformly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # renamed across jax versions
    from jax import shard_map as _shard_map          # type: ignore
except ImportError:                                   # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from .sharding import param_axis_indices


def _smap(f, mesh, in_specs, out_specs):
    import inspect
    params = inspect.signature(_shard_map).parameters
    kw = {"check_vma": False} if "check_vma" in params else \
        {"check_rep": False}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


@dataclass(frozen=True)
class RowShardPlan:
    """Resolved row-shard placement for one embedding op: which mesh
    axes carry the row blocks (`row_axes`, consumed leading-first like
    every other degree), how many shards that makes, and how many
    logical rows each shard owns."""

    mesh: Mesh
    row_axes: Tuple[str, ...]     # mesh axes the rows shard over
    nshards: int                  # product of row-axis sizes
    rows_local: int               # logical rows per shard (per table)
    flat_rows_local: int          # rows per shard of the FLAT local view

    @property
    def all_axes(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def nonrow_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in self.mesh.axis_names
                     if a not in self.row_axes)

    @property
    def ndev(self) -> int:
        n = 1
        for a in self.mesh.axis_names:
            n *= self.mesh.shape[a]
        return n


def plan_row_shard(mesh: Optional[Mesh], param_degree: int,
                   rows: int, pack: int, tables: int = 1
                   ) -> Optional[RowShardPlan]:
    """Build the RowShardPlan for `param_degree` row shards of a table
    with `rows` logical rows stored `pack`-per-lane-tile, or None with
    the structural reason it cannot apply (caller logs it)."""
    if mesh is None or param_degree <= 1:
        return None
    sizes = [int(mesh.shape[a]) for a in mesh.axis_names]
    if int(np.prod(sizes)) <= 1:
        return None
    idx = param_axis_indices(param_degree, sizes)
    if idx is None:
        return None
    # equal row blocks per shard, aligned to the lane packing so a
    # shard's packed block reshapes to whole logical rows
    if rows % (param_degree * max(pack, 1)) != 0:
        return None
    axes = tuple(mesh.axis_names[i] for i in idx)
    rows_local = rows // param_degree
    return RowShardPlan(mesh=mesh, row_axes=axes, nshards=param_degree,
                        rows_local=rows_local,
                        flat_rows_local=tables * rows_local)


# ---- routing primitives (inside the shard_map body) ----------------------


def _bucket_ranks(owner_f: jnp.ndarray) -> jnp.ndarray:
    """Rank of each local lookup within its owner's bucket (stable: the
    local flatten order is preserved inside each bucket — the ordering
    half of the bit-identity contract)."""
    n = owner_f.shape[0]
    order = jnp.argsort(owner_f)                       # stable
    so = jnp.take(owner_f, order)
    start = jnp.searchsorted(so, so, side="left")
    ranks_sorted = jnp.arange(n, dtype=jnp.int32) - start.astype(jnp.int32)
    return jnp.zeros((n,), jnp.int32).at[order].set(ranks_sorted)


def _device_linear_index(mesh: Mesh) -> jnp.ndarray:
    """This device's linear index over ALL mesh axes in mesh order —
    the same order input batches block-shard over, so `dev * n + j` is
    the GLOBAL flatten position of local lookup j."""
    dev = jnp.zeros((), jnp.int32)
    for a in mesh.axis_names:
        dev = dev * mesh.shape[a] + jax.lax.axis_index(a)
    return dev


def _route_requests(plan: RowShardPlan, owner_f, local_f):
    """Bucketize + index all-to-all. Returns (recv_ids (P*C,), valid
    mask, of/rank for the return path, capacity C)."""
    n = owner_f.shape[0]
    C = n                                   # exact dense capacity
    rank = _bucket_ranks(owner_f)
    slot = owner_f * C + rank
    sentinel = jnp.int32(plan.flat_rows_local)
    send = jnp.full((plan.nshards * C,), sentinel, jnp.int32
                    ).at[slot].set(local_f)
    recv = jax.lax.all_to_all(send.reshape(plan.nshards, C),
                              plan.row_axes, 0, 0).reshape(-1)
    return recv, recv < sentinel, rank, C


def row_sharded_bag_lookup(plan: RowShardPlan, table, table_spec,
                           owner, local_id, d: int, aggr: str,
                           block_shape):
    """Forward lookup with explicit all-to-all routing.

    table     : global packed kernel, row-sharded per `table_spec`
    owner     : (batch, T, bag) int32 — owning shard of each lookup
    local_id  : (batch, T, bag) int32 — row id within the owner's flat
                local (flat_rows_local, d) view
    returns   : (batch, T, d) aggregated bags, batch-sharded over the
                whole mesh

    Differentiable: a custom VJP routes output cotangent rows back to
    their owning shards (all-to-all) and scatter-adds them there, so
    even the dense-update path never all-reduces a table-sized
    gradient. (The sparse touched-rows updates below bypass autodiff
    entirely.)
    """
    mesh = plan.mesh

    def fwd_body(tbl_blk, ow, lo):
        flat = tbl_blk.reshape(-1, d)              # (flat_rows_local, d)
        shape = ow.shape                            # (b_loc, T, bag)
        of = ow.reshape(-1)
        lf = lo.reshape(-1)
        recv, valid, rank, C = _route_requests(plan, of, lf)
        safe = jnp.minimum(recv, plan.flat_rows_local - 1)
        rows = jnp.take(flat, safe, axis=0)
        rows = jnp.where(valid[:, None], rows, 0.0)
        back = jax.lax.all_to_all(rows.reshape(plan.nshards, C, d),
                                  plan.row_axes, 0, 0)
        mine = jnp.take(back.reshape(plan.nshards * C, d),
                        of * C + rank, axis=0)
        rows_btb = mine.reshape(shape + (d,))
        # bag is always the last index dim ((batch, T, bag) or
        # (batch, bag)); aggregate it, keep the feature dim
        if aggr == "avg":
            return jnp.mean(rows_btb, axis=-2)
        return jnp.sum(rows_btb, axis=-2)

    batch_spec = PartitionSpec(plan.all_axes)
    lookup = _smap(fwd_body, mesh,
                   in_specs=(table_spec, batch_spec, batch_spec),
                   out_specs=batch_spec)

    @jax.custom_vjp
    def _call(tbl, ow, lo):
        return lookup(tbl, ow, lo)

    def _call_fwd(tbl, ow, lo):
        return lookup(tbl, ow, lo), (ow, lo)

    def _call_bwd(res, ct):
        ow, lo = res
        upd = _bag_cotangent_rows(ct, ow.shape, d, aggr)
        body = _scatter_body(plan, d, block_shape, mode="grad")
        grad = _smap(body, mesh,
                     in_specs=(batch_spec, batch_spec, batch_spec),
                     out_specs=table_spec)(ow, lo, upd)
        # integer operands carry float0 cotangents
        return (grad,
                np.zeros(ow.shape, jax.dtypes.float0),
                np.zeros(lo.shape, jax.dtypes.float0))

    _call.defvjp(_call_fwd, _call_bwd)
    return _call(table, owner, local_id)


def _bag_cotangent_rows(ct, idx_shape, d: int, aggr: str):
    """Output cotangent (batch, T, d) -> per-lookup gradient rows
    (batch, T, bag, d): each bag slot receives the bag-sum's cotangent
    (divided by the bag size under AVG)."""
    ct = ct.astype(jnp.float32)
    if aggr == "avg":
        ct = ct / idx_shape[-1]
    return jnp.broadcast_to(ct[..., None, :], tuple(idx_shape) + (d,))


def _scatter_body(plan: RowShardPlan, d: int, block_shape, mode: str,
                  lr: float = 0.0, opt=None, slab_names=()):
    """shard_map body routing per-lookup update rows to their owning
    shard and applying them there in canonical global order.

    mode "grad":  scatter-add raw rows into zeros (the custom-VJP table
                  gradient).
    mode "sgd":   w -= lr * rows, touched rows only (plain-SGD sparse
                  update).
    mode "opt":   stateful touched-rows update (lazy momentum/Adam) via
                  the shared logical-row dedup + optimizer row math.
    """
    mesh = plan.mesh
    sentinel = plan.flat_rows_local
    INT_MAX = jnp.iinfo(jnp.int32).max

    def route(ow, lo, upd):
        """-> (rids, rupds) for THIS shard, in canonical global order."""
        shape = ow.shape
        n = int(np.prod(shape))
        of = ow.reshape(-1)
        lf = lo.reshape(-1)
        uf = upd.reshape(n, d)
        dev = _device_linear_index(mesh)
        pos = dev * n + jnp.arange(n, dtype=jnp.int32)
        rank = _bucket_ranks(of)
        C = n
        slot = of * C + rank
        send_id = jnp.full((plan.nshards * C,), sentinel, jnp.int32
                           ).at[slot].set(lf)
        send_pos = jnp.full((plan.nshards * C,), INT_MAX, jnp.int32
                            ).at[slot].set(pos)
        send_upd = jnp.zeros((plan.nshards * C, d), jnp.float32
                             ).at[slot].set(uf.astype(jnp.float32))
        rid = jax.lax.all_to_all(send_id.reshape(plan.nshards, C),
                                 plan.row_axes, 0, 0).reshape(-1)
        rpos = jax.lax.all_to_all(send_pos.reshape(plan.nshards, C),
                                  plan.row_axes, 0, 0).reshape(-1)
        rupd = jax.lax.all_to_all(send_upd.reshape(plan.nshards, C, d),
                                  plan.row_axes, 0, 0).reshape(-1, d)
        # a row shard is replicated across the non-row axes, whose
        # device groups each saw a different batch slice: gather every
        # group's contributions so all replicas apply the full set (and
        # stay bitwise in lockstep)
        if plan.nonrow_axes:
            rid = jax.lax.all_gather(rid, plan.nonrow_axes, axis=0,
                                     tiled=True)
            rpos = jax.lax.all_gather(rpos, plan.nonrow_axes, axis=0,
                                      tiled=True)
            rupd = jax.lax.all_gather(rupd, plan.nonrow_axes, axis=0,
                                      tiled=True)
        # canonical order: ascending global lookup position (pads last)
        # — duplicate rows accumulate in the same sequence as the
        # replicated baseline's flatten-order scatter, for ANY topology
        order = jnp.argsort(rpos)
        return jnp.take(rid, order), jnp.take(rupd, order, axis=0)

    if mode == "grad":
        def body(ow, lo, upd):
            rid, rupd = route(ow, lo, upd)
            zero = jnp.zeros((sentinel, d), jnp.float32)
            return zero.at[rid].add(rupd, mode="drop"
                                    ).reshape(block_shape)
        return body

    if mode == "sgd":
        def body(tbl_blk, ow, lo, upd):
            rid, rupd = route(ow, lo, upd)
            flat = tbl_blk.reshape(-1, d)
            flat = flat.at[rid].add(-lr * rupd.astype(flat.dtype),
                                    mode="drop")
            return flat.reshape(tbl_blk.shape)
        return body

    if mode == "opt":
        def body(tbl_blk, slab_blks, ow, lo, upd, step):
            from ..ops.embedding import _stateful_update_rows_xla
            rid, rupd = route(ow, lo, upd)
            flat = tbl_blk.reshape(-1, d)
            slabs = {k: v.reshape(-1, d)
                     for k, v in zip(slab_names, slab_blks)}
            new_flat, new_slabs = _stateful_update_rows_xla(
                flat, rid, rupd, opt, slabs, step)
            return (new_flat.reshape(tbl_blk.shape),
                    tuple(new_slabs[k].reshape(tbl_blk.shape)
                          for k in slab_names))
        return body

    raise ValueError(f"unknown scatter mode {mode!r}")


def row_sharded_sgd_update(plan: RowShardPlan, table, table_spec,
                           owner, local_id, upd, lr: float, d: int):
    """Touched-rows plain-SGD update with all-to-all gradient-row
    routing: each shard applies -lr * (its rows' updates), in canonical
    global order. `upd` is (batch, T, bag, d) RAW gradient rows."""
    batch_spec = PartitionSpec(plan.all_axes)
    body = _scatter_body(plan, d, None, mode="sgd", lr=float(lr))
    return _smap(body, plan.mesh,
                 in_specs=(table_spec, batch_spec, batch_spec,
                           batch_spec),
                 out_specs=table_spec)(table, owner, local_id, upd)


def row_sharded_opt_update(plan: RowShardPlan, table, slabs, table_spec,
                           owner, local_id, upd, opt, step, d: int):
    """Stateful (lazy momentum/Adam) touched-rows update with
    all-to-all routing; optimizer state slabs are sharded exactly like
    the kernel, so state rows never leave their shard."""
    slab_names = tuple(sorted(slabs))
    batch_spec = PartitionSpec(plan.all_axes)
    body = _scatter_body(plan, d, None, mode="opt", opt=opt,
                         slab_names=slab_names)
    new_tbl, new_slab_vals = _smap(
        body, plan.mesh,
        in_specs=(table_spec, (table_spec,) * len(slab_names),
                  batch_spec, batch_spec, batch_spec, PartitionSpec()),
        out_specs=(table_spec, (table_spec,) * len(slab_names)),
    )(table, tuple(slabs[k] for k in slab_names), owner, local_id, upd,
      step)
    return new_tbl, dict(zip(slab_names, new_slab_vals))


# ---- accounting ----------------------------------------------------------


def dense_exchange_hlo_bytes(plan: RowShardPlan, lookups_global: int,
                             d: int, table_itemsize: int = 4) -> int:
    """All-to-all buffer bytes ONE device sends per step under the DENSE
    padded exchange this jax implementation actually lowers — what the
    HLO auditor must find in the partitioned program, instruction for
    instruction: request ids out (S x C int32), embedded rows back
    (S x C x d at the table dtype), then the gradient path's id + global-
    position + fp32 update-row exchanges. C (slot capacity per peer) is
    the full local lookup count n_local — the always-exact worst case —
    so the dense exchange moves S x the BALANCED bytes the cost model
    prices (`exchange_bytes_per_step`); the drift report shows both."""
    n_local = int(lookups_global) // max(plan.ndev, 1)
    S, C = plan.nshards, n_local
    fwd = S * C * 4 + S * C * d * table_itemsize
    bwd = S * C * 4 + S * C * 4 + S * C * d * 4
    return int(fwd + bwd)


def exchange_bytes_per_step(plan: RowShardPlan, lookups_global: int,
                            d: int, itemsize: int = 4,
                            backward: bool = True) -> int:
    """All-to-all bytes ONE device moves per step under the BALANCED
    (ragged / production) exchange: request ids out, embedded rows
    back, and (backward) gradient rows out again — each (P-1)/P of the
    device's ~lookups/ndev share. What bench_shard reports and the cost
    model prices."""
    n_dev = lookups_global / max(plan.ndev, 1)
    frac = (plan.nshards - 1) / plan.nshards
    fwd = n_dev * frac * (4 + d * itemsize)
    bwd = n_dev * frac * (4 + d * 4) if backward else 0.0
    return int(fwd + bwd)
