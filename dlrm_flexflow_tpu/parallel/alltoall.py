"""Row-sharded embedding tables with explicit all-to-all lookup routing.

The pod-scale DLRM shape (Naumov et al. 2019; Mudigere et al., ZionEX
2022): each device owns a ROW block of every embedding table and a slice
of the batch; per-sample lookups are routed to the owning shard and the
embedded rows routed back. The reference got this movement implicitly
from Legion DMA for whole-table placement (dlrm_strategy.cc:252-256);
`EmbeddingBagStacked`'s table-dim sharding reproduces that — but every
table must still fit one device. Row sharding (`ParallelConfig.
param_degree > 1`) is what removes that ceiling.

The exchange, per training step, under one `shard_map` over the mesh:

  forward   bucketize local lookups by owning shard (stable sort by
            owner + rank-in-bucket) → dense all-to-all of request row
            ids over the row axes → local gather on each owner →
            all-to-all of the embedded rows back → unpermute + bag
            aggregation. Output is batch-sharded over the whole mesh.
  backward  the same routing in reverse: gradient rows travel TO their
            owning shard (all-to-all), are put into one canonical
            global order, and scatter-add into the local row block —
            so the table gradient, and therefore the optimizer state,
            stays shard-local. No table-sized dense gradient and no
            cross-replica table all-reduce ever materializes.

Skew-aware refinements (ParallelConfig.exchange / hot_fraction — real
recommendation traffic is zipfian, so a handful of hot ids dominate):

- DEDUP-BEFORE-EXCHANGE (`exchange="dedup"`, Neo/ZionEX): each device
  sort→uniques its local lookup ids, routes only the DISTINCT ids
  through the exchange, scatters the returned rows back through the
  inverse map, and pre-accumulates gradient rows per unique id before
  the return exchange. Exchanged (valid) bytes then scale with distinct
  ids, not batch size; the padded capacity also drops to
  min(n_local, rows a shard owns) — after dedup an owner can never
  receive more requests than it has rows.
- HOT/COLD HYBRID (`hot_fraction > 0`, FAE): the top-H (low-numbered,
  hot) rows of every table are REPLICATED on each device — their
  lookups are purely local and their updates apply in lockstep from an
  all-gather — while the cold tail stays row-sharded. Hot traffic never
  touches the exchange at all.

Exactness contract (tests/test_rowshard.py pins it): forward outputs,
gradients, and optimizer updates are BIT-IDENTICAL across the dense,
dedup'd, and hybrid paths on the same mesh, for any row-shard degree
and any mesh factorization — including duplicate lookups. Three
mechanisms make that hold:

- the request buckets are filled in local flatten order and received in
  peer order, and batch blocks are assigned to devices in mesh order —
  so each row's duplicate updates arrive in global batch order;
- before applying, every receiver puts updates in CANONICAL order:
  combine duplicate rows per (row, source device) — a pos-ordered
  segment sum, exactly what the dedup path pre-computes on the sender —
  then apply the per-device partial sums in ascending first-occurrence
  global position. The accumulation tree is therefore identical whether
  duplicates were combined before or after the exchange, and
  independent of the routing topology (dedup at pd=4 == dedup at pd=8);
- hot (replicated) rows apply the SAME canonical combine from an
  all-gather of every device's updates, so replicas stay bitwise in
  lockstep and match what the owner shard would have computed.

Capacity: the dense exchange reserves `n_local` slots per peer (the
always-exact worst case — one owner could receive every local lookup);
the dedup'd exchange reserves min(n_local, flat_rows_local). A
production TPU kernel would use a ragged exchange at the actual
distinct-id counts (this jax version predates `ragged_all_to_all`); the
cost model prices that balanced exchange — with the expected distinct
ids from an observed id histogram (utils/histogram.py) when one is
attached — which is also what the padded dense form approaches.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

try:  # renamed across jax versions
    from jax import shard_map as _shard_map          # type: ignore
except ImportError:                                   # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from .sharding import param_axis_indices

_INT_MAX = np.iinfo(np.int32).max


def _smap(f, mesh, in_specs, out_specs):
    import inspect
    params = inspect.signature(_shard_map).parameters
    kw = {"check_vma": False} if "check_vma" in params else \
        {"check_rep": False}
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


@dataclass(frozen=True)
class RowShardPlan:
    """Resolved row-shard placement for one embedding op: which mesh
    axes carry the row blocks (`row_axes`, consumed leading-first like
    every other degree), how many shards that makes, and how many
    logical COLD (routed) rows each shard owns. `dedup` selects the
    unique-ids exchange; `hot_rows` > 0 is the hybrid placement's
    per-table replicated-row count (the plan's row geometry then
    describes only the cold tail)."""

    mesh: Mesh
    row_axes: Tuple[str, ...]     # mesh axes the rows shard over
    nshards: int                  # product of row-axis sizes
    rows_local: int               # logical COLD rows per shard (per table)
    flat_rows_local: int          # cold rows per shard of the FLAT view
    dedup: bool = False           # unique-ids exchange
    hot_rows: int = 0             # replicated hot rows per table
    tables: int = 1
    # pipelined exchange: decompose each fused all-to-all into
    # independent rounds (a ppermute ring over a single row axis,
    # capacity-chunked collectives over a factorized one) so XLA's
    # async scheduler can hide them under the step's dense compute.
    # Same blocks, same positions — bit-identical to the fused form.
    overlap: bool = False

    @property
    def all_axes(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def nonrow_axes(self) -> Tuple[str, ...]:
        return tuple(a for a in self.mesh.axis_names
                     if a not in self.row_axes)

    @property
    def hot_rows_flat(self) -> int:
        """Rows of the FLAT replicated hot block (all tables)."""
        return self.tables * self.hot_rows

    @property
    def ndev(self) -> int:
        n = 1
        for a in self.mesh.axis_names:
            n *= self.mesh.shape[a]
        return n

    def capacity(self, n_local: int) -> int:
        """Per-peer slot capacity of the index/row exchange: the dense
        path reserves the always-exact worst case (one owner receives
        every local lookup); after dedup an owner can receive at most
        as many DISTINCT requests as it has rows."""
        if self.dedup:
            return max(min(int(n_local), self.flat_rows_local), 1)
        return int(n_local)

    def row_ranges(self) -> list:
        """The [lo, hi) flat-row block each shard owns, in shard order —
        the same owner math the exchange body evaluates as
        ``owner = id // rows_local`` (see :func:`shard_row_ranges`)."""
        return shard_row_ranges(self.flat_rows_local * self.nshards,
                                self.nshards)


# ---- shared owner math (training exchange AND the serving shard tier) ----
#
# The exchange body computes `owner = flat_id // rows_local` with equal
# row blocks per shard; these module-level helpers are the host-side
# (numpy) statement of the same placement, generalized to a row count
# that does not divide evenly (the last shard owns the short tail). The
# serving shard tier (serve/shardtier.py) slices lookup shards with
# them, so a serving plan's row ownership is BY CONSTRUCTION the one a
# row-sharded training mesh would use — and shardcheck's FLX507 tiling
# audit verifies any plan against the same functions.


def shard_rows_local(rows: int, nshards: int) -> int:
    """Rows per shard (ceil-division block size)."""
    if nshards < 1:
        raise ValueError(f"nshards must be >= 1, got {nshards}")
    return -(-int(rows) // int(nshards))


def shard_row_ranges(rows: int, nshards: int) -> list:
    """[(lo, hi), ...] per shard, tiling [0, rows) exactly — contiguous
    equal blocks (the last possibly short, possibly empty)."""
    per = shard_rows_local(rows, nshards)
    return [(min(s * per, rows), min((s + 1) * per, rows))
            for s in range(nshards)]


def row_owners(ids, rows: int, nshards: int) -> np.ndarray:
    """Owning shard per flat row id — `id // rows_local`, clamped into
    range (ids are taken mod `rows` first, matching every host lookup's
    wrap semantics)."""
    per = shard_rows_local(rows, nshards)
    g = np.asarray(ids, np.int64) % max(int(rows), 1)
    return np.minimum(g // per, nshards - 1).astype(np.int64)


def plan_row_shard(mesh: Optional[Mesh], param_degree: int,
                   rows: int, pack: int, tables: int = 1,
                   dedup: bool = False, hot_rows: int = 0,
                   overlap: bool = False
                   ) -> Optional[RowShardPlan]:
    """Build the RowShardPlan for `param_degree` row shards of a table
    whose COLD (routed) tail has `rows` logical rows stored
    `pack`-per-lane-tile, or None with the structural reason it cannot
    apply (caller logs it). `hot_rows` records the hybrid placement's
    replicated per-table head (already excluded from `rows`);
    `overlap` selects the pipelined (decomposed) exchange."""
    if mesh is None or param_degree <= 1:
        return None
    sizes = [int(mesh.shape[a]) for a in mesh.axis_names]
    if int(np.prod(sizes)) <= 1:
        return None
    idx = param_axis_indices(param_degree, sizes)
    if idx is None:
        return None
    # equal row blocks per shard, aligned to the lane packing so a
    # shard's packed block reshapes to whole logical rows
    if rows % (param_degree * max(pack, 1)) != 0:
        return None
    axes = tuple(mesh.axis_names[i] for i in idx)
    rows_local = rows // param_degree
    return RowShardPlan(mesh=mesh, row_axes=axes, nshards=param_degree,
                        rows_local=rows_local,
                        flat_rows_local=tables * rows_local,
                        dedup=bool(dedup), hot_rows=int(hot_rows),
                        tables=int(tables), overlap=bool(overlap))


# ---- the exchange collective (inside the shard_map body) -----------------

# capacity-dim chunk count of the pipelined multi-axis exchange: enough
# independent collectives for the scheduler to overlap send k+1 with
# compute consuming chunk k, few enough that per-collective dispatch
# overhead stays under the ~0.5 ms floor the calibration measures
_OVERLAP_CHUNKS = 4


def _ring_a2a(plan: RowShardPlan, x):
    """Pipelined single-axis exchange: decompose the fused all-to-all
    of one (S, C[, d]) buffer into S-1 `ppermute` rounds. Round `s`
    sends block (me+s) mod S one hop of distance s and lands the block
    received from peer (me-s) mod S in its slot; the self block never
    leaves the device. Each round is an independent collective-permute,
    so XLA's async scheduler (collective-permute-start/-done) hoists
    them over whatever dense compute has no data dependence on the
    received blocks — that is the whole overlap. The output buffer is
    position-for-position the one `jax.lax.all_to_all` returns:
    out[j] = x_of_peer_j[me]. No payload arithmetic, so bit-identity
    with the fused exchange is by construction."""
    axis = plan.row_axes[0]
    S = plan.nshards
    me = jax.lax.axis_index(axis)
    out = x                         # keeps the self block at slot `me`
    for s in range(1, S):
        perm = [(i, (i + s) % S) for i in range(S)]
        blk = jax.lax.dynamic_index_in_dim(x, (me + s) % S, axis=0,
                                           keepdims=True)
        recv = jax.lax.ppermute(blk, axis, perm)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, recv, (me + S - s) % S, axis=0)
    return out


def _chunked_a2a(plan: RowShardPlan, x):
    """Pipelined multi-axis exchange: the ring form needs one linear
    peer order, which a factorized row axis does not have — so chunk
    the CAPACITY dim instead and issue one independent all-to-all per
    chunk. Identical bytes, identical slots (the chunks concatenate
    back in order); the scheduler overlaps chunk k+1's exchange with
    compute consuming chunk k. Falls back to the fused collective when
    the capacity has no usable divisor."""
    C = x.shape[1]
    k = 1
    for cand in range(min(_OVERLAP_CHUNKS, C), 1, -1):
        if C % cand == 0:
            k = cand
            break
    if k <= 1:
        return jax.lax.all_to_all(x, plan.row_axes, 0, 0)
    step = C // k
    parts = [jax.lax.all_to_all(
        jax.lax.slice_in_dim(x, i * step, (i + 1) * step, axis=1),
        plan.row_axes, 0, 0) for i in range(k)]
    return jnp.concatenate(parts, axis=1)


def _a2a(plan: RowShardPlan, x):
    """THE row-shard exchange collective on one (S, C[, d]) send buffer
    (block i addressed to shard i; returns the same layout with block j
    received from shard j). Every exchange in this module routes
    through here: serial plans lower the single fused
    `jax.lax.all_to_all` (one blocking collective, reference behavior);
    `plan.overlap` decomposes it into independent rounds the compiler
    can hide under dense compute. All three forms move the same blocks
    to the same slots — the bit-identity contract does not depend on
    which one ran."""
    if not plan.overlap or plan.nshards <= 1:
        return jax.lax.all_to_all(x, plan.row_axes, 0, 0)
    if len(plan.row_axes) == 1:
        return _ring_a2a(plan, x)
    return _chunked_a2a(plan, x)


# ---- routing primitives (inside the shard_map body) ----------------------


def _bucket_ranks(owner_f: jnp.ndarray) -> jnp.ndarray:
    """Rank of each local lookup within its owner's bucket (stable: the
    local flatten order is preserved inside each bucket — the ordering
    half of the bit-identity contract)."""
    n = owner_f.shape[0]
    order = jnp.argsort(owner_f)                       # stable
    so = jnp.take(owner_f, order)
    start = jnp.searchsorted(so, so, side="left")
    ranks_sorted = jnp.arange(n, dtype=jnp.int32) - start.astype(jnp.int32)
    return jnp.zeros((n,), jnp.int32).at[order].set(ranks_sorted)


def _device_linear_index(mesh: Mesh) -> jnp.ndarray:
    """This device's linear index over ALL mesh axes in mesh order —
    the same order input batches block-shard over, so `dev * n + j` is
    the GLOBAL flatten position of local lookup j."""
    dev = jnp.zeros((), jnp.int32)
    for a in mesh.axis_names:
        dev = dev * mesh.shape[a] + jax.lax.axis_index(a)
    return dev


def _dedup_keys(gf: jnp.ndarray):
    """Sort→unique machinery over flat lookup keys `gf` (n,): returns
    (order, seg, rep, inv, nuniq) where `order` is the stable sort
    permutation, `seg` the unique-segment id per SORTED position (within
    a segment, positions ascend — the canonical accumulation order),
    `rep` each unique slot's FIRST-occurrence original position (pads:
    int32 max), `inv` each lookup's unique slot, and `nuniq` the live
    unique count. Slots >= nuniq are padding."""
    n = gf.shape[0]
    order = jnp.argsort(gf)                            # stable
    sg = jnp.take(gf, order)
    first = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                             sg[1:] != sg[:-1]])
    seg = jnp.cumsum(first) - 1
    inv = jnp.zeros((n,), jnp.int32).at[order].set(
        seg.astype(jnp.int32))
    rep = jax.ops.segment_min(order.astype(jnp.int32), seg,
                              num_segments=n, indices_are_sorted=True)
    return order, seg, rep, inv, seg[-1] + 1


def _route_ids(plan: RowShardPlan, owner_f, local_f, C: int):
    """Bucketize + index all-to-all at per-peer capacity `C`. Slots with
    owner >= nshards (hot / dedup padding) are dropped from the send
    buffer and never consume a real peer's capacity. Returns (recv ids
    (S*C,), valid mask, ranks for the return path)."""
    rank = _bucket_ranks(owner_f)
    slot = owner_f * C + rank
    sentinel = jnp.int32(plan.flat_rows_local)
    send = jnp.full((plan.nshards * C,), sentinel, jnp.int32
                    ).at[slot].set(local_f, mode="drop")
    recv = _a2a(plan, send.reshape(plan.nshards, C)).reshape(-1)
    return recv, recv < sentinel, rank


def _combine_received(rid, rpos, rupd, n_local: int, sentinel: int):
    """THE canonical combine: put received update rows in the order
    every path agrees on. Duplicate rows pre-combine per (row id,
    source device) — a segment sum in ascending-position order, which is
    bitwise what the dedup sender already computed locally — and the
    per-device partial sums come back sorted by their first-occurrence
    global position. Padding (rid == sentinel) sorts last and is
    dropped by the appliers' mode="drop" scatters.

    rid (L,) int32 row ids (sentinel pads); rpos (L,) int32 global
    first-occurrence positions (int32-max pads); rupd (L, d) fp32."""
    L = rid.shape[0]
    o1 = jnp.argsort(rpos)                              # stable
    rid1 = jnp.take(rid, o1)
    rpos1 = jnp.take(rpos, o1)
    rupd1 = jnp.take(rupd, o1, axis=0)
    o2 = jnp.argsort(rid1)          # stable → within rid, pos ascending
    rid2 = jnp.take(rid1, o2)
    rpos2 = jnp.take(rpos1, o2)
    rupd2 = jnp.take(rupd1, o2, axis=0)
    dev2 = rpos2 // jnp.int32(max(n_local, 1))
    first = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                             (rid2[1:] != rid2[:-1])
                             | (dev2[1:] != dev2[:-1])])
    seg = jnp.cumsum(first) - 1
    partial = jax.ops.segment_sum(rupd2, seg, num_segments=L,
                                  indices_are_sorted=True)
    ppos = jax.ops.segment_min(rpos2, seg, num_segments=L,
                               indices_are_sorted=True)
    prid = jax.ops.segment_max(rid2, seg, num_segments=L,
                               indices_are_sorted=True)
    valid = jnp.arange(L) < seg[-1] + 1
    prid = jnp.where(valid, prid, sentinel).astype(jnp.int32)
    ppos = jnp.where(valid, ppos, _INT_MAX).astype(jnp.int32)
    o3 = jnp.argsort(ppos)                              # stable
    return jnp.take(prid, o3), jnp.take(partial, o3, axis=0)


def _hot_combine(plan: RowShardPlan, hot_id, pos, upd, n_local: int):
    """Gather every device's hot-row updates (over ALL mesh axes — each
    device group saw a different batch slice AND hot rows are replicated
    on every shard) and put them in canonical order. All replicas apply
    the identical sequence, staying bitwise in lockstep — and matching
    what the owner shard of a non-hybrid plan would have computed.

    The sender pre-combines per hot id first — bitwise the per-(row,
    source-device) partials the canonical combine forms anyway — so the
    gathered buffer holds DISTINCT hot rows, at capacity
    min(n_local, hot rows): hot traffic is the most duplicate-heavy of
    all, and shipping raw per-lookup rows would make the hybrid's
    update gather scale with batch size again."""
    n = hot_id.shape[0]
    sent = int(plan.hot_rows_flat)
    order, seg, rep, _inv, nuniq = _dedup_keys(hot_id)
    partial = jax.ops.segment_sum(jnp.take(upd, order, axis=0), seg,
                                  num_segments=n,
                                  indices_are_sorted=True)
    upos = jax.ops.segment_min(jnp.take(pos, order), seg,
                               num_segments=n, indices_are_sorted=True)
    safe_rep = jnp.minimum(rep, n - 1)
    valid = jnp.arange(n) < nuniq
    uid = jnp.where(valid, jnp.take(hot_id, safe_rep), sent)
    hotv = valid & (uid < sent)
    upos = jnp.where(hotv, upos, _INT_MAX).astype(jnp.int32)
    uid = jnp.where(hotv, uid, sent).astype(jnp.int32)
    # compact: the sentinel (cold/pad) key sorts LAST, so hot uniques
    # occupy segments 0..k-1 with k <= min(n, hot rows) — truncation
    # only ever drops padding
    C = max(min(n, sent), 1)
    uid, upos, partial = uid[:C], upos[:C], partial[:C]
    ids = jax.lax.all_gather(uid, plan.all_axes, axis=0, tiled=True)
    ps = jax.lax.all_gather(upos, plan.all_axes, axis=0, tiled=True)
    us = jax.lax.all_gather(partial, plan.all_axes, axis=0, tiled=True)
    return _combine_received(ids, ps, us, n_local, sent)


# ---- forward lookup ------------------------------------------------------


def _fwd_rows(plan: RowShardPlan, flat, of, lf, gf):
    """Routed per-lookup rows (n, d) from this shard's flat cold block.
    Slots with owner >= nshards (hot slots under the hybrid placement)
    come back as zeros — the caller overlays their locally-gathered hot
    rows. Under `plan.dedup` only distinct ids travel; results scatter
    back through the inverse map (bitwise identical: a gather is a
    gather, whichever duplicate requested it)."""
    n = of.shape[0]
    d = flat.shape[-1]
    C = plan.capacity(n)
    sentinel = jnp.int32(plan.flat_rows_local)
    if plan.dedup:
        _, _, rep, inv, nuniq = _dedup_keys(gf)
        safe_rep = jnp.minimum(rep, n - 1)
        valid_u = jnp.arange(n) < nuniq
        uof = jnp.where(valid_u, jnp.take(of, safe_rep),
                        jnp.int32(plan.nshards))
        ulf = jnp.where(valid_u, jnp.take(lf, safe_rep), sentinel)
    else:
        uof, ulf, inv = of, lf, None
    recv, valid, rank = _route_ids(plan, uof, ulf, C)
    safe = jnp.minimum(recv, plan.flat_rows_local - 1)
    rows = jnp.take(flat, safe, axis=0)
    rows = jnp.where(valid[:, None], rows, 0.0)
    back = _a2a(plan, rows.reshape(plan.nshards, C, d))
    idx = jnp.minimum(uof, plan.nshards - 1) * C + rank
    mine = jnp.take(back.reshape(plan.nshards * C, d),
                    jnp.minimum(idx, plan.nshards * C - 1), axis=0)
    mine = jnp.where((uof < plan.nshards)[:, None], mine, 0.0)
    if inv is not None:
        mine = jnp.take(mine, inv, axis=0)
    return mine


def row_sharded_bag_lookup(plan: RowShardPlan, table, table_spec,
                           owner, local_id, d: int, aggr: str,
                           block_shape, gid=None,
                           hot_table=None, hot_id=None,
                           hot_block_shape=None):
    """Forward lookup with explicit all-to-all routing.

    table     : global packed kernel (COLD rows), row-sharded per
                `table_spec`
    owner     : (batch, T, bag) int32 — owning shard of each lookup;
                >= nshards marks a HOT slot (served locally, excluded
                from the exchange)
    local_id  : (batch, T, bag) int32 — row id within the owner's flat
                local (flat_rows_local, d) view (sentinel on hot slots)
    gid       : (batch, T, bag) int32 flat global cold id — the dedup
                key (required when plan.dedup)
    hot_table : replicated packed hot block (hybrid placement); hot_id
                the flat hot-row id per lookup (sentinel on cold slots)
    returns   : (batch, T, d) aggregated bags, batch-sharded over the
                whole mesh

    Differentiable: a custom VJP routes output cotangent rows back to
    their owning shards (all-to-all) and scatter-adds them there — and,
    under the hybrid placement, applies hot-row cotangents identically
    on every replica from an all-gather — so even the dense-update
    autodiff path never all-reduces a table-sized gradient."""
    mesh = plan.mesh
    batch_spec = PartitionSpec(plan.all_axes)
    hot = hot_table is not None
    if plan.dedup and gid is None:
        raise ValueError("dedup exchange needs the flat global ids")
    if gid is None:
        gid = local_id   # unused key space; keeps one body signature

    def _aggregate(rows_btb):
        # bag is always the last index dim; aggregate it, keep features
        if aggr == "avg":
            return jnp.mean(rows_btb, axis=-2)
        return jnp.sum(rows_btb, axis=-2)

    if not hot:
        def fwd_body(tbl_blk, ow, lo, gi):
            flat = tbl_blk.reshape(-1, d)
            shape = ow.shape
            mine = _fwd_rows(plan, flat, ow.reshape(-1), lo.reshape(-1),
                             gi.reshape(-1))
            return _aggregate(mine.reshape(shape + (d,)))

        lookup = _smap(fwd_body, mesh,
                       in_specs=(table_spec, batch_spec, batch_spec,
                                 batch_spec),
                       out_specs=batch_spec)

        @jax.custom_vjp
        def _call(tbl, ow, lo, gi):
            return lookup(tbl, ow, lo, gi)

        def _call_fwd(tbl, ow, lo, gi):
            return lookup(tbl, ow, lo, gi), (ow, lo, gi)

        def _call_bwd(res, ct):
            ow, lo, gi = res
            upd = _bag_cotangent_rows(ct, ow.shape, d, aggr)
            body = _scatter_body(plan, d, block_shape, mode="grad")
            grad = _smap(body, mesh,
                         in_specs=(batch_spec,) * 4,
                         out_specs=table_spec)(ow, lo, gi, upd)
            f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # noqa
            return (grad, f0(ow), f0(lo), f0(gi))

        _call.defvjp(_call_fwd, _call_bwd)
        return _call(table, owner, local_id, gid)

    # ---- hybrid (hot/cold) form -----------------------------------------
    hot_spec = PartitionSpec()            # replicated on every device

    def fwd_body_h(tbl_blk, hot_blk, ow, lo, gi, hi):
        flat = tbl_blk.reshape(-1, d)
        hflat = hot_blk.reshape(-1, d)
        shape = ow.shape
        of = ow.reshape(-1)
        hf = hi.reshape(-1)
        cold = _fwd_rows(plan, flat, of, lo.reshape(-1), gi.reshape(-1))
        hrows = jnp.take(hflat, jnp.minimum(hf, plan.hot_rows_flat - 1),
                         axis=0)
        mine = jnp.where((of >= plan.nshards)[:, None], hrows, cold)
        return _aggregate(mine.reshape(shape + (d,)))

    lookup = _smap(fwd_body_h, mesh,
                   in_specs=(table_spec, hot_spec) + (batch_spec,) * 4,
                   out_specs=batch_spec)

    @jax.custom_vjp
    def _call(tbl, htbl, ow, lo, gi, hi):
        return lookup(tbl, htbl, ow, lo, gi, hi)

    def _call_fwd(tbl, htbl, ow, lo, gi, hi):
        return lookup(tbl, htbl, ow, lo, gi, hi), (ow, lo, gi, hi)

    def _call_bwd(res, ct):
        ow, lo, gi, hi = res
        upd = _bag_cotangent_rows(ct, ow.shape, d, aggr)
        body = _scatter_body(plan, d, block_shape, mode="grad",
                             hot_block_shape=hot_block_shape)
        grad, hgrad = _smap(body, mesh,
                            in_specs=(batch_spec,) * 5,
                            out_specs=(table_spec, hot_spec))(
            ow, lo, gi, hi, upd)
        f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)  # noqa
        return (grad, hgrad, f0(ow), f0(lo), f0(gi), f0(hi))

    _call.defvjp(_call_fwd, _call_bwd)
    return _call(table, hot_table, owner, local_id, gid, hot_id)


def _bag_cotangent_rows(ct, idx_shape, d: int, aggr: str):
    """Output cotangent (batch, T, d) -> per-lookup gradient rows
    (batch, T, bag, d): each bag slot receives the bag-sum's cotangent
    (divided by the bag size under AVG)."""
    ct = ct.astype(jnp.float32)
    if aggr == "avg":
        ct = ct / idx_shape[-1]
    return jnp.broadcast_to(ct[..., None, :], tuple(idx_shape) + (d,))


# ---- update routing ------------------------------------------------------


def _route_updates(plan: RowShardPlan, of, lf, gf, uf):
    """-> (rids, rupds) for THIS shard, in canonical order: per-(row,
    source-device) partial sums sorted by first-occurrence global
    position (see _combine_received). Under `plan.dedup` duplicates
    pre-accumulate on the SENDER — bitwise the same segment sums the
    receiver's combine would have formed — so the gradient exchange,
    like the forward one, carries one slot per distinct id."""
    mesh = plan.mesh
    n = of.shape[0]
    d = uf.shape[-1]
    sentinel = jnp.int32(plan.flat_rows_local)
    dev = _device_linear_index(mesh)
    pos = dev * n + jnp.arange(n, dtype=jnp.int32)
    if plan.dedup:
        order, seg, rep, _inv, nuniq = _dedup_keys(gf)
        # per-unique partial sum, accumulated in ascending position —
        # within a segment the stable sort keeps local flatten order
        partial = jax.ops.segment_sum(jnp.take(uf, order, axis=0), seg,
                                      num_segments=n,
                                      indices_are_sorted=True)
        upos = jax.ops.segment_min(jnp.take(pos, order), seg,
                                   num_segments=n,
                                   indices_are_sorted=True)
        safe_rep = jnp.minimum(rep, n - 1)
        valid_u = jnp.arange(n) < nuniq
        s_of = jnp.where(valid_u, jnp.take(of, safe_rep),
                         jnp.int32(plan.nshards))
        s_lf = jnp.where(valid_u, jnp.take(lf, safe_rep), sentinel)
        s_pos = jnp.where(valid_u, upos, _INT_MAX).astype(jnp.int32)
        s_upd = partial
    else:
        s_of, s_lf, s_pos, s_upd = of, lf, pos, uf
    C = plan.capacity(n)
    rank = _bucket_ranks(s_of)
    slot = s_of * C + rank
    send_id = jnp.full((plan.nshards * C,), sentinel, jnp.int32
                       ).at[slot].set(s_lf, mode="drop")
    send_pos = jnp.full((plan.nshards * C,), _INT_MAX, jnp.int32
                        ).at[slot].set(s_pos, mode="drop")
    send_upd = jnp.zeros((plan.nshards * C, d), jnp.float32
                         ).at[slot].set(s_upd.astype(jnp.float32),
                                        mode="drop")
    rid = _a2a(plan, send_id.reshape(plan.nshards, C)).reshape(-1)
    rpos = _a2a(plan, send_pos.reshape(plan.nshards, C)).reshape(-1)
    rupd = _a2a(plan, send_upd.reshape(plan.nshards, C, d)).reshape(-1, d)
    # a row shard is replicated across the non-row axes, whose device
    # groups each saw a different batch slice: gather every group's
    # contributions so all replicas apply the full set (and stay
    # bitwise in lockstep)
    if plan.nonrow_axes:
        rid = jax.lax.all_gather(rid, plan.nonrow_axes, axis=0,
                                 tiled=True)
        rpos = jax.lax.all_gather(rpos, plan.nonrow_axes, axis=0,
                                  tiled=True)
        rupd = jax.lax.all_gather(rupd, plan.nonrow_axes, axis=0,
                                  tiled=True)
    return _combine_received(rid, rpos, rupd, n,
                             int(plan.flat_rows_local))


def _scatter_body(plan: RowShardPlan, d: int, block_shape, mode: str,
                  lr: float = 0.0, opt=None, slab_names=(),
                  hot_block_shape=None):
    """shard_map body routing per-lookup update rows to their owning
    shard and applying them there in canonical order. With a hybrid
    placement (hot_block_shape given), hot slots bypass the exchange:
    their updates all-gather and apply to the replicated hot block
    through the SAME canonical combine.

    mode "grad":  scatter-add combined rows into zeros (the custom-VJP
                  table gradient).
    mode "sgd":   w -= lr * rows, touched rows only (plain-SGD sparse
                  update).
    mode "opt":   stateful touched-rows update (lazy momentum/Adam) via
                  the shared logical-row dedup + optimizer row math.
    """
    mesh = plan.mesh
    sentinel = plan.flat_rows_local
    hot = hot_block_shape is not None
    hot_sent = plan.hot_rows_flat

    def split(ow, lo, gi, hi, upd):
        """Flatten + split one batch's updates into the routed cold
        stream and (hybrid) the gathered hot stream, both in canonical
        combined order."""
        shape = ow.shape
        n = int(np.prod(shape))
        of = ow.reshape(-1)
        lf = lo.reshape(-1)
        gf = gi.reshape(-1)
        uf = upd.reshape(n, d).astype(jnp.float32)
        rid, rupd = _route_updates(plan, of, lf, gf, uf)
        if not hot:
            return rid, rupd, None, None
        dev = _device_linear_index(mesh)
        pos = dev * n + jnp.arange(n, dtype=jnp.int32)
        hf = hi.reshape(-1)
        is_hot = of >= plan.nshards
        hid = jnp.where(is_hot, hf, jnp.int32(hot_sent))
        hpos = jnp.where(is_hot, pos, _INT_MAX).astype(jnp.int32)
        hupd = jnp.where(is_hot[:, None], uf, 0.0)
        hrid, hrupd = _hot_combine(plan, hid, hpos, hupd, n)
        return rid, rupd, hrid, hrupd

    if mode == "grad":
        def body(ow, lo, gi, hi_or_upd, upd=None):
            hi, u = (hi_or_upd, upd) if hot else (None, hi_or_upd)
            rid, rupd, hrid, hrupd = split(ow, lo, gi, hi, u)
            zero = jnp.zeros((sentinel, d), jnp.float32)
            cold = zero.at[rid].add(rupd, mode="drop"
                                    ).reshape(block_shape)
            if not hot:
                return cold
            hzero = jnp.zeros((hot_sent, d), jnp.float32)
            hgrad = hzero.at[hrid].add(hrupd, mode="drop"
                                       ).reshape(hot_block_shape)
            return cold, hgrad
        return body

    if mode == "sgd":
        def body(tbl_blk, *args):
            if hot:
                hot_blk, ow, lo, gi, hi, upd = args
            else:
                (ow, lo, gi, upd), hot_blk, hi = args, None, None
            rid, rupd, hrid, hrupd = split(ow, lo, gi, hi, upd)
            flat = tbl_blk.reshape(-1, d)
            flat = flat.at[rid].add(-lr * rupd.astype(flat.dtype),
                                    mode="drop")
            new = flat.reshape(tbl_blk.shape)
            if not hot:
                return new
            hflat = hot_blk.reshape(-1, d)
            hflat = hflat.at[hrid].add(-lr * hrupd.astype(hflat.dtype),
                                       mode="drop")
            return new, hflat.reshape(hot_blk.shape)
        return body

    if mode == "opt":
        def body(tbl_blk, slab_blks, *args):
            from ..ops.embedding import _stateful_update_rows_xla
            if hot:
                hot_blk, hot_slab_blks, ow, lo, gi, hi, upd, step = args
            else:
                ow, lo, gi, upd, step = args
                hot_blk = hot_slab_blks = hi = None
            rid, rupd, hrid, hrupd = split(ow, lo, gi, hi, upd)
            flat = tbl_blk.reshape(-1, d)
            slabs = {k: v.reshape(-1, d)
                     for k, v in zip(slab_names, slab_blks)}
            new_flat, new_slabs = _stateful_update_rows_xla(
                flat, rid, rupd, opt, slabs, step)
            cold = (new_flat.reshape(tbl_blk.shape),
                    tuple(new_slabs[k].reshape(tbl_blk.shape)
                          for k in slab_names))
            if not hot:
                return cold
            hflat = hot_blk.reshape(-1, d)
            hslabs = {k: v.reshape(-1, d)
                      for k, v in zip(slab_names, hot_slab_blks)}
            nh, nhs = _stateful_update_rows_xla(hflat, hrid, hrupd, opt,
                                                hslabs, step)
            return cold + (nh.reshape(hot_blk.shape),
                           tuple(nhs[k].reshape(hot_blk.shape)
                                 for k in slab_names))
        return body

    raise ValueError(f"unknown scatter mode {mode!r}")


def row_sharded_sgd_update(plan: RowShardPlan, table, table_spec,
                           owner, local_id, upd, lr: float, d: int,
                           gid=None, hot_table=None, hot_id=None):
    """Touched-rows plain-SGD update with all-to-all gradient-row
    routing: each shard applies -lr * (its rows' combined updates), in
    canonical order. `upd` is (batch, T, bag, d) RAW gradient rows.
    With a hybrid placement returns (new_table, new_hot_table)."""
    batch_spec = PartitionSpec(plan.all_axes)
    if gid is None:
        gid = local_id
    hot = hot_table is not None
    body = _scatter_body(plan, d, None, mode="sgd", lr=float(lr),
                         hot_block_shape=(() if hot else None))
    if not hot:
        return _smap(body, plan.mesh,
                     in_specs=(table_spec,) + (batch_spec,) * 4,
                     out_specs=table_spec)(table, owner, local_id, gid,
                                           upd)
    hot_spec = PartitionSpec()
    new, new_hot = _smap(
        body, plan.mesh,
        in_specs=(table_spec, hot_spec) + (batch_spec,) * 5,
        out_specs=(table_spec, hot_spec))(table, hot_table, owner,
                                          local_id, gid, hot_id, upd)
    return new, new_hot


def row_sharded_opt_update(plan: RowShardPlan, table, slabs, table_spec,
                           owner, local_id, upd, opt, step, d: int,
                           gid=None, hot_table=None, hot_slabs=None,
                           hot_id=None):
    """Stateful (lazy momentum/Adam) touched-rows update with
    all-to-all routing; optimizer state slabs are sharded exactly like
    the kernel, so state rows never leave their shard. With a hybrid
    placement the replicated hot block (and its slabs) updates in
    lockstep from the all-gathered hot stream; returns
    (new_tbl, new_slabs[, new_hot, new_hot_slabs])."""
    slab_names = tuple(sorted(slabs))
    batch_spec = PartitionSpec(plan.all_axes)
    if gid is None:
        gid = local_id
    hot = hot_table is not None
    body = _scatter_body(plan, d, None, mode="opt", opt=opt,
                         slab_names=slab_names,
                         hot_block_shape=(() if hot else None))
    if not hot:
        new_tbl, new_slab_vals = _smap(
            body, plan.mesh,
            in_specs=(table_spec, (table_spec,) * len(slab_names),
                      batch_spec, batch_spec, batch_spec, batch_spec,
                      PartitionSpec()),
            out_specs=(table_spec, (table_spec,) * len(slab_names)),
        )(table, tuple(slabs[k] for k in slab_names), owner, local_id,
          gid, upd, step)
        return new_tbl, dict(zip(slab_names, new_slab_vals))
    hot_spec = PartitionSpec()
    new_tbl, new_slab_vals, new_hot, new_hot_vals = _smap(
        body, plan.mesh,
        in_specs=(table_spec, (table_spec,) * len(slab_names),
                  hot_spec, (hot_spec,) * len(slab_names),
                  batch_spec, batch_spec, batch_spec, batch_spec,
                  batch_spec, PartitionSpec()),
        out_specs=(table_spec, (table_spec,) * len(slab_names),
                   hot_spec, (hot_spec,) * len(slab_names)),
    )(table, tuple(slabs[k] for k in slab_names),
      hot_table, tuple(hot_slabs[k] for k in slab_names),
      owner, local_id, gid, hot_id, upd, step)
    return (new_tbl, dict(zip(slab_names, new_slab_vals)),
            new_hot, dict(zip(slab_names, new_hot_vals)))


# ---- accounting ----------------------------------------------------------


def _exchange_buffer_blocks(plan: RowShardPlan) -> int:
    """Per-peer block count of the exchange buffers ONE device actually
    SENDS: the fused all-to-all (and the chunked multi-axis pipelined
    form, which moves identical bytes) ships all S blocks including the
    device's own; the single-axis ppermute ring keeps the self block
    local, so only S-1 blocks travel. The HLO byte predictions below
    must match the lowered program instruction for instruction, so they
    account for which form `_a2a` lowers."""
    if plan.overlap and len(plan.row_axes) == 1 and plan.nshards > 1:
        return plan.nshards - 1
    return plan.nshards


def _hlo_exchange_bytes(plan: RowShardPlan, C: int, d: int,
                        table_itemsize: int) -> int:
    S = _exchange_buffer_blocks(plan)
    fwd = S * C * 4 + S * C * d * table_itemsize
    bwd = S * C * 4 + S * C * 4 + S * C * d * 4
    return int(fwd + bwd)


def dense_exchange_hlo_bytes(plan: RowShardPlan, lookups_global: int,
                             d: int, table_itemsize: int = 4) -> int:
    """Exchange buffer bytes ONE device sends per step under the DENSE
    padded exchange this jax implementation actually lowers — what the
    HLO auditor must find in the partitioned program, instruction for
    instruction: request ids out (S x C int32), embedded rows back
    (S x C x d at the table dtype), then the gradient path's id + global-
    position + fp32 update-row exchanges. C (slot capacity per peer) is
    the full local lookup count n_local — the always-exact worst case —
    so the dense exchange moves S x the BALANCED bytes the cost model
    prices (`exchange_bytes_per_step`); the drift report shows both.
    Under the single-axis pipelined exchange (`plan.overlap`) the self
    block never travels, so S drops to nshards-1 and the bytes land in
    the collective-permute bucket instead of all-to-all — the auditor
    folds the buckets together (analysis/hlo_audit.py)."""
    n_local = int(lookups_global) // max(plan.ndev, 1)
    return _hlo_exchange_bytes(plan, n_local, d, table_itemsize)


def dedup_exchange_hlo_bytes(plan: RowShardPlan, lookups_global: int,
                             d: int, table_itemsize: int = 4) -> int:
    """The dedup'd sibling of :func:`dense_exchange_hlo_bytes`: the
    unique-ids exchange lowers the same four exchanges but at per-peer
    capacity C = min(n_local, flat_rows_local) — after dedup an owner
    can never receive more DISTINCT requests than it has rows, so the
    padded buffers shrink exactly when duplicates are structurally
    guaranteed. Deterministic, so FLX513 can pin predicted == lowered
    on the dedup plan too (overlap-aware like the dense form)."""
    n_local = int(lookups_global) // max(plan.ndev, 1)
    return _hlo_exchange_bytes(plan, plan.capacity(n_local), d,
                               table_itemsize)


def exchange_bytes_per_step(plan: RowShardPlan, lookups_global: int,
                            d: int, itemsize: int = 4,
                            backward: bool = True,
                            distinct_per_device: Optional[float] = None
                            ) -> int:
    """All-to-all bytes ONE device moves per step under the BALANCED
    (ragged / production) exchange: request ids out, embedded rows
    back, and (backward) gradient rows out again — each (P-1)/P of the
    device's routed share. `distinct_per_device` overrides the per-
    device routed count (the dedup'd exchange routes DISTINCT ids —
    pass the measured or expected count so reported bytes scale with
    skew, not batch size). What bench_shard reports and the cost model
    prices."""
    n_dev = lookups_global / max(plan.ndev, 1)
    if distinct_per_device is not None:
        n_dev = float(distinct_per_device)
    frac = (plan.nshards - 1) / plan.nshards
    fwd = n_dev * frac * (4 + d * itemsize)
    bwd = n_dev * frac * (4 + d * 4) if backward else 0.0
    return int(fwd + bwd)
