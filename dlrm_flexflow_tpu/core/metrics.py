"""Training metrics.

Parity with the reference PerfMetrics (reference:
include/metrics_functions.h:26-40, src/runtime/metrics_functions.cu:57-262):
train_all / train_correct (accuracy), cce, sparse_cce, mse, rmse, mae.

TPU-native redesign: the reference accumulates per-partition metrics with
device atomics into a `PerfMetrics` struct returned as a Legion future, then
folds futures in a CPU task (model.cc:1182-1205) so metrics never block the
train loop. Here metrics are computed inside the jitted train step as sharded
reductions (XLA inserts the cross-chip psum) and returned as device arrays;
asynchronous dispatch gives the same never-blocks property — the host only
syncs when it prints (utils/logging.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

import jax.numpy as jnp

METRICS_ACCURACY = "accuracy"
METRICS_CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
METRICS_SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
METRICS_MEAN_SQUARED_ERROR = "mean_squared_error"
METRICS_ROOT_MEAN_SQUARED_ERROR = "root_mean_squared_error"
METRICS_MEAN_ABSOLUTE_ERROR = "mean_absolute_error"

_ALIASES = {
    "acc": METRICS_ACCURACY,
    "mse": METRICS_MEAN_SQUARED_ERROR,
    "rmse": METRICS_ROOT_MEAN_SQUARED_ERROR,
    "mae": METRICS_MEAN_ABSOLUTE_ERROR,
    "cce": METRICS_CATEGORICAL_CROSSENTROPY,
    "scce": METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
}

ALL_METRICS = (METRICS_ACCURACY, METRICS_CATEGORICAL_CROSSENTROPY,
               METRICS_SPARSE_CATEGORICAL_CROSSENTROPY,
               METRICS_MEAN_SQUARED_ERROR, METRICS_ROOT_MEAN_SQUARED_ERROR,
               METRICS_MEAN_ABSOLUTE_ERROR)


def canonical_metrics(names: List[str]) -> List[str]:
    out = []
    for n in names:
        n = _ALIASES.get(n.lower(), n.lower())
        if n not in ALL_METRICS:
            raise ValueError(f"unknown metric: {n}")
        out.append(n)
    return out


def compute_metrics(metrics: List[str], loss_type: str, preds, labels) -> Dict[str, jnp.ndarray]:
    """Per-batch *sums* (plus count) so epochs accumulate exactly like the
    reference's PerfMetrics::update (metrics_functions.cc)."""
    out: Dict[str, jnp.ndarray] = {}
    preds32 = preds.astype(jnp.float32)
    labels32 = labels.astype(jnp.float32)
    batch = preds.shape[0]
    out["train_all"] = jnp.asarray(batch, jnp.float32)

    sparse = "sparse" in loss_type
    for m in metrics:
        if m == METRICS_ACCURACY:
            if sparse:
                lab = labels.astype(jnp.int32).reshape(-1)
                correct = (jnp.argmax(preds32.reshape(-1, preds32.shape[-1]),
                                      axis=-1) == lab)
            elif preds32.shape[-1] == 1:
                # regression-style accuracy: rounded prediction (reference
                # metrics_functions.cu accuracy for MSE-style labels)
                correct = jnp.abs(preds32 - labels32).reshape(batch, -1).max(axis=-1) < 0.5
            else:
                correct = (jnp.argmax(preds32, axis=-1)
                           == jnp.argmax(labels32, axis=-1))
            out["train_correct"] = jnp.sum(correct.astype(jnp.float32))
        elif m == METRICS_SPARSE_CATEGORICAL_CROSSENTROPY:
            lab = labels.astype(jnp.int32).reshape(-1)
            logp = jnp.log(jnp.clip(preds32.reshape(-1, preds32.shape[-1]),
                                    1e-12, None))
            out["sparse_cce"] = -jnp.sum(
                jnp.take_along_axis(logp, lab[:, None], axis=-1))
        elif m == METRICS_CATEGORICAL_CROSSENTROPY:
            logp = jnp.log(jnp.clip(preds32, 1e-12, None))
            out["cce"] = -jnp.sum(labels32 * logp)
        elif m == METRICS_MEAN_SQUARED_ERROR:
            out["mse"] = jnp.sum(
                jnp.square(preds32 - labels32).reshape(batch, -1).sum(-1))
        elif m == METRICS_ROOT_MEAN_SQUARED_ERROR:
            out["rmse"] = jnp.sum(jnp.sqrt(
                jnp.square(preds32 - labels32).reshape(batch, -1).sum(-1)))
        elif m == METRICS_MEAN_ABSOLUTE_ERROR:
            out["mae"] = jnp.sum(
                jnp.abs(preds32 - labels32).reshape(batch, -1).sum(-1))
    return out


@dataclass
class PerfMetrics:
    """Host-side accumulator folding per-step metric sums, mirroring the
    reference UPDATE_METRICS_TASK fold (model.cc:1182-1205)."""

    sums: Dict[str, float] = field(default_factory=dict)

    def update(self, step_metrics: Dict[str, jnp.ndarray]):
        # accumulate device arrays without forcing a host sync — additions
        # dispatch asynchronously; only report()/summary_line() sync (the
        # reference's future-chain has the same property, model.cc:1182-1205)
        for k, v in step_metrics.items():
            prev = self.sums.get(k)
            self.sums[k] = v if prev is None else prev + v

    def reset(self):
        self.sums.clear()

    def _host_sums(self) -> Dict[str, float]:
        return {k: float(v) for k, v in self.sums.items()}

    def report(self) -> Dict[str, float]:
        self.sums = dict(self._host_sums())
        n = max(self.sums.get("train_all", 0.0), 1.0)
        out = {}
        for k, v in self.sums.items():
            if k == "train_all":
                out[k] = v
            elif k == "train_correct":
                out["accuracy"] = v / n
            else:
                out[k] = v / n
        return out

    def summary_line(self) -> str:
        rep = self.report()
        parts = []
        if "accuracy" in rep:
            parts.append(f"accuracy={rep['accuracy'] * 100.0:.2f}%"
                         f" ({int(self.sums.get('train_correct', 0))}"
                         f"/{int(self.sums.get('train_all', 0))})")
        for k in ("cce", "sparse_cce", "mse", "rmse", "mae"):
            if k in rep:
                parts.append(f"{k}={rep[k]:.6f}")
        return "[Metrics] " + " ".join(parts)
