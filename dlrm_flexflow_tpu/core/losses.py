"""Loss functions.

Parity with the reference Loss (reference: include/loss_functions.h:39-41,
src/runtime/loss_functions.cu:37-73): sparse categorical cross-entropy,
categorical cross-entropy, and mean-squared-error, all scaled by
1/global_batch (the reference writes logit gradients scaled by
`scale_factor = 1.0f / global_batch`; here the same scaling falls out of
taking `mean` over the batch and letting jax.grad differentiate).

The reference computes loss *gradients* only (backward-only task); the loss
value itself is reported via Metrics. We expose scalar loss values (needed by
jax.grad) and get the identical gradients by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LOSS_CATEGORICAL_CROSSENTROPY = "categorical_crossentropy"
LOSS_SPARSE_CATEGORICAL_CROSSENTROPY = "sparse_categorical_crossentropy"
LOSS_MEAN_SQUARED_ERROR = "mean_squared_error"
# aliases accepted by the python frontend of the reference
_ALIASES = {
    "mse": LOSS_MEAN_SQUARED_ERROR,
    "mean_squared_error_avg_reduce": LOSS_MEAN_SQUARED_ERROR,
    "cce": LOSS_CATEGORICAL_CROSSENTROPY,
    "scce": LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
}


def canonical_loss(name: str) -> str:
    name = name.lower()
    name = _ALIASES.get(name, name)
    if name not in (LOSS_CATEGORICAL_CROSSENTROPY,
                    LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                    LOSS_MEAN_SQUARED_ERROR):
        raise ValueError(f"unknown loss type: {name}")
    return name


def sparse_categorical_crossentropy(logits, labels):
    """labels: any int shape whose element count equals the number of logit
    rows (e.g. [batch], [batch, 1], or [batch, seq] against folded
    [batch*seq, classes] logits as in NMT); logits: float[..., classes].

    Reference kernel sparse_categorical_crossentropy_loss_backward writes
    softmax(logits) - onehot(label); grad of this fn reproduces it.
    """
    logits2 = logits.reshape(-1, logits.shape[-1])
    labels = labels.astype(jnp.int32).reshape(-1)
    logp = jax.nn.log_softmax(logits2.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(nll)


def categorical_crossentropy(logits, labels):
    """Dense one-hot labels float[batch, classes]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def mean_squared_error(preds, labels):
    """Reference mseloss_backward: grad = 2*(pred-label)/batch ⇒ loss = mean
    over batch of the summed squared error per sample."""
    d = preds.astype(jnp.float32) - labels.astype(jnp.float32)
    per_sample = jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=-1)
    return jnp.mean(per_sample)


def loss_fn(loss_type: str):
    loss_type = canonical_loss(loss_type)
    return {
        LOSS_SPARSE_CATEGORICAL_CROSSENTROPY: sparse_categorical_crossentropy,
        LOSS_CATEGORICAL_CROSSENTROPY: categorical_crossentropy,
        LOSS_MEAN_SQUARED_ERROR: mean_squared_error,
    }[loss_type]
