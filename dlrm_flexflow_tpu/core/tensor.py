"""Symbolic tensors for the FFModel graph.

TPU-native re-design of the reference Tensor (reference: include/model.h:181-217,
src/runtime/model.cc:457-553). In the reference a Tensor owns Legion logical
regions (data + grad) and an equal-block partition derived from a
ParallelConfig. Here a Tensor is a *symbolic* node in a functional graph:
concrete values live in JAX arrays whose sharding is derived from the op's
ParallelConfig at compile time (GSPMD), and gradients come from jax.grad —
no explicit grad regions are needed.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional, TYPE_CHECKING

import jax.numpy as jnp

if TYPE_CHECKING:
    from .op import Op

_tensor_guid = itertools.count(1000)

# Reference supports max 4-d (5 with MAX_TENSOR_DIM build flag,
# python/Makefile:44). We keep the same ceiling for strategy compatibility.
MAX_TENSOR_DIM = 5


@dataclass
class Tensor:
    """A node in the model graph: static shape + dtype + producing op.

    `shape` follows the reference convention with the sample (batch) dim
    first for activations (model.cc:457-553 builds regions with the sample
    dim outermost).
    """

    shape: tuple
    dtype: jnp.dtype = jnp.float32
    owner_op: Optional["Op"] = None
    owner_idx: int = 0
    name: str = ""
    guid: int = field(default_factory=lambda: next(_tensor_guid))
    # physical in-memory layout of the concrete array, when it differs
    # from the logical `shape` order: None = logical, "nhwc" = a rank-4
    # NCHW-logical tensor stored NHWC (the TPU-native conv layout; convs
    # produce it, consumers either accept it or transpose back — see
    # FFModel._forward_env)
    physical: Optional[str] = None

    def __post_init__(self):
        self.shape = tuple(int(d) for d in self.shape)
        if len(self.shape) > MAX_TENSOR_DIM:
            raise ValueError(
                f"Tensor rank {len(self.shape)} exceeds MAX_TENSOR_DIM="
                f"{MAX_TENSOR_DIM} (reference python/Makefile:44)")
        if not self.name:
            self.name = f"tensor_{self.guid}"

    @property
    def num_dims(self) -> int:
        return len(self.shape)

    def __hash__(self):
        return hash(self.guid)

    def __eq__(self, other):
        return isinstance(other, Tensor) and other.guid == self.guid

    def __repr__(self):
        return (f"Tensor(name={self.name!r}, shape={self.shape}, "
                f"dtype={jnp.dtype(self.dtype).name}, "
                f"op={self.owner_op.name if self.owner_op else None})")
