"""Parameter initializers.

Parity with the reference initializer set (reference: include/initializer.h:26-100,
src/runtime/initializer.cc, initializer_kernel.cu): GlorotUniform, Zero,
Uniform, Normal, Constant. The reference runs each as a curand GPU task; here
each is a pure function of a jax PRNG key, executed on-device by XLA at
`FFModel.init_layers()` time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Initializer:
    def __call__(self, key, shape, dtype=jnp.float32):
        raise NotImplementedError


class GlorotUniform(Initializer):
    """Glorot/Xavier uniform (reference initializer.cc GlorotUniform::init_task).

    The reference computes fan-in/fan-out from the last two dims of the
    weight region (initializer_kernel.cu glorot path); we follow the same
    convention: limit = sqrt(6 / (fan_in + fan_out)).
    """

    def __init__(self, seed: int = 0):
        self.seed = seed

    def __call__(self, key, shape, dtype=jnp.float32):
        if len(shape) >= 3:
            # conv-style OIHW kernel: fans scale with the receptive field
            # (reference initializer_kernel.cu rank-3/4 path:
            # fan = channels x receptive_field)
            receptive = 1
            for d in shape[2:]:
                receptive *= d
            fan_in, fan_out = shape[1] * receptive, shape[0] * receptive
        elif len(shape) == 2:
            fan_in, fan_out = shape[0], shape[1]
        else:
            fan_in = fan_out = shape[0]
        limit = (6.0 / (fan_in + fan_out)) ** 0.5
        return jax.random.uniform(key, shape, dtype, -limit, limit)


class ZeroInitializer(Initializer):
    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class UniformInitializer(Initializer):
    def __init__(self, seed: int = 0, min_val: float = -0.05, max_val: float = 0.05):
        self.seed = seed
        self.min_val = float(min_val)
        self.max_val = float(max_val)

    def __call__(self, key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, self.min_val, self.max_val)


class NormInitializer(Initializer):
    def __init__(self, seed: int = 0, mean: float = 0.0, stddev: float = 1.0):
        self.seed = seed
        self.mean = float(mean)
        self.stddev = float(stddev)

    def __call__(self, key, shape, dtype=jnp.float32):
        return self.mean + self.stddev * jax.random.normal(key, shape, dtype)


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = float(value)

    def __call__(self, key, shape, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


DEFAULT_KERNEL_INIT = GlorotUniform
DEFAULT_BIAS_INIT = ZeroInitializer
