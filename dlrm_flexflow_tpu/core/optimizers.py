"""Optimizers: SGD (momentum/nesterov/weight-decay) and Adam.

Parity with the reference optimizers (reference: include/optimizer.h:26-73,
src/runtime/optimizer.cc:75-102, src/runtime/optimizer_kernel.cu:22-236).

TPU-native redesign: the reference launches one Legion task per parameter
whose region requirement gathers all data-parallel gradient replicas and sums
the first `num_replicas` on-device before the update kernel
(optimizer_kernel.cu:98-104). Under GSPMD that replica-gather + sum is the
`psum` XLA inserts for sharded-batch gradients automatically; the update
itself is the pure functions below, jitted and sharded like the parameters
(a ZeRO-like sharded update falls out of the parameter sharding spec).

State is a pytree mirroring the parameter pytree, so it shards identically.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


class Optimizer:
    """Stateless descriptor + pure (init, update) functions."""

    def init_state(self, params) -> Any:
        raise NotImplementedError

    def update(self, params, grads, state):
        """Returns (new_params, new_state)."""
        raise NotImplementedError

    def hyperparams(self) -> Dict[str, float]:
        raise NotImplementedError

    # ---- sparse (touched-rows-only) embedding support ----------------
    # The reference updates embedding tables densely (one update task
    # streaming the whole table + its table-sized gradient region,
    # optimizer_kernel.cu:22-236). Here eligible embeddings take a
    # touched-rows-only update (ops/embedding.py); stateful optimizers
    # participate via the two hooks below with LAZY semantics (torch
    # SparseAdam-style): state rows update only when their row is
    # touched, and weight decay applies lazily on touch. Within a step
    # the result on touched rows is EXACTLY the dense update (duplicate
    # lookups are pre-summed into one gradient row by the caller).

    def sparse_slab_names(self) -> tuple:
        """State slabs (table-shaped arrays) the sparse path must carry."""
        return ()

    def sparse_row_update(self, w, g, slabs, touched, step):
        """Update gathered rows: w, g (m, k) float32; slabs {name: (m, k)};
        touched (m, k) bool — lanes of w belonging to rows that received
        gradient (lane-packed tiles hold several logical rows; untouched
        lanes must pass through unchanged). step = pre-increment scalar.
        Returns (new_w, new_slabs)."""
        raise NotImplementedError

    def sparse_row_update_np(self, w, g, slabs, step):
        """Numpy twin of sparse_row_update for HOST-resident tables (all
        rows pre-deduped/touched; pure host math, never touches the
        accelerator). Returns (new_w, new_slabs)."""
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    """SGD with momentum / nesterov / weight decay.

    Update rule matches the reference kernel (optimizer_kernel.cu sgd_update):
        gt = g + weight_decay * w
        v  = momentum * v + gt
        d  = gt + momentum * v   (nesterov)   |   v   (classic)   |   gt (no momentum)
        w -= lr * d
    """

    def __init__(self, lr=0.01, momentum=0.0, nesterov=False, weight_decay=0.0):
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.nesterov = bool(nesterov)
        self.weight_decay = float(weight_decay)

    def hyperparams(self):
        return dict(lr=self.lr, momentum=self.momentum,
                    nesterov=self.nesterov, weight_decay=self.weight_decay)

    def init_state(self, params):
        if self.momentum > 0.0:
            return {"v": jax.tree.map(jnp.zeros_like, params)}
        return {}

    def update(self, params, grads, state):
        lr, m, wd = self.lr, self.momentum, self.weight_decay

        if m > 0.0:
            def upd(w, g, v):
                gt = g + wd * w if wd > 0.0 else g
                v = m * v + gt
                d = gt + m * v if self.nesterov else v
                return (w - lr * d).astype(w.dtype), v

            flat = jax.tree.map(upd, params, grads, state["v"])
            new_params = jax.tree.map(lambda t: t[0], flat,
                                      is_leaf=lambda t: isinstance(t, tuple))
            new_v = jax.tree.map(lambda t: t[1], flat,
                                 is_leaf=lambda t: isinstance(t, tuple))
            return new_params, {"v": new_v}

        def upd_plain(w, g):
            gt = g + wd * w if wd > 0.0 else g
            return (w - lr * gt).astype(w.dtype)

        return jax.tree.map(upd_plain, params, grads), state

    def sparse_slab_names(self):
        return ("v",) if self.momentum > 0.0 else ()

    def sparse_row_update(self, w, g, slabs, touched, step):
        lr, m, wd = self.lr, self.momentum, self.weight_decay
        gt = g + wd * w * touched if wd > 0.0 else g
        if m > 0.0:
            v = slabs["v"]
            vn = jnp.where(touched, m * v + gt, v)
            d = gt + m * vn if self.nesterov else vn
            return jnp.where(touched, w - lr * d, w), {"v": vn}
        return jnp.where(touched, w - lr * gt, w), {}

    def sparse_row_update_np(self, w, g, slabs, step):
        lr, m, wd = self.lr, self.momentum, self.weight_decay
        gt = g + wd * w if wd > 0.0 else g
        if m > 0.0:
            vn = m * slabs["v"] + gt
            d = gt + m * vn if self.nesterov else vn
            return w - lr * d, {"v": vn}
        return w - lr * gt, {}


class AdamOptimizer(Optimizer):
    """Adam (reference optimizer_kernel.cu adam_update, optimizer.cc AdamOptimizer).

    The reference carries running beta1_t/beta2_t powers updated by next()
    each step and folds the bias correction into alpha_t =
    alpha * sqrt(1-beta2_t) / (1-beta1_t); we keep an integer step count and
    compute the same alpha_t inside the jitted update.
    """

    def __init__(self, alpha=0.001, beta1=0.9, beta2=0.999,
                 weight_decay=0.0, epsilon=1e-8):
        self.alpha = float(alpha)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)
        self.weight_decay = float(weight_decay)
        self.epsilon = float(epsilon)

    def hyperparams(self):
        return dict(alpha=self.alpha, beta1=self.beta1, beta2=self.beta2,
                    weight_decay=self.weight_decay, epsilon=self.epsilon)

    def init_state(self, params):
        return {
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32),
        }

    def update(self, params, grads, state):
        step = state["step"] + 1
        t = step.astype(jnp.float32)
        alpha_t = (self.alpha * jnp.sqrt(1.0 - self.beta2 ** t)
                   / (1.0 - self.beta1 ** t))
        wd, b1, b2, eps = self.weight_decay, self.beta1, self.beta2, self.epsilon

        def upd(w, g, m_, v_):
            gt = g + wd * w if wd > 0.0 else g
            m_ = b1 * m_ + (1.0 - b1) * gt
            v_ = b2 * v_ + (1.0 - b2) * gt * gt
            w = w - alpha_t * m_ / (jnp.sqrt(v_) + eps)
            return w.astype(g.dtype), m_, v_

        flat = jax.tree.map(upd, params, grads, state["m"], state["v"])
        is_triple = lambda t_: isinstance(t_, tuple)
        new_params = jax.tree.map(lambda t_: t_[0], flat, is_leaf=is_triple)
        new_m = jax.tree.map(lambda t_: t_[1], flat, is_leaf=is_triple)
        new_v = jax.tree.map(lambda t_: t_[2], flat, is_leaf=is_triple)
        return new_params, {"m": new_m, "v": new_v, "step": step}

    def sparse_slab_names(self):
        return ("m", "v")

    def sparse_row_update_np(self, w, g, slabs, step):
        import numpy as np
        t = float(step) + 1.0
        alpha_t = (self.alpha * np.sqrt(1.0 - self.beta2 ** t)
                   / (1.0 - self.beta1 ** t))
        wd, b1, b2, eps = (self.weight_decay, self.beta1, self.beta2,
                           self.epsilon)
        gt = g + wd * w if wd > 0.0 else g
        mn = b1 * slabs["m"] + (1.0 - b1) * gt
        vn = b2 * slabs["v"] + (1.0 - b2) * gt * gt
        return w - alpha_t * mn / (np.sqrt(vn) + eps), {"m": mn, "v": vn}

    def sparse_row_update(self, w, g, slabs, touched, step):
        t = (step + 1).astype(jnp.float32)
        alpha_t = (self.alpha * jnp.sqrt(1.0 - self.beta2 ** t)
                   / (1.0 - self.beta1 ** t))
        wd, b1, b2, eps = (self.weight_decay, self.beta1, self.beta2,
                           self.epsilon)
        gt = g + wd * w * touched if wd > 0.0 else g
        m_, v_ = slabs["m"], slabs["v"]
        mn = jnp.where(touched, b1 * m_ + (1.0 - b1) * gt, m_)
        vn = jnp.where(touched, b2 * v_ + (1.0 - b2) * gt * gt, v_)
        wn = jnp.where(touched, w - alpha_t * mn / (jnp.sqrt(vn) + eps), w)
        return wn, {"m": mn, "v": vn}
