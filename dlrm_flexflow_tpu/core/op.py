"""Operator base class.

Parity with the reference `Op` (reference: include/model.h:240-281,
src/runtime/model.cc:256-372): ops are named "<Type>_<guid>" (the name is the
strategy key), own their input/output tensors and parameters, and expose
shape/partition queries used by the auto-parallelizer.

TPU-native redesign: the reference Op carries Legion index spaces and
launches CUDA tasks for init/forward/backward. Here an Op is a pure-function
factory: `apply(params, inputs)` returns outputs and is traced once into the
jitted train step; backward comes from jax.grad; "init" is parameter
initialization. Per-op parallelization is a ParallelConfig lowered to GSPMD
shardings (parallel/sharding.py) instead of a Legion partition + mapper
routing.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .initializers import Initializer
from .tensor import Tensor
from ..parallel.pconfig import ParallelConfig


@dataclass
class ParamDef:
    shape: tuple
    dtype: Any
    initializer: Initializer


class Op:
    """Base operator. Subclasses set `type_name`, build `self.outputs` in
    __init__, and implement `apply` (+ optionally param_defs / shardings /
    flops overrides)."""

    type_name: str = "Op"

    def __init__(self, model, inputs: Sequence[Tensor], name: Optional[str] = None):
        self.model = model
        self.guid = model._next_op_guid()
        # reference op ctors name ops "<Name>_<guid>" (model.cc Op::Op);
        # that name keys the parallelization strategy (strategy.cc:23-26)
        self.name = name or f"{self.type_name}_{self.guid}"
        self.inputs: List[Tensor] = list(inputs)
        self.outputs: List[Tensor] = []

    # ---- graph construction helpers -------------------------------------
    def _make_output(self, shape, dtype=jnp.float32, idx: int = 0) -> Tensor:
        # registration happens on first output creation, AFTER the subclass
        # constructor validated its inputs — a throwing constructor leaves
        # no half-built op in the graph
        if not getattr(self, "_registered", False):
            self.model._register_op(self)
            self._registered = True
        t = Tensor(tuple(shape), dtype, owner_op=self, owner_idx=idx,
                   name=f"{self.name}_out{idx}")
        return t

    # ---- parameters ------------------------------------------------------
    def param_defs(self) -> Dict[str, ParamDef]:
        """Parameter name -> ParamDef. Empty for stateless ops."""
        return {}

    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        defs = self.param_defs()
        if not defs:
            return {}
        keys = jax.random.split(key, len(defs))
        return {n: d.initializer(k, d.shape, d.dtype)
                for (n, d), k in zip(sorted(defs.items()), keys)}

    # ---- execution -------------------------------------------------------
    def apply(self, params: Dict[str, jnp.ndarray], xs: List[jnp.ndarray], *,
              training: bool = False, rng=None) -> List[jnp.ndarray]:
        raise NotImplementedError

    # ---- parallelization -------------------------------------------------
    def default_parallel_config(self, num_devices: int) -> ParallelConfig:
        """Data parallelism over the sample dim (reference
        Op::get_data_parallel_config, model.cc:282-293)."""
        return ParallelConfig.data_parallel(self.outputs[0].num_dims, num_devices)

    def candidate_parallel_configs(self, num_devices: int,
                                   feasible_degrees: List[int]) -> List[ParallelConfig]:
        """Enumeration used by the MCMC search (reference
        Op::get_random_parallel_config, model.cc:295-324, draws a random
        factorization of a random device count over the output dims).
        Default: sample-dim DP at every feasible degree."""
        out = []
        nd = self.outputs[0].num_dims
        for d in feasible_degrees:
            if d <= num_devices:
                degs = [1] * nd
                degs[0] = d
                out.append(ParallelConfig(tuple(degs)))
        return out

    def feasible_parallel_configs(self, num_devices: int,
                                  feasible_degrees: List[int]) -> List[ParallelConfig]:
        """candidate_parallel_configs filtered by real divisibility of the
        output shape AND joint mesh-axis assignability, so the config the
        search costs is exactly the config compile() executes
        (Model._effective_pc never clamps it and _build_shardings never
        falls back to replication)."""
        shape = self.outputs[0].shape
        from ..parallel.sharding import AxisAssigner, assignable
        assigner = None
        mesh = getattr(self.model, "mesh", None)
        if mesh is not None and mesh.size == num_devices:
            assigner = AxisAssigner(mesh)
            axis_sizes = list(assigner.axis_sizes)
        else:
            # no live mesh, or searching for a DIFFERENT target device
            # count than the attached mesh (offline planning): use the
            # factorization make_mesh would build for the target
            from ..parallel.mesh import structural_axis_sizes
            axis_sizes = structural_axis_sizes(num_devices)
        out = []
        for pc in self.candidate_parallel_configs(num_devices,
                                                  feasible_degrees):
            degs = pc.degrees[:len(shape)]
            if not all(d == 1 or shape[i] % d == 0
                       for i, d in enumerate(degs)):
                continue
            # the PARAM-axis (row-shard) degree must factorize the mesh
            # on its own (it consumes axes independently of the output
            # degrees — rows and batch may share axes)
            pd = getattr(pc, "param_degree", 1)
            if pd > 1 and not assignable((pd,), axis_sizes):
                continue
            # per-dim degrees can each be expressible yet not jointly
            # assignable (they consume mesh axes in order)
            if assigner is not None:
                try:
                    self.output_axes(pc, assigner)
                except ValueError:
                    continue
            elif not assignable(pc.degrees, axis_sizes):
                continue
            out.append(pc)
        return out

    # True when the op interprets its strategy's RAW degrees itself (e.g.
    # the concat-embedding row-shards its table on ANY requested table
    # parallelism even when the output dim can't split evenly) — the
    # _effective_pc clamp is then expected, not a misconfiguration
    raw_degree_semantics: bool = False

    def output_axes(self, pc: ParallelConfig, assigner, raw_pc=None):
        """Mesh axes per output dim for this config (default: positional
        assignment of the degrees). Ops whose natural SPMD output layout
        differs from the degree positions override this — e.g. a row-
        sharded concat-embedding gather emits a batch-sharded output, and
        constraining its T dim instead would force a full reshard.
        `raw_pc` is the UNclamped strategy (see param_axes), for ops whose
        layout intent survives an output-shape clamp."""
        return assigner.assign(pc.degrees)

    def param_axes(self, pc: ParallelConfig, out_axes,
                   raw_pc=None) -> Dict[str, tuple]:
        """Mesh-axis assignment per parameter dim, given the mesh axes
        already assigned to each output dim (`out_axes[i]` is a tuple of
        axis names for output dim i). Default: replicated (the reference
        replicates weights across data-parallel parts and syncs grads via
        replica regions, model.cc:634-726; GSPMD psums instead)."""
        return {n: ((),) * len(d.shape) for n, d in self.param_defs().items()}

    # ---- cost model ------------------------------------------------------
    def flops_per_sample(self) -> float:
        """Forward FLOPs per sample, for the analytical simulator."""
        return 0.0

    def random_hbm_rows(self, backward: bool = False,
                        raw: bool = False) -> float:
        """Number of RANDOM HBM row accesses this op makes per step
        (embedding gathers/scatters). These are priced at the measured
        per-row latency (TPUSpec.hbm_random_row_s), not at bandwidth —
        the dominant cost of sparse lookups on TPU. `raw` bypasses
        device-cache gating (host-DRAM pricing wants raw counts)."""
        return 0.0

    def update_random_hbm_rows(self, pc=None) -> float:
        """Random row accesses of this op's PARAMETER update (the sparse
        touched-rows scatter; `pc` is the candidate config being priced —
        sharded tables take the costlier RMW path)."""
        return 0.0

    def hbm_io_factor(self) -> float:
        """Multiplier on this op's modeled HBM activation traffic.
        Elementwise-class ops (BatchNorm, unary/binary elementwise)
        override with 0.5: XLA fuses them into their producer's epilogue
        (the input read happens in registers/VMEM, not HBM). Measured
        r4: pricing them standalone overcharges ResNet-18 by ~50%."""
        return 1.0

    def mxu_utilization_factor(self) -> float:
        """Multiplier on TPUSpec.mxu_utilization for this op class. The
        global 0.55 is calibrated on gemm-shaped work (DLRM/MLP, round-2
        sweep); round-4 calibration shows large convs sustain ~25% MORE
        of peak (XLA's spatial conv emitter tiles the MXU better) while
        flash attention sustains far LESS (block-wise softmax
        recomputation, causal masking, small batch*heads grids). Override
        per op class; calibrated against benchmarks/sim_calibration.json."""
        return 1.0

    def sequential_steps(self, pc=None, vmem_bytes: int = 0) -> int:
        """Number of inherently serial inner iterations (a lax.scan's
        length — the recurrent time loop of an LSTM). Each costs a fixed
        per-iteration latency (TPUSpec.scan_iter_s) no matter how little
        work the body holds: a scanned op's wall time floors at
        steps x iter latency, which dominates small-batch RNNs.
        `pc` (a CANDIDATE ParallelConfig, passed by the cost model) lets
        scanned ops answer for the strategy being priced rather than the
        currently-compiled one."""
        return 0

    def scan_weights_resident(self, pc=None, vmem_bytes: int = 0) -> bool:
        """True when this op's serial scan keeps its weights resident in
        VMEM (the pallas LSTM kernel) — the cost model then skips the
        per-iteration weight re-stream term it charges lax.scan ops.
        With `pc` (strategy search) the answer is for the CANDIDATE
        config on the TPU target, independent of the attached backend
        and of whatever sharding is currently compiled."""
        return False

    def scan_param_stream_bytes(self) -> int:
        """fp32 bytes of the params the serial scan re-streams EVERY
        iteration — only the weights consumed INSIDE the loop body (an
        LSTM's recurrent wh; hoisted input projections stream once).
        Default: all params (ops that hoist override)."""
        return self.param_bytes()

    def output_bytes(self) -> int:
        t = self.outputs[0]
        return int(math.prod(t.shape)) * jnp.dtype(t.dtype).itemsize

    def param_bytes(self) -> int:
        return sum(int(math.prod(d.shape)) * jnp.dtype(d.dtype).itemsize
                   for d in self.param_defs().values())

    def input_shard_shapes(self, pc: ParallelConfig) -> List[tuple]:
        """Per-device input shapes under `pc`, for measured cost-model
        microbenchmarks. Default: shard only the sample dim by degrees[0]
        (output degrees applied positionally to input dims would split the
        wrong axes for rank-mismatched ops); ops whose inputs follow other
        sharded dims override (must stay consistent with
        param_shard_shapes so apply() traces)."""
        ds = max(pc.degrees[0] if pc.degrees else 1, 1)
        return [
            (max(t.shape[0] // ds, 1),) + tuple(t.shape[1:])
            if t.num_dims > 0 else t.shape
            for t in self.inputs]

    def param_shard_shapes(self, pc: ParallelConfig,
                           ndev: Optional[int] = None) -> Dict[str, tuple]:
        """Per-device parameter shapes under `pc` (for measured cost-model
        microbenchmarks and the simulator's HBM-capacity check). `ndev` is
        the total device count, for ops whose sharding spans the whole
        mesh rather than pc.num_parts. Default: FULL shapes (replicated
        weights — the common DP case); model-parallel ops override."""
        return {n: tuple(d.shape) for n, d in self.param_defs().items()}

    def param_bytes_touched_per_step(self, num_parts: int = 1) -> int:
        """Parameter bytes ONE DEVICE streams through HBM in one training
        step — what the cost model should charge. Defaults to the full
        parameter size (dense ops read every weight, whatever the batch
        partitioning); sparse-update embeddings override with this shard's
        gathered-rows traffic."""
        return self.param_bytes()

    def __repr__(self):
        return (f"{type(self).__name__}(name={self.name!r}, "
                f"in={[t.shape for t in self.inputs]}, "
                f"out={[t.shape for t in self.outputs]})")


class InputOp(Op):
    """Placeholder op owning a model input tensor (the reference creates
    input tensors directly via FFModel::create_tensor, model.cc:457-553; we
    give them a producing op so the graph interpreter is uniform)."""

    type_name = "Input"

    def __init__(self, model, shape, dtype, name=None):
        super().__init__(model, [], name)
        self.outputs = [self._make_output(shape, dtype)]

    def apply(self, params, xs, *, training=False, rng=None):
        raise RuntimeError("InputOp is fed externally")
