"""FFModel: graph builder + compile + training verbs.

Parity with the reference FFModel engine (reference: include/model.h:291-517,
src/runtime/model.cc):
- tensor-in/tensor-out builder methods (model.h:291-401) — `dense`,
  `conv2d`, `pool2d`, `batch_norm`, `embedding`, `concat`, `split`, `flat`,
  `softmax`, `dropout`, unary/binary elementwise, `batch_matmul`,
  `transpose`, `reshape`, `reverse`;
- `compile(optimizer, loss_type, metrics)` (model.cc:1003-1080): resolves
  the per-op parallelization strategy (import file / search / default DP),
  builds parameter shardings, and traces+jits the train step;
- training verbs `init_layers/forward/backward/update/zero_gradients`
  (model.cc:942-993, 1146-1149) — provided for API parity; the performant
  path is the fused jitted `train_step` used by `fit()`;
- metrics future-chain (model.cc:1182-1205) — metrics come back as device
  arrays off the async dispatch stream and are folded host-side.

TPU-native redesign: there are no Legion regions/partitions/mappers; the
graph is traced once into XLA, per-op ParallelConfigs lower to GSPMD
shardings (parallel/sharding.py), resharding between ops is XLA collectives,
and Legion trace replay (dlrm.cc:179-185) is subsumed by jit
compile-once/execute-many.
"""

from __future__ import annotations

import time
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence)

import jax
import jax.numpy as jnp
import numpy as np

from ..config import FFConfig
from ..parallel.mesh import make_mesh
from ..parallel.pconfig import ParallelConfig, StrategyMap
from ..parallel.sharding import AxisAssigner
from ..parallel.distributed import MeshDegraded, MeshReturned, put_global
from ..obs import trace as obstrace
from ..utils.profiling import superstep_annotation
from ..utils.watchdog import StallReport, WorkerStalled
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from . import losses as losses_mod
from . import metrics as metrics_mod
from .op import InputOp, Op
from .optimizers import Optimizer, SGDOptimizer
from .tensor import Tensor
from ..utils.logging import log_model
from ..utils import faults


def _sharding_mismatch(e: Exception) -> bool:
    """True when a cached AOT executable rejected its inputs because
    GSPMD propagated different shardings than it was compiled with (the
    recompile-once fallback). The message wording changed across jax
    releases ("...that disagree..." -> "...does not match...")."""
    msg = str(e)
    return "disagree" in msg or ("sharding" in msg
                                 and "does not match" in msg)


def _to_memory(v, space: str):
    """Stage a traced value into host or device memory for the hetero
    host-offload path. `jax.memory.Space` moved across jax releases; on
    versions without it the transfer annotation is
    `TransferToMemoryKind` with the corresponding memory-kind string."""
    mem = getattr(jax, "memory", None)
    if mem is not None and hasattr(mem, "Space"):
        tgt = mem.Space.Host if space == "host" else mem.Space.Device
    else:
        from jax._src.sharding_impls import TransferToMemoryKind
        tgt = TransferToMemoryKind(
            "unpinned_host" if space == "host" else "device")
    return jax.device_put(v, tgt)


class AnomalyError(RuntimeError):
    """A train step produced a non-finite loss or gradient norm and the
    anomaly policy is "rollback" or "raise" (FFConfig.anomaly_policy).
    Under "rollback", fit(checkpoint_dir=...) catches this, restores the
    last good snapshot, and re-winds; outside fit() it propagates.
    The offending update was already suppressed on device — params/opt
    state keep their pre-step values."""

    def __init__(self, step: int, loss: float, grad_norm: float):
        super().__init__(
            f"non-finite training step {step}: loss={loss}, "
            f"global grad norm={grad_norm}")
        self.step = step
        self.loss = loss
        self.grad_norm = grad_norm
        # anomaly-sentinel fires land in the obs layer at the one choke
        # point every policy passes through (trace instant + counter,
        # no-op when --obs off) — visible even if the recovery path
        # that catches this never reports it
        from ..obs import metrics as _obsm
        from ..obs import trace as _obstrace
        _obsm.counter("ff_anomalies_total",
                      "non-finite training steps the sentinel caught"
                      ).inc()
        _obstrace.instant("anomaly", cat="sentinel", step=int(step),
                          loss=repr(loss), grad_norm=repr(grad_norm))


class StagedStep(NamedTuple):
    """One fully-staged train-step input (`FFModel._stage_step`): the
    device-put batch (host-only inputs already popped) plus the numpy
    indices for host-resident tables (None when there are none). The
    prefetch pipeline stages these ahead of the hot loop.

    `k` > 1 marks a fused-superstep megabatch (`_stage_superstep`):
    `device_batch` holds `[k, batch, ...]` stacked arrays and
    `train_batch_staged` routes it to the K-step scan executable (one
    dispatch trains k steps); host_idx is always None there — host-
    resident-table models fall back to k=1."""

    device_batch: Dict[str, Any]
    host_idx: Optional[Dict[str, Any]]
    k: int = 1


class FFModel:
    def __init__(self, config: Optional[FFConfig] = None):
        self.config = config or FFConfig()
        if getattr(self.config, "debug_nans", None) is not None:
            jax.config.update("jax_debug_nans",
                              bool(self.config.debug_nans))
        self._op_guid = 0
        self.ops: List[Op] = []          # topological (construction) order
        self.input_tensors: List[Tensor] = []
        self.compute_dtype = self.config.jnp_compute_dtype
        # set by compile()
        self.optimizer: Optional[Optimizer] = None
        self.loss_type: Optional[str] = None
        self.metrics: List[str] = []
        self.mesh: Optional[Mesh] = None
        self.strategies: StrategyMap = {}
        self.label_tensor: Optional[Tensor] = None
        self._logits_tensor: Optional[Tensor] = None
        self._preds_tensor: Optional[Tensor] = None
        # runtime state (set by init_layers)
        self.params = None
        self.opt_state = None
        self.op_state = None
        self._step = 0
        self.perf = metrics_mod.PerfMetrics()

    # ------------------------------------------------------------------
    # graph construction
    # ------------------------------------------------------------------
    def _next_op_guid(self) -> int:
        self._op_guid += 1
        return self._op_guid

    def _register_op(self, op: Op):
        # the op name keys strategies/params/shardings (reference hashes it
        # into the MappingTagID, strategy.cc:23-26) — collisions corrupt all
        # three maps, so reject them at build time
        if any(o.name == op.name for o in self.ops):
            raise ValueError(
                f"duplicate op name {op.name!r}; op names must be unique "
                f"(they key parallelization strategies and parameters)")
        self.ops.append(op)

    def create_tensor(self, shape: Sequence[int], dtype=jnp.float32,
                      name: Optional[str] = None) -> Tensor:
        """Reference FFModel::create_tensor (model.cc:457-553); sample dim
        first."""
        op = InputOp(self, shape, dtype, name)
        t = op.outputs[0]
        if name:
            t.name = name
        self.input_tensors.append(t)
        return t

    # --- op builders (reference model.h:291-401) -----------------------
    def dense(self, input_tensor, out_dim, activation=None, use_bias=True,
              kernel_initializer=None, bias_initializer=None, name=None):
        from ..ops.linear import Linear
        if activation == "softmax":
            # lower to a separate Softmax op (not a fused epilogue) so the
            # loss's logits-extraction special case in compile() can see it
            t = Linear(self, input_tensor, out_dim, "none", use_bias,
                       kernel_initializer, bias_initializer, name).outputs[0]
            return self.softmax(t, name=f"{name}_softmax" if name else None)
        return Linear(self, input_tensor, out_dim, activation or "none",
                      use_bias, kernel_initializer, bias_initializer,
                      name).outputs[0]

    def conv2d(self, input_tensor, out_channels, kernel_h, kernel_w,
               stride_h, stride_w, padding_h, padding_w, activation=None,
               use_bias=True, groups=1, kernel_initializer=None,
               bias_initializer=None, name=None):
        from ..ops.conv import Conv2D
        return Conv2D(self, input_tensor, out_channels, kernel_h, kernel_w,
                      stride_h, stride_w, padding_h, padding_w,
                      activation or "none", use_bias, groups,
                      kernel_initializer, bias_initializer, name).outputs[0]

    def pool2d(self, input_tensor, kernel_h, kernel_w, stride_h, stride_w,
               padding_h, padding_w, pool_type="max", activation=None,
               name=None):
        from ..ops.conv import Pool2D
        return Pool2D(self, input_tensor, kernel_h, kernel_w, stride_h,
                      stride_w, padding_h, padding_w, pool_type,
                      activation or "none", name).outputs[0]

    def batch_norm(self, input_tensor, relu=True, name=None):
        from ..ops.conv import BatchNorm
        return BatchNorm(self, input_tensor, relu, name).outputs[0]

    def embedding(self, input_tensor, num_entries, out_dim, aggr="sum",
                  kernel_initializer=None, name=None):
        from ..ops.embedding import Embedding
        return Embedding(self, input_tensor, num_entries, out_dim, aggr,
                         kernel_initializer, name).outputs[0]

    def embedding_stacked(self, input_tensor, num_tables, num_entries,
                          out_dim, aggr="sum", kernel_initializer=None,
                          name=None):
        from ..ops.embedding import EmbeddingBagStacked
        return EmbeddingBagStacked(self, input_tensor, num_tables,
                                   num_entries, out_dim, aggr,
                                   kernel_initializer, name).outputs[0]

    def embedding_concat(self, input_tensor, table_sizes, out_dim,
                         aggr="sum", kernel_initializer=None, name=None):
        """Non-uniform tables (shared width, different row counts) fused
        into one concatenated-rows parameter — see ops.embedding
        EmbeddingBagConcat."""
        from ..ops.embedding import EmbeddingBagConcat
        return EmbeddingBagConcat(self, input_tensor, table_sizes, out_dim,
                                  aggr, kernel_initializer, name).outputs[0]

    def concat(self, tensors, axis, name=None):
        from ..ops.tensor_ops import Concat
        return Concat(self, list(tensors), axis, name).outputs[0]

    def split(self, input_tensor, sizes, axis, name=None):
        from ..ops.tensor_ops import Split
        return Split(self, input_tensor, sizes, axis, name).outputs

    def flat(self, input_tensor, name=None):
        from ..ops.tensor_ops import Flat
        return Flat(self, input_tensor, name).outputs[0]

    def reshape(self, input_tensor, shape, name=None):
        from ..ops.tensor_ops import Reshape
        return Reshape(self, input_tensor, shape, name).outputs[0]

    def transpose(self, input_tensor, name=None):
        from ..ops.tensor_ops import Transpose
        return Transpose(self, input_tensor, name).outputs[0]

    def reverse(self, input_tensor, axis, name=None):
        from ..ops.tensor_ops import Reverse
        return Reverse(self, input_tensor, axis, name).outputs[0]

    def index_select(self, input_tensor, indices, axis, name=None):
        from ..ops.tensor_ops import IndexSelect
        return IndexSelect(self, input_tensor, indices, axis, name).outputs[0]

    def softmax(self, input_tensor, name=None):
        from ..ops.elementwise import Softmax
        return Softmax(self, input_tensor, name).outputs[0]

    def dropout(self, input_tensor, rate, seed=0, name=None):
        from ..ops.elementwise import Dropout
        return Dropout(self, input_tensor, rate, seed, name).outputs[0]

    def multihead_attention(self, q, k=None, v=None, embed_dim=None,
                            num_heads=8, causal=False, name=None):
        from ..ops.attention import MultiHeadAttention
        k = q if k is None else k
        v = q if v is None else v
        embed_dim = embed_dim or q.shape[-1]
        return MultiHeadAttention(self, q, k, v, embed_dim, num_heads,
                                  causal, name).outputs[0]

    def lstm_stack(self, input_tensor, hidden, num_layers, name=None):
        """N stacked LSTM layers in ONE scan (see ops/rnn.LSTMStack:
        pays the serial per-iteration latency once per timestep instead
        of once per layer per timestep)."""
        from ..ops.rnn import LSTMStack
        return LSTMStack(self, input_tensor, hidden, num_layers,
                         name).outputs[0]

    def lstm(self, input_tensor, hidden, name=None):
        from ..ops.rnn import LSTM
        return LSTM(self, input_tensor, hidden, name).outputs[0]

    def batch_matmul(self, a, b, trans_a=True, trans_b=False, name=None):
        from ..ops.batch_matmul import BatchMatmul
        return BatchMatmul(self, a, b, trans_a, trans_b, name).outputs[0]

    def fused_dot_interaction(self, sparse_idx, bottom, num_entries,
                              out_dim, activation="relu",
                              emb_initializer=None, kernel_initializer=None,
                              bias_initializer=None, name=None):
        """Fused gather→dot-interaction→first-top-MLP-layer (see
        ops/interaction.FusedDotInteraction): on TPU the whole chain runs
        in one Pallas kernel and the (B, F, F) interaction tensor never
        reaches HBM."""
        from ..ops.interaction import FusedDotInteraction
        return FusedDotInteraction(self, sparse_idx, bottom, num_entries,
                                   out_dim, activation, emb_initializer,
                                   kernel_initializer, bias_initializer,
                                   name).outputs[0]

    def _unary(self, op_type, x, name=None):
        from ..ops.elementwise import ElementUnary
        return ElementUnary(self, x, op_type, name).outputs[0]

    def exp(self, x, name=None):
        return self._unary("exp", x, name)

    def relu(self, x, name=None):
        return self._unary("relu", x, name)

    def sigmoid(self, x, name=None):
        return self._unary("sigmoid", x, name)

    def tanh(self, x, name=None):
        return self._unary("tanh", x, name)

    def elu(self, x, name=None):
        return self._unary("elu", x, name)

    def _binary(self, op_type, a, b, name=None):
        from ..ops.elementwise import ElementBinary
        return ElementBinary(self, a, b, op_type, name).outputs[0]

    def add(self, a, b, name=None):
        return self._binary("add", a, b, name)

    def subtract(self, a, b, name=None):
        return self._binary("subtract", a, b, name)

    def multiply(self, a, b, name=None):
        return self._binary("multiply", a, b, name)

    def divide(self, a, b, name=None):
        return self._binary("divide", a, b, name)

    def get_layer_by_id(self, idx: int) -> Op:
        """Reference flexflow_cbinding.py FFModel.get_layer_by_id — indexes
        non-input ops in construction order."""
        return [op for op in self.ops if not isinstance(op, InputOp)][idx]

    def get_layer_by_name(self, name: str) -> Op:
        for op in self.ops:
            if op.name == name:
                return op
        raise KeyError(name)

    # ------------------------------------------------------------------
    # compile
    # ------------------------------------------------------------------
    def compile(self, optimizer: Optional[Optimizer] = None,
                loss_type: str = "mean_squared_error",
                metrics: Sequence[str] = ("mean_squared_error",),
                mesh: Optional[Mesh] = None,
                strategies: Optional[StrategyMap] = None,
                final_tensor: Optional[Tensor] = None):
        """Resolve strategy + build the jitted train/eval steps.

        Mirrors reference FFModel::compile (model.cc:1003-1080): [load or
        search strategies] → per-op partitioning/weights → label tensor →
        optimizer init. Search (--budget) is run by the caller via
        search.mcmc before compile, or lazily here when
        config.search_budget > 0.
        """
        self.optimizer = optimizer or SGDOptimizer(
            lr=self.config.learning_rate,
            weight_decay=self.config.weight_decay)
        self.loss_type = losses_mod.canonical_loss(loss_type)
        self.metrics = metrics_mod.canonical_metrics(list(metrics))
        self.mesh = mesh if mesh is not None else make_mesh(
            num_devices=self.config.num_devices)
        ndev = int(np.prod([self.mesh.shape[a] for a in self.mesh.axis_names]))

        # --- strategies -------------------------------------------------
        self.strategies = dict(strategies or {})
        if not self.strategies and self.config.import_strategy_file:
            from ..parallel.strategy_io import load_strategies
            # load-time validation: degrees must factorize THIS mesh and
            # every entry must reference an op of THIS model (or a
            # reference-style generic key) — a malformed file fails here
            # with file+op+reason, not as a downstream GSPMD error
            self.strategies = load_strategies(
                self.config.import_strategy_file, num_devices=ndev,
                known_ops={op.name for op in self.ops},
                row_shard_ops={op.name for op in self.ops
                               if hasattr(op, "_row_shard_geometry")})
        if self.config.search_budget > 0 and not self.strategies:
            try:
                from ..search.mcmc import optimize
            except ImportError as e:
                raise NotImplementedError(
                    "--budget strategy search requires the search.mcmc "
                    "module (not built yet in this checkout)") from e
            cm = None
            if self.config.search_measure:
                from ..search.cost_model import CostModel
                cm = CostModel(compute_dtype=self.compute_dtype,
                               measure=True)
            self.strategies = optimize(self, budget=self.config.search_budget,
                                       alpha=self.config.search_alpha,
                                       cost_model=cm)
        # reference-style generic keys: the reference's DLRM strategies key
        # ops as "embedding{i}" / "linear" / "concat" / "mse_loss" shared
        # across ops of a type (dlrm_strategy.py, dlrm_strategy_hetero.cc) —
        # resolve those for ops without an exact-name entry
        self._resolve_generic_strategy_keys(ndev)
        # default: data parallelism for every op (reference mapper fallback,
        # mapper.cc:297-311)
        for op in self.ops:
            if isinstance(op, InputOp):
                continue
            if op.name not in self.strategies:
                self.strategies[op.name] = op.default_parallel_config(ndev)
        if self.config.export_strategy_file:
            from ..parallel.strategy_io import save_strategies
            save_strategies(self.config.export_strategy_file, self.strategies)

        # --- final tensors / label -------------------------------------
        from ..ops.elementwise import Softmax
        last_op = [op for op in self.ops if not isinstance(op, InputOp)][-1]
        preds = final_tensor if final_tensor is not None else last_op.outputs[0]
        self._preds_tensor = preds
        # reference applies CCE losses to softmax output; we keep the probs
        # for metrics but feed pre-softmax logits to the loss for stability
        if (isinstance(preds.owner_op, Softmax)
                and "crossentropy" in self.loss_type):
            self._logits_tensor = preds.owner_op.inputs[0]
        else:
            self._logits_tensor = preds
        # label tensor (reference model.cc:1062 creates it sized like the
        # final output, int for sparse labels)
        if self.loss_type == losses_mod.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
            lshape, ldtype = (preds.shape[0], 1), jnp.int32
        else:
            lshape, ldtype = preds.shape, jnp.float32
        self.label_tensor = Tensor(lshape, ldtype, name="label")

        self._build_shardings()
        self._build_steps()
        return self

    def _resolve_generic_strategy_keys(self, ndev: int):
        """Translate reference-keyed strategies onto this graph's ops.

        The reference DLRM strategy files (src/runtime/dlrm_strategy.py,
        dlrm_strategy_hetero.cc:28-49) key per-table embeddings as
        "embedding{i}" (dims (1,1), whole table placed on device
        `device_ids[0]` — model parallelism by placement) and share one
        "linear"/"concat"/"mse_loss" entry across all ops of that type.
        GSPMD translation: N tables round-robined over D distinct devices
        become table-dim sharding of degree D on the stacked embedding (or
        per-op placement for unfused tables); shared type keys apply to every
        op of the type; CPU device_type marks host offload.
        """
        from ..ops.embedding import (Embedding, EmbeddingBagConcat,
                                     EmbeddingBagStacked)
        from ..ops.linear import Linear
        from ..ops.tensor_ops import Concat
        strategies = self.strategies
        if not strategies:
            return
        emb_keys = sorted((k for k in strategies
                           if k.startswith("embedding")
                           and k[len("embedding"):].isdigit()),
                          key=lambda k: int(k[len("embedding"):]))
        fused_types = (EmbeddingBagStacked, EmbeddingBagConcat)
        emb_ops = [op for op in self.ops
                   if isinstance(op, (Embedding,) + fused_types)]
        for i, op in enumerate(emb_ops):
            if op.name in strategies:
                continue
            if isinstance(op, fused_types) and emb_keys:
                pcs = [strategies[k] for k in emb_keys]
                distinct = {pc.device_ids[:1] for pc in pcs if pc.device_ids}
                degree = max(1, min(len(distinct), op.num_tables, ndev))
                dtyp = pcs[0].device_type
                if any(pc.device_type != dtyp for pc in pcs):
                    log_model.warning(
                        "per-table strategies mix device types %s; the "
                        "fused embedding %r uses %r for all tables",
                        sorted({pc.device_type for pc in pcs}), op.name,
                        dtyp)
                # per-table ZCM marks host-RESIDENT storage
                # (strategy.proto:11-14); any table marked ZCM makes the
                # fused op host-resident — dropping it here would silently
                # fall back to HBM tables and OOM the >HBM configs this
                # path exists for
                zcm = ["ZCM" in pc.memory_types for pc in pcs]
                mem = ("ZCM",) if any(zcm) else ()
                if any(zcm) and not all(zcm):
                    log_model.warning(
                        "per-table strategies mark only %d/%d tables ZCM; "
                        "the fused embedding %r stores ALL tables "
                        "host-resident (fusion constraint)",
                        sum(zcm), len(zcm), op.name)
                # per-table PARAM-axis (row-shard) degrees fuse to the
                # largest requested: rows of every table shard over that
                # many devices with all-to-all lookup routing, output
                # data-parallel over the whole mesh
                pd = max((getattr(pc, "param_degree", 1) for pc in pcs),
                         default=1)
                if pd > 1 and not mem:
                    batch = op.inputs[0].shape[0]
                    ds = ndev if batch % max(ndev, 1) == 0 else 1
                    # skew policies fuse like the degree: dedup if any
                    # table asked for it, the largest hot fraction wins
                    exch = ("dedup" if any(
                        getattr(pc, "exchange", "dense") == "dedup"
                        for pc in pcs) else "dense")
                    frac = max((getattr(pc, "hot_fraction", 0.0)
                                for pc in pcs), default=0.0)
                    ovl = any(getattr(pc, "overlap", False)
                              for pc in pcs)
                    strategies[op.name] = ParallelConfig(
                        (ds, 1, 1), device_type=dtyp, param_degree=pd,
                        exchange=exch, hot_fraction=frac, overlap=ovl)
                    continue
                strategies[op.name] = ParallelConfig(
                    (1, degree, 1), device_type=dtyp, memory_types=mem)
                # honor the per-table device assignment, not just its
                # degree: group tables by their strategy device so
                # block-sharding the stacked dim lands table i exactly on
                # device_ids[i] (reference round-robin placement,
                # dlrm_strategy.cc:242-296)
                dev_of = [pc.device_ids[0] if pc.device_ids else None
                          for pc in pcs]
                if len(emb_keys) == op.num_tables and None not in dev_of:
                    devs = sorted(set(dev_of))
                    if hasattr(op, "set_device_groups") and len(devs) > 1:
                        # concatenated-rows form: UNEVEN per-table
                        # placement is honored exactly by grouping the
                        # rows by device with per-group padding
                        before = op.total_rows
                        op.set_device_groups(dev_of)
                        if op.total_rows > 1.25 * before:
                            log_model.warning(
                                "honoring per-table device placement "
                                "pads %r from %d to %d rows (+%d%%): "
                                "groups pad to the LARGEST device's row "
                                "count — skewed placements cost memory",
                                op.name, before, op.total_rows,
                                round(100 * (op.total_rows / before - 1)))
                        if len(devs) != ndev:
                            log_model.warning(
                                "strategy places tables on %d devices "
                                "but the mesh has %d; row blocks land "
                                "in device order, placement is "
                                "approximate", len(devs), ndev)
                    elif hasattr(op, "set_table_order"):
                        per = op.num_tables // max(len(devs), 1)
                        if (len(devs) == degree
                                and all(dev_of.count(g) == per
                                        for g in devs)):
                            op.set_table_order(tuple(
                                i for g in devs
                                for i, dg in enumerate(dev_of)
                                if dg == g))
                        elif len(devs) > 1:
                            log_model.warning(
                                "per-table device_ids place %d tables "
                                "unevenly across %d devices (counts %s); "
                                "the stacked uniform embedding can only "
                                "block-shard equal groups — PLACEMENT "
                                "INTENT DROPPED, executing degree-%d "
                                "table sharding in declaration order",
                                op.num_tables, len(devs),
                                [dev_of.count(g) for g in devs], degree)
            elif not isinstance(op, fused_types) and i < len(emb_keys):
                strategies[op.name] = strategies[emb_keys[i]]
        for op in self.ops:
            if isinstance(op, InputOp) or op.name in strategies:
                continue
            generic = None
            if isinstance(op, Linear):
                generic = "linear"
            elif isinstance(op, Concat):
                generic = "concat"
            if generic and generic in strategies:
                pc = strategies[generic]
                nd = op.outputs[0].num_dims
                degs = tuple(pc.degrees[:nd]) + (1,) * (nd - len(pc.degrees))
                strategies[op.name] = ParallelConfig(
                    degs, device_type=pc.device_type,
                    device_ids=pc.device_ids)

    # --- sharding plumbing --------------------------------------------
    def _effective_pc(self, op: Op) -> ParallelConfig:
        """Clamp strategy degrees to divide the actual tensor dims.

        A rewrite is loud: warn by default, raise under
        FFConfig.strict_strategies — a searched/imported config that does
        not divide the real shapes would otherwise execute as a silently
        different strategy."""
        pc = self.strategies[op.name]
        shape = op.outputs[0].shape
        degs = list(pc.degrees)[:len(shape)]
        degs += [1] * (len(shape) - len(degs))
        asn = AxisAssigner(self.mesh)
        feas = asn.feasible_degrees()
        for i, d in enumerate(degs):
            d = min(d, shape[i])
            while d > 1 and (shape[i] % d != 0 or d not in feas):
                d -= 1
            degs[i] = max(d, 1)
        eff = ParallelConfig(tuple(degs), pc.device_type, pc.device_ids)
        requested = tuple(pc.degrees)[:len(shape)]
        requested += (1,) * (len(shape) - len(requested))
        if tuple(degs) != requested and not op.raw_degree_semantics:
            msg = (f"strategy for {op.name!r} requests degrees {requested} "
                   f"but output shape {shape} / mesh {tuple(self.mesh.shape.values())} "
                   f"only admits {tuple(degs)}; executing the clamped config")
            if getattr(self.config, "strict_strategies", False):
                raise ValueError(msg)
            log_model.warning(msg)
        return eff

    def _build_shardings(self):
        asn = AxisAssigner(self.mesh)
        self._out_sharding: Dict[int, NamedSharding] = {}   # tensor.guid ->
        self._param_sharding: Dict[str, Dict[str, NamedSharding]] = {}
        # ops host-offloaded by a hetero strategy (device_type "CPU",
        # reference dlrm_strategy_hetero.cc:28-36): their compute runs under
        # compute_on("device_host"), with operands staged HBM→host per step —
        # the analog of the reference's zero-copy-memory staging
        # (embedding.cu:280-283).
        self._host_offload_ops: set = set()
        # HOST-RESIDENT tables (reference hetero semantics proper: tables
        # STORED in CPU RAM and looked up there, embedding_avx2.cc +
        # dlrm_strategy_hetero.cc:28-49 — the capability that lets
        # DLRM-Terabyte run on few chips). XLA memory-kind shardings crash
        # this build's partitioner, so residency is explicit instead: the
        # table lives in model.host_params as numpy, the wrapper gathers
        # rows on the host before each step, the jitted step consumes them
        # via the overrides mechanism and returns their cotangents, and
        # the wrapper applies the touched-rows SGD scatter on the host.
        # Selected per op by strategy memory_types ZCM (strategy.proto:
        # 11-14) or globally by FFConfig.host_resident_tables.
        hres: set = set()
        force_host = bool(getattr(self.config, "host_resident_tables",
                                  False))
        for op in self.ops:
            if isinstance(op, InputOp) or not hasattr(op, "host_lookup"):
                continue
            raw = self.strategies.get(op.name)
            if force_host or (raw is not None
                              and "ZCM" in raw.memory_types):
                hres.add(op.name)
        self._host_resident_ops = hres
        # per-op quantized-storage policies (quant/), re-resolved per
        # compile (configure_quant fills it; non-default policies only)
        self._quant_policies = {}

        def spec_from_axes(axes_per_dim):
            return NamedSharding(self.mesh,
                                 AxisAssigner.axes_to_spec(axes_per_dim))

        for op in self.ops:
            if isinstance(op, InputOp):
                continue
            pc = self._effective_pc(op)
            if pc.device_type == "CPU" and op.name not in hres:
                self._host_offload_ops.add(op.name)
            # row/PARAM-axis sharding for embedding tables (strategy
            # param_degree > 1): resolve the all-to-all routing plan
            # BEFORE output/param axes — both consult it
            if hasattr(op, "_row_shard_geometry"):
                from ..ops.embedding import configure_row_shard
                configure_row_shard(op, self.strategies.get(op.name))
            # quantized-storage policy for embedding tables (strategy
            # quant_dtype / --emb-dtype): resolved beside the row-shard
            # plan so search, serving, and the publisher read one policy
            if hasattr(op, "host_lookup"):
                from ..ops.embedding import configure_quant
                configure_quant(op, self.strategies.get(op.name))
            try:
                out_axes = op.output_axes(
                    pc, asn, raw_pc=self.strategies.get(op.name, pc))
            except ValueError:
                msg = (f"strategy for {op.name!r} degrees {pc.degrees} are "
                       f"not jointly assignable on mesh "
                       f"{dict(self.mesh.shape)}; executing replicated")
                if getattr(self.config, "strict_strategies", False):
                    raise ValueError(msg)
                log_model.warning(msg)
                pc = ParallelConfig((1,) * op.outputs[0].num_dims)
                out_axes = asn.assign(pc.degrees)
            self._op_pc = getattr(self, "_op_pc", {})
            self._op_pc[op.name] = pc
            # ops that implement their own collectives (ring attention)
            # need the resolved config + the mesh axes of their seq dim
            op._compiled_pc = pc
            op._seq_axes = tuple(out_axes[1]) if len(out_axes) > 1 else ()
            for t in op.outputs:
                axes = list(out_axes[:t.num_dims])
                axes += [()] * (t.num_dims - len(axes))
                shape = t.shape
                if t.physical == "nhwc" and t.num_dims == 4:
                    # constraints apply to the CONCRETE (NHWC) array:
                    # permute the logical NCHW axis assignment to match
                    axes = [axes[0], axes[2], axes[3], axes[1]]
                    shape = (shape[0], shape[2], shape[3], shape[1])
                # divisibility against the actual axis products (output_axes
                # overrides may differ from the positional degrees)
                sizes = [int(np.prod([self.mesh.shape[a] for a in ax]))
                         if ax else 1 for ax in axes]
                ok = all(shape[i] % s == 0 for i, s in enumerate(sizes))
                self._out_sharding[t.guid] = (
                    spec_from_axes(axes) if ok else
                    NamedSharding(self.mesh, PartitionSpec()))
            if op.param_defs() and op.name not in hres:
                # raw_pc = the UNclamped strategy, for ops whose param
                # sharding keys off the requested (not shape-clamped)
                # degrees — e.g. the concatenated-rows embedding row-shards
                # on ANY requested table parallelism even when the output
                # table dim can't split evenly
                p_axes = op.param_axes(
                    pc, out_axes, raw_pc=self.strategies.get(op.name, pc))
                self._param_sharding[op.name] = {
                    pname: spec_from_axes(axes)
                    for pname, axes in p_axes.items()}

        self._propagate_host_offload_to_views()
        if len(self._host_offload_ops) > 3:
            import jax as _jax
            if _jax.default_backend() == "tpu":
                import warnings
                warnings.warn(
                    f"{len(self._host_offload_ops)} ops are host-offloaded; "
                    "this TPU compiler build is known to crash (SIGABRT) on "
                    "many separate host-compute regions. Prefer the fused "
                    "stacked-embedding form (build_dlrm "
                    "fuse_embeddings=True), which keeps one host region.")

        # model inputs: shard the sample dim over all mesh axes when possible
        flat_axes = tuple(self.mesh.axis_names)
        ndev = int(np.prod([self.mesh.shape[a] for a in flat_axes]))
        for t in self.input_tensors:
            if t.shape[0] % ndev == 0 and ndev > 1:
                self._out_sharding[t.guid] = NamedSharding(
                    self.mesh, PartitionSpec(flat_axes))
            else:
                self._out_sharding[t.guid] = NamedSharding(
                    self.mesh, PartitionSpec())
        # label follows inputs
        lt = self.label_tensor
        if lt.shape[0] % ndev == 0 and ndev > 1:
            self._label_sharding = NamedSharding(self.mesh,
                                                 PartitionSpec(flat_axes))
        else:
            self._label_sharding = NamedSharding(self.mesh, PartitionSpec())

    # --- forward interpreter ------------------------------------------
    def _propagate_host_offload_to_views(self):
        """Pull zero-FLOP view ops (reshape/flat/transpose) into the host
        region when every producer of their inputs is host-offloaded.

        Views are free on either side of the boundary, but leaving them on
        the device puts the host→device transfer *before* the view, and
        this XLA build miscompiles the view's backward at that seam (a
        bitcast between the host buffer and the TPU tiled layout hits
        "Bitcast cannot have different shape sizes"). Running the view on
        the host moves the transfer after it, which compiles and keeps one
        boundary per host subgraph.
        """
        from ..ops.tensor_ops import Flat, Reshape, Transpose
        if not self._host_offload_ops:
            return
        for op in self.ops:  # construction order is topological
            if not isinstance(op, (Reshape, Flat, Transpose)):
                continue
            producers = [t.owner_op for t in op.inputs]
            if producers and all(
                    p is not None and p.name in self._host_offload_ops
                    for p in producers):
                self._host_offload_ops.add(op.name)

    def _forward_env(self, params, op_state, batch: Dict[str, Any],
                     training: bool, rng, overrides: Optional[Dict] = None,
                     only_ops: Optional[set] = None):
        """Run the graph, returning tensor.guid -> value and new op_state.

        `overrides` maps op name -> precomputed output value; the op's
        compute is skipped and the value used instead (the sparse-update
        path threads embedding outputs through here so jax.grad yields
        their cotangents without touching the tables). `only_ops` restricts
        evaluation to a subset of ops (ancestor subgraphs)."""
        import contextlib

        env: Dict[int, Any] = {}
        new_state: Dict[str, Any] = {}
        constrain = jax.lax.with_sharding_constraint
        host_ops = getattr(self, "_host_offload_ops", set())
        # under bf16 compute, float inputs enter the graph in bf16 so the
        # WHOLE activation stream (ops preserve their input dtype) flows at
        # half the HBM bytes; fp32 stats/accumulations inside ops keep
        # their precision. No-op under the default f32 compute dtype.
        cast_bf16 = (jnp.dtype(self.compute_dtype)
                     == jnp.dtype(jnp.bfloat16))
        for t in self.input_tensors:
            if t.name in batch:   # host-only inputs are popped pre-jit
                v = batch[t.name]
                if cast_bf16 and jnp.issubdtype(jnp.dtype(t.dtype),
                                                jnp.floating):
                    v = v.astype(self.compute_dtype)
                env[t.guid] = v
        for op in self.ops:
            if isinstance(op, InputOp):
                continue
            if only_ops is not None and op.name not in only_ops:
                continue
            if overrides and op.name in overrides:
                t = op.outputs[0]
                v = overrides[op.name]
                sh = self._out_sharding.get(t.guid)
                env[t.guid] = constrain(v, sh) if sh is not None else v
                continue
            # physical-layout boundary: ops that didn't opt into NHWC get
            # their conv-stack inputs transposed back to logical NCHW
            # (ops/conv.py module docstring)
            accepts_nhwc = getattr(op, "_accepts_nhwc_inputs", False)
            xs = []
            for t in op.inputs:
                v = env[t.guid]
                if t.physical == "nhwc" and not accepts_nhwc:
                    v = jnp.transpose(v, (0, 3, 1, 2))
                xs.append(v)
            p = params.get(op.name, {})
            host = op.name in host_ops
            if host:
                # hetero host offload (reference CPU device_type +
                # embedding_avx2.cc CPU kernels): run this op's compute on
                # the host; operands are explicitly staged HBM→host→HBM,
                # the analog of the reference's zero-copy-memory staging
                # (embedding.cu:280-283)
                from jax.experimental.compute_on import compute_on
                ctx = compute_on("device_host")
                xs = [_to_memory(x, "host") for x in xs]
                p = {pn: _to_memory(v, "host") for pn, v in p.items()}
            else:
                ctx = contextlib.nullcontext()
            if hasattr(op, "apply_with_state"):
                st = op_state.get(op.name, {})
                if host:
                    st = jax.tree.map(lambda v: _to_memory(v, "host"), st)
                with ctx:
                    outs, st2 = op.apply_with_state(p, st, xs,
                                                    training=training,
                                                    rng=rng)
                if host:
                    st2 = jax.tree.map(lambda v: _to_memory(v, "device"),
                                       st2)
                new_state[op.name] = st2
            else:
                with ctx:
                    outs = op.apply(p, xs, training=training, rng=rng)
            if host:
                outs = [_to_memory(o, "device") for o in outs]
            for t, v in zip(op.outputs, outs):
                sh = self._out_sharding.get(t.guid)
                if sh is not None:
                    v = constrain(v, sh)
                env[t.guid] = v
        return env, new_state

    # --- jitted steps --------------------------------------------------
    def _select_sparse_update_ops(self):
        """Embedding-type ops whose tables take a touched-rows-only
        update: plain SGD goes through the state-free sparse_sgd_update;
        momentum/weight-decay SGD and Adam go through the STATEFUL lazy
        sparse_opt_update (touched-rows state, lazily-applied decay) —
        the reference's Adam world pays a full dense table stream
        otherwise (optimizer_kernel.cu:110+). Disabled by
        config.sparse_embedding_update=False (--dense-embedding-update)."""
        from ..core.optimizers import AdamOptimizer
        from ..ops.embedding import (Embedding, EmbeddingBagConcat,
                                     EmbeddingBagStacked)
        if not getattr(self.config, "sparse_embedding_update", True):
            return []
        opt = self.optimizer
        plain = (isinstance(opt, SGDOptimizer) and opt.momentum == 0.0
                 and opt.weight_decay == 0.0)
        stateful = ((isinstance(opt, SGDOptimizer) and not plain)
                    or isinstance(opt, AdamOptimizer))
        if not (plain or stateful):
            return []
        host = (getattr(self, "_host_offload_ops", set())
                | getattr(self, "_host_resident_ops", set()))
        ops = [op for op in self.ops
               if isinstance(op, (Embedding, EmbeddingBagStacked,
                                  EmbeddingBagConcat))
               and op.supports_sparse_update() and op.name not in host]
        if stateful:
            ops = [op for op in ops if hasattr(op, "sparse_opt_update")]
        return ops

    def _ancestor_op_names(self, targets) -> set:
        out: set = set()

        def visit(op):
            if isinstance(op, InputOp) or op.name in out:
                return
            out.add(op.name)
            for t in op.inputs:
                if t.owner_op is not None:
                    visit(t.owner_op)

        for op in targets:
            visit(op)
        return out

    def _build_steps(self):
        # drop any AOT executables compiled against the previous step
        # function (a re-compile() with a new optimizer/loss/strategies
        # must not keep training with the old one). This also runs on
        # every elastic reshard (recover() re-enters compile()), so
        # old-mesh executables can never serve a post-reshard dispatch.
        from collections import OrderedDict
        self._train_step_execs = {}
        self._superstep_execs = {}
        self._eval_step_execs = OrderedDict()
        policy = getattr(self.config, "anomaly_policy", "none") or "none"
        if policy not in ("none", "skip_step", "rollback", "raise"):
            raise ValueError(
                f"anomaly_policy must be none|skip_step|rollback|raise, "
                f"got {policy!r}")
        self._anomaly_policy = policy
        sentinel = policy != "none"
        loss_f = losses_mod.loss_fn(self.loss_type)
        logits_guid = self._logits_tensor.guid
        preds_guid = self._preds_tensor.guid
        metric_names = self.metrics
        loss_type = self.loss_type
        sparse_ops = self._select_sparse_update_ops()
        self._sparse_update_ops = [op.name for op in sparse_ops]
        anc_names = self._ancestor_op_names(sparse_ops)
        # conv-final models: env values are NHWC-physical; loss/metrics
        # compare against logical-NCHW labels
        logits_nhwc = self._logits_tensor.physical == "nhwc"
        preds_is_nhwc = self._preds_tensor.physical == "nhwc"

        def _env_logits(env):
            v = env[logits_guid]
            return jnp.transpose(v, (0, 3, 1, 2)) if logits_nhwc else v

        def _env_preds(env):
            v = env[preds_guid]
            return jnp.transpose(v, (0, 3, 1, 2)) if preds_is_nhwc else v
        host_ops = [op for op in self.ops
                    if op.name in getattr(self, "_host_resident_ops", set())]
        self._host_resident_list = host_ops
        for op in host_ops:
            for t in op.inputs:
                if t.owner_op is not None and not isinstance(t.owner_op,
                                                             InputOp):
                    raise ValueError(
                        f"host-resident table op {op.name!r} must consume "
                        f"a model input directly (use the fused DLRM "
                        f"embedding layout)")
        from ..core.optimizers import AdamOptimizer
        if host_ops and not isinstance(self.optimizer,
                                       (SGDOptimizer, AdamOptimizer)):
            raise ValueError(
                "host-resident tables support SGD (plain/momentum/"
                "weight-decay) and Adam — stateful optimizers take the "
                "lazy touched-rows host update")
        for op in host_ops:
            if (getattr(op, "aggr", None) == "none"
                    and not getattr(op, "host_aggr_none_ok", False)):
                raise ValueError(
                    f"host-resident table op {op.name!r}: aggr='none' "
                    f"is not implemented on the host path for this op")
        # inputs consumed ONLY by host-resident ops never need to touch the
        # device: the wrapper reads them on the host for the gather/scatter
        # and the jitted step sees only the override values
        consumers_of: Dict[str, List[Op]] = {}
        for op in self.ops:
            if isinstance(op, InputOp):
                continue
            for t in op.inputs:
                if t.owner_op is not None and isinstance(t.owner_op, InputOp):
                    consumers_of.setdefault(t.name, []).append(op)
        hres_names = {op.name for op in host_ops}
        self._host_only_inputs = {
            name for name, cons in consumers_of.items()
            if cons and all(c.name in hres_names for c in cons)}

        def train_step(params, opt_state, op_state, msums, batch, step,
                       host_emb=None):
            rng = jax.random.fold_in(jax.random.PRNGKey(self.config.seed),
                                     step)

            host_cts = None
            if sparse_ops or host_ops:
                sparse_names = {op.name for op in sparse_ops}
                p_dense = {k: v for k, v in params.items()
                           if k not in sparse_names}
                # phase A (no grad): index pipelines, then the embedding
                # lookups evaluated DIRECTLY so ops can hand their
                # forward-gather residuals to the write-only sparse update
                # (apply_with_fwd)
                anc_env, _ = self._forward_env(
                    params, op_state, batch, True, rng,
                    only_ops=set(anc_names) - sparse_names)
                emb_vals, emb_fwd = {}, {}
                for op in sparse_ops:
                    xs_ = [anc_env[t.guid] for t in op.inputs]
                    f = getattr(op, "apply_with_fwd", None)
                    if f is not None:
                        outs, fwd = f(params[op.name], xs_, rng=rng)
                    else:
                        outs, fwd = op.apply(params[op.name], xs_,
                                             training=True, rng=rng), None
                    v = outs[0]
                    sh = self._out_sharding.get(op.outputs[0].guid)
                    if sh is not None:
                        v = jax.lax.with_sharding_constraint(v, sh)
                    emb_vals[op.name] = v
                    anc_env[op.outputs[0].guid] = v
                    if fwd is not None:
                        emb_fwd[op.name] = fwd
                if host_ops:
                    # host-gathered rows enter as plain inputs; their
                    # cotangents leave for the wrapper's host scatter
                    emb_vals = {**emb_vals, **(host_emb or {})}

                # phase B: differentiate the rest of the graph w.r.t. the
                # dense params AND the embedding outputs; the tables never
                # enter the autodiff, so no table-sized dense gradient is
                # ever materialized
                def objective(pd, ev, st):
                    env, st2 = self._forward_env(pd, st, batch, True, rng,
                                                 overrides=dict(ev))
                    loss = loss_f(_env_logits(env), batch["label"])
                    return loss, (_env_preds(env), st2)

                (loss, (preds, st2)), (gd, gev) = jax.value_and_grad(
                    objective, argnums=(0, 1), has_aux=True)(
                        p_dense, emb_vals, op_state)
                grad_leaves = jax.tree.leaves((gd, gev))
                # the optimizer state for sparse tables is NOT part of the
                # dense update: split it out, update it touched-rows-only
                # below, and merge back (keeps one opt_state pytree for
                # checkpoints/sharding)
                slab_names = self.optimizer.sparse_slab_names()
                dense_state = {}
                sparse_state = {}
                for k, sub in opt_state.items():
                    if k in slab_names and isinstance(sub, dict):
                        dense_state[k] = {pk: pv for pk, pv in sub.items()
                                          if pk not in sparse_names}
                        sparse_state[k] = {pk: pv for pk, pv in sub.items()
                                           if pk in sparse_names}
                    else:
                        dense_state[k] = sub
                new_params, new_opt = self.optimizer.update(p_dense, gd,
                                                            dense_state)
                stateful = bool(slab_names) or (
                    isinstance(self.optimizer, SGDOptimizer)
                    and self.optimizer.weight_decay != 0.0)
                pre_step = opt_state.get("step",
                                         jnp.zeros((), jnp.int32))
                for op in sparse_ops:
                    xs = [anc_env[t.guid] for t in op.inputs]
                    if stateful:
                        # the whole per-param slab dict goes in (the
                        # hybrid placement splits an embedding into
                        # kernel + hot_kernel, each with its own state)
                        slabs = {k: dict(sparse_state[k][op.name])
                                 for k in slab_names}
                        new_k, new_slabs = op.sparse_opt_update(
                            params[op.name], xs, gev[op.name],
                            self.optimizer, slabs, pre_step,
                            fwd=emb_fwd.get(op.name))
                        new_params[op.name] = new_k
                        for k in slab_names:
                            ns = new_slabs[k]
                            new_opt[k][op.name] = (
                                ns if isinstance(ns, dict)
                                else {"kernel": ns})
                    else:
                        new_params[op.name] = op.sparse_sgd_update(
                            params[op.name], xs, gev[op.name],
                            self.optimizer.lr, fwd=emb_fwd.get(op.name))
                if host_ops:
                    host_cts = {op.name: gev[op.name] for op in host_ops}
            else:
                def objective(p, st):
                    env, st2 = self._forward_env(p, st, batch, True, rng)
                    loss = loss_f(_env_logits(env), batch["label"])
                    return loss, (_env_preds(env), st2)

                (loss, (preds, st2)), grads = jax.value_and_grad(
                    objective, has_aux=True)(params, op_state)
                grad_leaves = jax.tree.leaves(grads)
                new_params, new_opt = self.optimizer.update(params, grads,
                                                            opt_state)
            # quantized storage, stochastic_rounding rule: re-quantize
            # the updated tables IN the step (master_weight keeps the
            # exact fp32 master — no requant, bit-identical to fp32
            # training; quantization happens at storage boundaries)
            new_params = self._requant_sr_params(new_params, rng)
            # anomaly sentinel: ONE on-device finiteness predicate over the
            # loss and the global gradient norm. Under any active policy
            # the non-finite update is suppressed ON DEVICE (jnp.where
            # against the pre-step values — both live inside the step, so
            # donation costs nothing), keeping params/opt/op-state clean
            # without a host sync; rollback/raise additionally read the
            # flag back at the step boundary (train_batch_device).
            step_ok = None
            if sentinel:
                gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in grad_leaves)
                gnorm = jnp.sqrt(gsq)
                step_ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)

                def _keep(new, old):
                    return jax.tree.map(
                        lambda n, o: jnp.where(step_ok, n, o), new, old)
                new_params = _keep(new_params, params)
                new_opt = _keep(new_opt, opt_state)
                st2 = _keep(st2, op_state)
            # CCE metrics expect probabilities; when the graph doesn't end
            # in a Softmax op, preds are raw logits — normalize them here
            if "crossentropy" in loss_type and preds_guid == logits_guid:
                mpreds = jax.nn.softmax(preds.astype(jnp.float32), axis=-1)
            else:
                mpreds = preds
            mets = metrics_mod.compute_metrics(metric_names, loss_type,
                                               mpreds, batch["label"])
            # accumulate running sums ON DEVICE inside the step (the
            # reference accumulates in device memory with atomics and folds
            # once per epoch, metrics_functions.cu:57-135; host-side
            # accumulation would dispatch extra tiny kernels every step)
            if sentinel:
                # a skipped step contributes nothing (NaNs would poison
                # the epoch's running sums irreversibly)
                new_msums = {k: msums[k]
                             + jnp.where(step_ok, v, jnp.zeros_like(v))
                             for k, v in mets.items()}
            else:
                new_msums = {k: msums[k] + v for k, v in mets.items()}
            mets["loss"] = loss
            if sentinel:
                mets["anomaly"] = ~step_ok
                mets["grad_norm"] = gnorm
            if host_cts is not None:
                mets["_host_cts"] = host_cts
            # the step counter stays device-resident across calls (feeding
            # a fresh host int every step would be one H2D transfer/step)
            return new_params, new_opt, st2, new_msums, step + 1, mets

        def eval_step(params, op_state, batch, host_emb=None):
            env, _ = self._forward_env(params, op_state, batch, False, None,
                                       overrides=host_emb)
            # _env_preds exposes the user-facing logical NCHW form
            return _env_preds(env)

        def train_superstep(params, opt_state, op_state, msums, sbatch,
                            step):
            """K fused steps in ONE executable: lax.scan over the
            stacked [K, ...] megabatch with the train-step body,
            donating the carries. One host→device dispatch then trains
            K steps — deleting K-1 of every K ~0.55 ms dispatch floors
            (BENCHMARKS.md r5 "floor-bound"). The per-step RNG fold,
            on-device sentinel suppression, and metric-sum accumulation
            all run unchanged inside the scan, so K>1 is bit-identical
            to K sequential dispatches of the same batches."""
            def body(carry, bk):
                p, o, st, ms, sp = carry
                p, o, st, ms, sp, mets = train_step(p, o, st, ms, bk, sp)
                return (p, o, st, ms, sp), mets

            (p, o, st, ms, sp), stacked = jax.lax.scan(
                body, (params, opt_state, op_state, msums, step), sbatch)
            # boundary-facing scalars (fit's loss print, the throttle)
            # are the LAST step's values; per-step [K] arrays (metrics,
            # anomaly flags) ride alongside for the boundary policies
            last = jax.tree.map(lambda a: a[-1], stacked)
            return p, o, st, ms, sp, last, stacked

        donate = (0, 1, 2, 3)
        self._train_step = jax.jit(train_step, donate_argnums=donate)
        self._superstep_fn = jax.jit(train_superstep, donate_argnums=donate)
        self._eval_step = jax.jit(eval_step)
        # discover the metric-sum pytree structure with tiny dummies (the
        # keys depend on metric names + loss type only)
        dummy_preds = jnp.zeros((2,) + tuple(self._preds_tensor.shape[1:]),
                                jnp.float32)
        if self.loss_type == losses_mod.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY:
            dummy_labels = jnp.zeros((2, 1), jnp.int32)
        else:
            dummy_labels = jnp.zeros(dummy_preds.shape, jnp.float32)
        self._msums_keys = sorted(metrics_mod.compute_metrics(
            metric_names, loss_type, dummy_preds, dummy_labels).keys())

    def _zero_msums(self):
        # committed replicated: the AOT executable cache requires inputs
        # with deterministic shardings (uncommitted scalars would pin to
        # device 0 and mismatch the executable on the next call)
        rep = NamedSharding(self.mesh, PartitionSpec())
        return {k: put_global(np.zeros((), np.float32), rep)
                for k in self._msums_keys}

    # ------------------------------------------------------------------
    # runtime verbs (reference model.cc:942-993)
    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    # quantized embedding storage (quant/)
    # ------------------------------------------------------------------
    def quant_policies(self):
        """Per-op NON-DEFAULT quantized-storage policies resolved at
        compile ({op name: QuantPolicy}) — what the delta publisher, the
        serving caches/shard tier, and the checkpoint manifest consume."""
        return dict(getattr(self, "_quant_policies", {}) or {})

    def _sr_quant_ops(self):
        """Ops whose policy re-quantizes in the training step
        (stochastic_rounding with a non-fp32 storage dtype), in
        deterministic order for the per-op RNG fold."""
        return sorted(
            (name, pol) for name, pol in self.quant_policies().items()
            if pol.update_rule == "stochastic_rounding"
            and pol.dtype != "fp32"
            and name not in getattr(self, "_host_resident_ops", set()))

    def _requant_sr_params(self, new_params, rng):
        """The in-step stochastic-rounding hook: re-quantize every
        updated table of an SR-policy op (kernel + hybrid hot_kernel) so
        the stored parameter is always the exact fp32 image of its
        quantized representation. Runs inside the jitted step (and thus
        inside the superstep scan body) with a per-(step, op, param)
        folded key — deterministic per seed."""
        sr = self._sr_quant_ops()
        if not sr:
            return new_params
        from ..quant.codec import fake_quant_stochastic
        for i, (name, pol) in enumerate(sr):
            if name not in new_params:
                continue
            sub = dict(new_params[name])
            for j, pname in enumerate(("kernel", "hot_kernel")):
                if pname in sub:
                    k = jax.random.fold_in(rng, 0x51 + 2 * i + j)
                    sub[pname] = fake_quant_stochastic(
                        sub[pname], pol.dtype, k)
            new_params[name] = sub
        return new_params

    def _sr_policy_of(self, op_name: str):
        pol = self.quant_policies().get(op_name)
        if pol is None or pol.dtype == "fp32" \
                or pol.update_rule != "stochastic_rounding":
            return None
        return pol

    def _quant_init_device(self, op, p):
        """Under stochastic_rounding, training starts FROM the stored
        (quantized) representation: quantize-dequantize the fresh table
        once at init (nearest — SR at init would just add noise).
        master_weight inits stay exact fp32."""
        pol = self._sr_policy_of(op.name)
        if pol is None:
            return p
        from ..quant.codec import fake_quant
        return {n: (fake_quant(v, pol.dtype)
                    if n in ("kernel", "hot_kernel") else v)
                for n, v in p.items()}

    def _quant_init_host(self, op):
        pol = self._sr_policy_of(op.name)
        if pol is None:
            return
        from ..quant.codec import fake_quant_np
        tbl = self.host_params[op.name]
        if "kernel" in tbl:
            k = tbl["kernel"]
            tbl["kernel"] = fake_quant_np(
                k.reshape(-1, k.shape[-1]), pol.dtype).reshape(
                    k.shape).astype(np.float32)

    def init_layers(self, seed: Optional[int] = None):
        """Initialize parameters/optimizer/op state, sharded per strategy
        (reference init_layers launches per-op init tasks; initializer GPU
        tasks run at compile, model.cc:1028-1045)."""
        self._pick_conv_s2d()
        seed = self.config.seed if seed is None else seed
        key = jax.random.PRNGKey(seed)
        params: Dict[str, Dict[str, jnp.ndarray]] = {}
        op_state: Dict[str, Any] = {}
        hres = getattr(self, "_host_resident_ops", set())
        self.host_params: Dict[str, Dict[str, np.ndarray]] = {}
        self.host_opt_state: Dict[str, Dict[str, np.ndarray]] = {}
        multiproc = jax.process_count() > 1
        # init computation runs on a LOCAL device (jax.devices()[0] is not
        # addressable from other ranks of a multi-controller job)
        with jax.default_device(jax.local_devices()[0]):
            for i, op in enumerate(self.ops):
                if isinstance(op, InputOp):
                    continue
                if op.name in hres:
                    # table lives in host RAM, filled there (numpy) —
                    # never device_put (reference embedding_avx2.cc path)
                    self.host_params[op.name] = op.host_init(seed + i)
                    self._quant_init_host(op)
                    # stateful optimizers keep their table-shaped state
                    # slabs on the host too (lazy touched-rows update)
                    for slab in self.optimizer.sparse_slab_names():
                        self.host_opt_state.setdefault(op.name, {})[
                            slab] = np.zeros_like(
                                self.host_params[op.name]["kernel"])
                    continue
                if op.param_defs():
                    key, sub = jax.random.split(key)
                    p = op.init_params(sub)
                    p = self._quant_init_device(op, p)
                    shards = self._param_sharding.get(op.name, {})
                    rep = NamedSharding(self.mesh, PartitionSpec())
                    params[op.name] = {
                        n: put_global(v, shards.get(n) or rep)
                        for n, v in p.items()}
                if hasattr(op, "state_defs"):
                    key, sub = jax.random.split(key)
                    defs = op.state_defs()
                    keys = jax.random.split(sub, len(defs))
                    rep = NamedSharding(self.mesh, PartitionSpec())
                    op_state[op.name] = {
                        n: put_global(d.initializer(k, d.shape, d.dtype),
                                      rep)
                        for (n, d), k in zip(sorted(defs.items()), keys)}
        self.params = params
        self.op_state = op_state
        # multi-controller: build optimizer state as one SPMD program so
        # every leaf (incl. fresh scalars like Adam's step) is a global
        # array, never a rank-local committed one
        self.opt_state = (jax.jit(self.optimizer.init_state)(params)
                          if multiproc and params
                          else self.optimizer.init_state(params))
        self._step = 0
        self._step_dev = None
        self._msums = None
        return self

    def _pick_conv_s2d(self):
        """Choose the conv stem lowering per FFConfig.conv_s2d: "on"
        forces space-to-depth on every eligible conv; "auto" measures
        both lowerings per eligible conv on the attached device and keeps
        the faster (the reference picks its conv algorithm the same way —
        by running candidates, conv_2d.cu:217)."""
        mode = getattr(self.config, "conv_s2d", "off")
        from ..ops.conv import Conv2D, measure_s2d_wins
        for op in self.ops:
            if not isinstance(op, Conv2D) or not op.s2d_eligible():
                continue
            # decisions are cached PER MODE: a re-init after the config
            # changed must not keep the previous mode's lowering
            if getattr(op, "_s2d_mode", None) == mode:
                continue
            op._use_s2d = (False if mode == "off"
                           else True if mode == "on"
                           else measure_s2d_wins(op))
            op._s2d_mode = mode
            op._s2d_decided = True
            if mode != "off":
                log_model.info("conv %s: space-to-depth lowering %s (%s)",
                               op.name, "ON" if op._use_s2d else "off",
                               mode)

    def _stage_input(self, arr, sharding):
        """Host batch -> global device array under the model's sharding.
        Multi-controller: every rank passes the SAME full host batch (the
        loaders keep the whole dataset per host, like the reference's
        per-node zero-copy residency, dlrm.cc:384-484) and jax extracts
        this rank's addressable shards — a plain device_put cannot target
        non-addressable devices."""
        if jax.process_count() > 1:
            arr = np.asarray(arr)
            return jax.make_array_from_process_local_data(
                sharding, arr, arr.shape)
        return jax.device_put(arr, sharding)

    def _device_batch(self, batch: Dict[str, np.ndarray],
                      with_label: bool = True) -> Dict[str, Any]:
        from ..analysis import sanitizer as _san
        _san.note_jax_dispatch("batch staging device_put")
        out = {}
        puts: Dict[str, tuple] = {}   # name -> (host array, sharding)
        host_only = getattr(self, "_host_only_inputs", set())
        for t in self.input_tensors:
            if t.name in batch:
                if t.name in host_only:
                    # consumed only by host-resident tables: stays numpy
                    # (no H2D; the wrapper reads it for the host gather)
                    out[t.name] = np.asarray(batch[t.name])
                else:
                    puts[t.name] = (batch[t.name],
                                    self._out_sharding[t.guid])
        if with_label:
            lab = batch["label"]
            sh = self._label_sharding
            # the label tensor's shape can be a folded view of what the user
            # passes (e.g. NMT feeds (batch, seq) labels against
            # (batch*seq, 1) logits); re-check divisibility on the real array
            ndev = int(np.prod([self.mesh.shape[a]
                                for a in self.mesh.axis_names]))
            if lab.shape[0] % ndev != 0:
                sh = NamedSharding(self.mesh, PartitionSpec())
            puts["label"] = (lab, sh)
        if jax.process_count() > 1:
            for k, (v, sh) in puts.items():
                out[k] = self._stage_input(v, sh)
        elif puts:
            # ONE batched device_put for the whole step input: the
            # per-call dispatch overhead (not the bytes) dominates small
            # H2D puts, and the hot loop pays it every step — batching
            # the puts measured ~1.6x faster staging on the DLRM input
            # dict (dense+sparse+label)
            names = list(puts)
            vals = jax.device_put([puts[k][0] for k in names],
                                  [puts[k][1] for k in names])
            out.update(zip(names, vals))
        return out

    def train_batch(self, batch: Dict[str, np.ndarray]):
        """One fused train step (forward+backward+update). Returns metrics
        dict of device scalars (async — don't block)."""
        return self.train_batch_device(self._device_batch(batch))

    def _ensure_step_state(self):
        """Lazy-init the device-resident step counter and metric sums that
        the jitted step threads through (single definition — warmup and
        hot loop must compile against identically-sharded inputs)."""
        if not getattr(self, "_msums", None):
            self._msums = self._zero_msums()
        if getattr(self, "_step_dev", None) is None:
            self._step_dev = put_global(
                np.asarray(self._step, np.int32),
                NamedSharding(self.mesh, PartitionSpec()))

    def _split_host_idx(self, device_batch: Dict):
        """(device_batch_for_jit, host_idx | None): indices for host-
        resident tables never ride PCIe — host-only inputs are kept numpy
        by _device_batch and popped before the jit call (np.asarray on an
        already-host array is free; on a staged device array it is the one
        unavoidable D2H)."""
        hres = getattr(self, "_host_resident_list", None)
        if not hres:
            return device_batch, None
        device_batch = dict(device_batch)
        host_idx = {}
        host_only = getattr(self, "_host_only_inputs", set())
        for op in hres:
            name = op.inputs[0].name
            host_idx[op.name] = np.asarray(device_batch[name])
            if name in host_only:
                device_batch.pop(name)
        return device_batch, host_idx

    def _exec_key(self, device_batch: Dict):
        """Executable-cache key for a staged batch. Stringifying shardings
        is the slow part, so memoize it by sharding-object identity (the
        model's sharding objects are long-lived)."""
        smemo = getattr(self, "_sharding_str_memo", None)
        if smemo is None:
            smemo = self._sharding_str_memo = {}

        def _shs(v):
            sh = getattr(v, "sharding", None)
            hit = smemo.get(id(sh))
            if hit is not None and hit[0] is sh:
                return hit[1]
            if len(smemo) > 256:
                smemo.clear()
            s = str(sh)
            # pin the sharding object so a GC'd id can't alias a
            # different sharding to a stale string
            smemo[id(sh)] = (sh, s)
            return s

        # numpy's dtype.name property is surprisingly slow (~µs each,
        # 3+ arrays x every step); memoize by the (singleton-ish,
        # hashable) dtype object
        dmemo = getattr(self, "_dtype_name_memo", None)
        if dmemo is None:
            dmemo = self._dtype_name_memo = {}

        def _dname(dt):
            n = dmemo.get(dt)
            if n is None:
                n = dmemo[dt] = dt.name
            return n

        return tuple(sorted(
            (k, v.shape, _dname(v.dtype), _shs(v))
            for k, v in device_batch.items()))

    # --- persistent warm caches (utils/warmcache.py) -------------------
    def attach_compile_cache(self, cache) -> None:
        """Attach a persistent :class:`~..utils.warmcache.CompileCache`
        (or a directory path) so AOT train/eval/superstep executables
        serialize to disk and later boots/recoveries load instead of
        recompiling. Survives ``compile()``/elastic reshards — the
        in-memory exec dicts reset, the disk cache persists."""
        if isinstance(cache, str):
            from ..utils.warmcache import CompileCache
            cache = CompileCache(cache)
        self._compile_cache = cache

    def attach_plan_cache(self, cache) -> None:
        """Attach a persistent :class:`~..utils.warmcache.PlanCache` so
        elastic ``recover()``/``expand()`` re-plans warm-start from disk
        instead of re-running the MCMC search."""
        if isinstance(cache, str):
            from ..utils.warmcache import PlanCache
            cache = PlanCache(cache)
        self._plan_cache = cache

    def compile_cache_stats(self) -> Optional[Dict[str, Any]]:
        cache = getattr(self, "_compile_cache", None)
        return None if cache is None else cache.stats()

    def _cached_compile(self, kind: str, shape_key, lower,
                        fresh: bool = False):
        """lower().compile() through the persistent CompileCache when
        one is attached: a hit deserializes the stored executable (ms)
        instead of recompiling (s); misses and EVERY invalid entry
        (torn, stale code, wrong mesh) compile fresh and re-store.
        `fresh=True` skips the lookup — the GSPMD
        recompile-on-sharding-disagree fallback must not re-load the
        very entry that just disagreed."""
        cache = getattr(self, "_compile_cache", None)
        if cache is None:
            return lower().compile()
        ckey = cache.exec_key(kind, self, shape_key)
        if not fresh:
            exec_ = cache.get(ckey)
            if exec_ is not None:
                return exec_
        exec_ = lower().compile()
        cache.put(ckey, exec_)
        return exec_

    def _maybe_return_devices(self, k: int = 1) -> None:
        """Scale-UP detection at a dispatch boundary: when elastic
        expansion is enabled and the fault plan (or a registry poll)
        reports devices RETURNED at any of the next `k` steps (a fused
        superstep checks its whole window, like the drop hook), raise
        the typed :class:`MeshReturned` BEFORE dispatch — symmetric with
        the drop-device hook, so no state for this step is half-applied
        and fit()'s expansion resumes exactly where the shrink path
        does."""
        if not getattr(self.config, "elastic_expand", False):
            return
        nret = 0
        for s in range(max(int(k), 1)):
            nret += faults.take_return_device(self._step + s)
        if not nret:
            return
        in_mesh = {id(d) for d in self.mesh.devices.flat}
        avail = [d for d in jax.devices() if id(d) not in in_mesh]
        if not avail:
            log_model.warning(
                "fault-injected device return at step %d ignored: no "
                "device outside the current %d-device mesh", self._step,
                self.mesh.size)
            return
        returned = avail[:nret]
        raise MeshReturned(
            f"fault-injected return of {len(returned)} device(s) at "
            f"step {self._step}", returned=returned)

    def _attach_configured_caches(self, checkpoint_dir=None) -> None:
        """Open the persistent plan/compile caches per
        ``FFConfig.compile_cache_dir`` ("" = off, "auto" = next to the
        checkpoint manifest, else an explicit path) and attach them,
        keeping any caches the caller attached explicitly."""
        configured = getattr(self.config, "compile_cache_dir", "") or ""
        if not configured:
            return
        if (getattr(self, "_plan_cache", None) is not None
                and getattr(self, "_compile_cache", None) is not None):
            return
        from ..utils.warmcache import open_caches
        plan, comp = open_caches(checkpoint_dir, configured)
        if plan is not None and getattr(self, "_plan_cache", None) is None:
            self._plan_cache = plan
        if comp is not None and getattr(self, "_compile_cache",
                                        None) is None:
            self._compile_cache = comp

    def _stage_step(self, batch: Dict[str, np.ndarray],
                    with_label: bool = True) -> "StagedStep":
        """Fully stage one host batch for the jitted step: H2D put against
        the input shardings + the host-index split. Everything here is
        thread-safe jax/numpy, so the prefetch pipeline's staging thread
        runs it for step N+1 while step N executes (data/prefetch.py)."""
        db = self._device_batch(batch, with_label=with_label)
        db, host_idx = self._split_host_idx(db)
        return StagedStep(db, host_idx)

    def train_batch_device(self, device_batch: Dict, next_host_idx=None):
        """train_batch for a batch already staged on device (skips the
        host->device put; used by benchmark loops that pre-stage)."""
        device_batch, host_idx = self._split_host_idx(device_batch)
        return self._train_dispatch(device_batch, host_idx, next_host_idx)

    def train_batch_staged(self, staged: "StagedStep", next_host_idx=None):
        """train step for a StagedStep from `_stage_step` (the prefetch
        pipeline's item type). `next_host_idx` — the NEXT staged batch's
        host-table indices (or a zero-arg callable returning them, eval'd
        at scatter-launch time) — lets the async host-table worker stage
        the gather for step N+1 while step N executes on device (gather
        first, then this step's scatter: deterministic one-step
        staleness, see FFConfig.host_tables_async).

        A `_stage_superstep` megabatch item (`staged.k > 1`) routes to
        the fused K-step scan executable instead — one dispatch, k
        optimizer steps."""
        if getattr(staged, "k", 1) > 1:
            return self.train_superstep_device(staged.device_batch)
        return self._train_dispatch(staged.device_batch, staged.host_idx,
                                    next_host_idx)

    # --- fused supersteps ---------------------------------------------
    def resolve_superstep(self, batch_size: Optional[int] = None) -> int:
        """The superstep K this model actually trains with.

        FFConfig.superstep: 1 = the exact legacy per-step dispatch; an
        int K>1 fuses K steps per dispatch; "auto" picks the largest
        power-of-two K <= 16 whose stacked megabatch fits the staging
        budget (5% of per-chip HBM on TPU — the megabatch lives beside
        params/opt state/activations — or a 128 MB host-RAM cap
        elsewhere). Host-resident-table models always resolve to 1 with
        a one-time warning: their per-step host gather/scatter cannot
        run inside the fused scan yet."""
        raw = getattr(self.config, "superstep", 1)
        if raw in (None, "", 1, "1"):
            return 1
        if getattr(self, "_host_resident_list", None):
            if not getattr(self, "_superstep_host_warned", False):
                self._superstep_host_warned = True
                log_model.warning(
                    "superstep=%s requested, but ops %s keep their "
                    "tables host-resident: the per-step host gather/"
                    "scatter cannot run inside the fused scan — falling "
                    "back to superstep=1", raw,
                    [op.name for op in self._host_resident_list])
            return 1
        if raw != "auto":
            k = int(raw)
            if k < 1:
                raise ValueError(f"superstep must be >= 1, got {raw!r}")
            return k
        bs = int(batch_size or self.config.batch_size)
        scale = bs / max(self.config.batch_size, 1)
        tensors = list(self.input_tensors)
        if self.label_tensor is not None:
            tensors.append(self.label_tensor)
        per_batch = sum(float(np.prod(t.shape))
                        * np.dtype(t.dtype).itemsize * scale
                        for t in tensors)
        if jax.default_backend() == "tpu":
            from ..search.cost_model import TPUSpec
            budget = 0.05 * TPUSpec.detect().hbm_capacity_bytes
        else:
            budget = 128e6
        k = 16
        while k > 1 and k * per_batch > budget:
            k //= 2
        return k

    def _superstep_sharding(self, sh: NamedSharding) -> NamedSharding:
        """Input sharding for a stacked [K, batch, ...] megabatch: the
        new leading step axis is unsharded, the per-step dims keep the
        model's input specs. Memoized by source-sharding identity (the
        model's sharding objects are long-lived — same trick as
        _exec_key's string memo)."""
        memo = getattr(self, "_super_sharding_memo", None)
        if memo is None:
            memo = self._super_sharding_memo = {}
        hit = memo.get(id(sh))
        if hit is not None and hit[0] is sh:
            return hit[1]
        if len(memo) > 256:
            memo.clear()
        s = NamedSharding(self.mesh,
                          PartitionSpec(*((None,) + tuple(sh.spec))))
        memo[id(sh)] = (sh, s)
        return s

    def _device_superbatch(self, stacked: Dict[str, Any]) -> Dict:
        """Stage a [K, batch, ...] stacked megabatch on device in ONE
        device_put (the K-step extension of _device_batch's single-put
        win): every input rides the model's per-step sharding with the
        leading step axis unsharded, so `sbatch[k]` inside the scan has
        exactly the per-step layout the K=1 executable sees."""
        if getattr(self, "_host_resident_list", None):
            raise ValueError(
                "superstep megabatches do not support host-resident "
                "tables (resolve_superstep falls back to K=1)")
        puts: Dict[str, tuple] = {}
        for t in self.input_tensors:
            if t.name in stacked:
                puts[t.name] = (stacked[t.name], self._superstep_sharding(
                    self._out_sharding[t.guid]))
        lab = np.asarray(stacked["label"])
        sh = self._label_sharding
        ndev = int(np.prod([self.mesh.shape[a]
                            for a in self.mesh.axis_names]))
        # same per-step divisibility re-check as _device_batch, against
        # the PER-STEP sample dim (axis 1 of the stacked array)
        if lab.shape[1] % ndev != 0:
            sh = NamedSharding(self.mesh, PartitionSpec())
        puts["label"] = (lab, self._superstep_sharding(sh))
        out: Dict[str, Any] = {}
        if jax.process_count() > 1:
            for name, (v, shd) in puts.items():
                out[name] = self._stage_input(v, shd)
        else:
            names = list(puts)
            vals = jax.device_put([puts[n][0] for n in names],
                                  [puts[n][1] for n in names])
            out.update(zip(names, vals))
        return out

    def _stage_superstep(self, stacked: Dict[str, Any]) -> "StagedStep":
        """Fully stage one K-step megabatch (stacked host arrays with
        leading axis K — data.prefetch.stack_batches, or a free reshape
        of a contiguous dataset slice) for the fused-scan executable.
        Thread-safe like _stage_step, so the prefetch ring stages
        megabatch G+1 while the device trains megabatch G."""
        k = int(np.asarray(next(iter(stacked.values()))).shape[0])
        return StagedStep(self._device_superbatch(stacked), None, k)

    def train_superstep(self, batches: Sequence[Dict[str, Any]]):
        """Train K fused steps from a list of same-shaped host batches
        (each including its "label"): one dispatch, len(batches)
        optimizer steps. Returns the LAST step's metrics plus
        `per_step` stacked [K] arrays for every metric."""
        from ..data.prefetch import stack_batches
        return self.train_batch_staged(
            self._stage_superstep(stack_batches(batches)))

    def train_superstep_device(self, sbatch: Dict):
        """Train step for a staged [K, batch, ...] megabatch: ONE
        host→device dispatch of the AOT-cached fused-scan executable
        trains K steps (step accounting advances by K). Boundary
        semantics match K sequential steps: the anomaly sentinel runs
        per step INSIDE the scan (skip_step suppresses there, with zero
        host syncs); rollback/raise fire here from the stacked flags
        with the faulting step index; fault-injected device loss
        scheduled for ANY step in the window surfaces as MeshDegraded
        BEFORE dispatch (elastic recovery checks at superstep
        boundaries, so no state for the window is half-applied)."""
        k = int(next(iter(sbatch.values())).shape[0])
        self._ensure_step_state()
        if faults.active() is not None:
            for s in range(self._step, self._step + k):
                ndrop = faults.take_drop_device(s)
                if ndrop:
                    devs = list(self.mesh.devices.flat)
                    ndrop = min(ndrop, len(devs) - 1)
                    raise MeshDegraded(
                        f"fault-injected loss of {ndrop} device(s) at "
                        f"superstep boundary (step {self._step}, K={k})",
                        lost=devs[len(devs) - ndrop:],
                        surviving=devs[:len(devs) - ndrop])
            for s in range(k):
                if faults.take_nan_grad(self._step + s):
                    # poison ONLY the faulting step's slice: the sibling
                    # steps in the scan must stay clean, exactly like
                    # the K=1 path poisons one step's batch
                    sbatch = faults.poison_batch(sbatch, row=s)
            self._maybe_return_devices(k)
        args = (self.params, self.opt_state, self.op_state, self._msums,
                sbatch, self._step_dev)
        key = (k,) + self._exec_key(sbatch)
        execs = getattr(self, "_superstep_execs", None)
        if execs is None:
            execs = self._superstep_execs = {}
        exec_ = execs.get(key)
        if exec_ is None:
            exec_ = execs[key] = self._cached_compile(
                "superstep", key, lambda: self._superstep_fn.lower(*args))
        with obstrace.span("train/superstep", step=self._step, k=k), \
                superstep_annotation(self._step, k,
                                     enabled=bool(
                                         self.config.profile_dir)):
            try:
                outs = exec_(*args)
            except ValueError as e:
                # same GSPMD recompile-on-sharding-disagree fallback as
                # the K=1 dispatch
                if not _sharding_mismatch(e):
                    raise
                exec_ = execs[key] = self._cached_compile(
                    "superstep", key,
                    lambda: self._superstep_fn.lower(*args), fresh=True)
                outs = exec_(*args)
        (self.params, self.opt_state, self.op_state, self._msums,
         self._step_dev, last, stacked) = outs
        step0 = self._step
        self._step += k
        self.perf.sums = dict(self._msums)
        mets = dict(last)
        mets["per_step"] = stacked
        mets["superstep"] = k
        policy = getattr(self, "_anomaly_policy", "none")
        if policy in ("rollback", "raise"):
            flags = np.asarray(stacked["anomaly"])
            if flags.any():
                # every bad update was already suppressed per step ON
                # DEVICE inside the scan (state is clean); report the
                # FIRST faulting step so the caller's recovery targets it
                idx = int(np.argmax(flags))
                raise AnomalyError(
                    step=step0 + idx,
                    loss=float(np.asarray(stacked["loss"])[idx]),
                    grad_norm=float(np.asarray(
                        stacked["grad_norm"])[idx]))
        return mets

    def _train_dispatch(self, device_batch: Dict, host_idx,
                        next_host_idx=None):
        self._ensure_step_state()
        if faults.active() is not None:
            ndrop = faults.take_drop_device(self._step)
            if ndrop:
                # simulated preemption: the runtime's view of the mesh
                # shrinks by the LAST ndrop devices (they stay physically
                # alive on a CPU test mesh — exactly how a lost peer
                # looks from the surviving hosts). Raised BEFORE dispatch
                # so no state for this step is half-applied.
                devs = list(self.mesh.devices.flat)
                ndrop = min(ndrop, len(devs) - 1)
                raise MeshDegraded(
                    f"fault-injected loss of {ndrop} device(s) at step "
                    f"{self._step}", lost=devs[len(devs) - ndrop:],
                    surviving=devs[:len(devs) - ndrop])
            self._maybe_return_devices()
        if faults.active() is not None and faults.take_nan_grad(self._step):
            # fault harness: poison the batch so NaNs flow through the
            # REAL autodiff into the loss/grad-norm the sentinel watches
            # (same shapes/dtypes/shardings — the cached executable holds)
            device_batch = faults.poison_batch(device_batch)
        args = (self.params, self.opt_state, self.op_state, self._msums,
                device_batch, self._step_dev)
        if host_idx is not None:
            args = args + (self._host_emb_input(host_idx),)
        hres = host_idx is not None
        # hot loop: call the AOT-compiled executable directly — the pjit
        # python dispatch re-validates the big param pytree every call,
        # which costs more than the step itself on fast models. Keyed by
        # the batch signature so alternating shapes (e.g. a remainder
        # batch) each compile once.
        key = self._exec_key(device_batch)
        from ..analysis import sanitizer as _san
        _san.note_jax_dispatch("train executable")
        execs = getattr(self, "_train_step_execs", None)
        if execs is None:
            execs = self._train_step_execs = {}
        exec_ = execs.get(key)
        if exec_ is None:
            exec_ = execs[key] = self._cached_compile(
                "train", key, lambda: self._train_step.lower(*args))
        with obstrace.span("train/step", step=self._step):
            try:
                outs = exec_(*args)
            except ValueError as e:
                # GSPMD may give step outputs different shardings than
                # the initial inputs; one recompile against the
                # propagated shardings reaches the fixed point (the
                # sharding check runs before execution, so donated
                # buffers are still intact)
                if not _sharding_mismatch(e):
                    raise
                exec_ = execs[key] = self._cached_compile(
                    "train", key, lambda: self._train_step.lower(*args),
                    fresh=True)
                outs = exec_(*args)
        (self.params, self.opt_state, self.op_state, self._msums,
         self._step_dev, mets) = outs
        self._step += 1
        policy = getattr(self, "_anomaly_policy", "none")
        # the sentinel flag (device bool) guards the host-table scatter on
        # every policy: NaN cotangents scattered into host tables could not
        # be undone by skip_step's on-device suppression
        anomaly_flag = mets.get("anomaly") if policy != "none" else None
        if hres:
            if getattr(self.config, "host_tables_async", True):
                # pipelined (double-buffering): the cotangent readback +
                # host scatter run on a worker thread, overlapping the
                # NEXT step's gather/H2D/dispatch and device execution.
                # When the caller knows the next batch (`next_host_idx` —
                # fit's streaming prefetch does), the worker gathers the
                # NEXT step's rows FIRST (they are ready almost
                # immediately, so the next dispatch never waits on the
                # scatter), then scatters this step's update — the
                # documented bounded ONE-step staleness, made
                # deterministic: the next step always sees updates
                # through step N-1. Table reads and writes serialize on
                # _host_table_lock, so any racing reader sees the table
                # atomically before or after the scatter — never torn
                # rows. Only one worker in flight: join the previous
                # first.
                self._host_drain()
                import threading
                cts = mets.pop("_host_cts")
                step = self._step - 1   # capture NOW: the thread may run
                # after the next call's increment
                nh = (next_host_idx() if callable(next_host_idx)
                      else next_host_idx)
                gathered = threading.Event()
                self._host_gather_pending = ((nh, gathered)
                                             if nh is not None else None)
                gen = getattr(self, "_host_gen", 0)

                def scatter():
                    try:
                        try:
                            if nh is not None:
                                self._host_gather_next = (
                                    nh, self._host_emb_forward(nh))
                        finally:
                            gathered.set()   # never leave a consumer
                            # parked on the event
                        faults.maybe_stall("scatter")   # wedged-worker
                        # fault: the drain watchdog must catch it
                        if gen != getattr(self, "_host_gen", 0):
                            # elastic recovery abandoned this worker and
                            # replaced the tables underneath it — a late
                            # scatter would corrupt the restored state
                            return
                        if (anomaly_flag is None
                                or not bool(np.asarray(anomaly_flag))):
                            self._host_emb_update(host_idx, cts, step)
                    except BaseException as e:   # re-raised at drain
                        self._host_scatter_exc = e
                t = threading.Thread(target=scatter, daemon=True,
                                     name="ff-scatter")
                self._host_scatter_thread = t
                t.start()
            else:
                # exact ordering: the cotangent readback is the step's
                # true completion
                cts = mets.pop("_host_cts")
                if (anomaly_flag is None
                        or not bool(np.asarray(anomaly_flag))):
                    self._host_emb_update(host_idx, cts, self._step - 1)
        # the running sums live on device; PerfMetrics syncs at report().
        # shallow-copy so perf.reset()/report() mutating perf.sums can
        # never corrupt the jit carry
        self.perf.sums = dict(self._msums)
        if policy in ("rollback", "raise") and bool(
                np.asarray(anomaly_flag)):
            # the flag readback is the one host sync these policies cost;
            # skip_step never syncs. The bad update was already suppressed
            # on device, so state is clean whichever way the caller (fit's
            # rollback loop, or the user) handles this.
            raise AnomalyError(step=self._step - 1,
                               loss=float(mets["loss"]),
                               grad_norm=float(np.asarray(
                                   mets["grad_norm"])))
        return mets

    @property
    def _host_lock(self):
        """Serializes host-table reads (gather) against the async scatter
        thread's writes — atomic either-order visibility on EVERY path
        (native, numpy fallback, stateful updates), not just the native
        pool's internal serialization."""
        lk = getattr(self, "_host_table_lock", None)
        if lk is None:
            from ..analysis.sanitizer import make_lock
            # no_dispatch: gathers copy rows OUT under the lock and
            # device_put after release; a dispatch in the critical
            # section would stall the scatter worker (FLX203)
            lk = self._host_table_lock = make_lock(
                "FFModel._host_table_lock", no_dispatch=True)
        return lk

    def _worker_deadline_s(self) -> float:
        """Configured background-worker liveness deadline (0 = watchdogs
        off, every wait blocks forever — the pre-elastic behavior)."""
        return float(getattr(self.config, "worker_deadline_s", 0.0)
                     or 0.0)

    def _host_drain(self, deadline_s: Optional[float] = None):
        """Join the in-flight async host scatter (no-op when none) and
        surface any exception it hit — a silently dropped scatter would
        corrupt training. Call before any read of host_params that needs
        the latest update (eval, checkpoint, end of fit).

        With a worker deadline configured (FFConfig.worker_deadline_s or
        the explicit argument), a scatter worker that outlives it raises
        a typed WorkerStalled (structured stall report, worker left
        un-joined) instead of hanging the training loop; the elastic
        layer abandons it via `_host_abandon` and recovers."""
        t = getattr(self, "_host_scatter_thread", None)
        if t is not None and t.is_alive():
            dl = (self._worker_deadline_s() if deadline_s is None
                  else deadline_s)
            if dl > 0:
                t0 = time.perf_counter()
                t.join(dl)
                if t.is_alive():
                    raise WorkerStalled(StallReport(
                        worker=t.name, waiting_for="host-table scatter "
                        "completion", waited_s=time.perf_counter() - t0,
                        deadline_s=dl, detail=f"step {self._step}"))
            else:
                t.join()
        self._host_scatter_thread = None
        exc = getattr(self, "_host_scatter_exc", None)
        if exc is not None:
            self._host_scatter_exc = None
            raise exc

    def _host_abandon(self):
        """Drop (without joining) the in-flight scatter worker and any
        chained gather, bumping the table generation so a late write
        from the abandoned worker is discarded rather than scattered
        into state the elastic recovery is about to replace."""
        self._host_gen = getattr(self, "_host_gen", 0) + 1
        self._host_scatter_thread = None
        self._host_scatter_exc = None
        self._host_prefetch_invalidate()

    def _host_prefetch_invalidate(self):
        """Drop a chained host-table gather (it is stale after anything
        that replaces the tables underneath it — checkpoint restore,
        rollback)."""
        self._host_gather_next = None
        self._host_gather_pending = None

    def _host_emb_input(self, host_idx):
        """Forward rows for the host-resident tables feeding the jitted
        step. Under the async pipeline the previous step's worker gathers
        these rows FIRST (before its scatter — the bounded one-step
        staleness the async mode documents), so by the time this step
        dispatches, the rows are usually staged; the consumer waits only
        on the gather event, never on the scatter, keeping the scatter
        overlapped with this step's device execution. Without a chained
        gather: inline gather (exact when async is off — there is no
        worker; bounded one-step staleness when async is on and a scatter
        is in flight — the table lock makes it atomic either-order)."""
        pending = getattr(self, "_host_gather_pending", None)
        if pending is not None and pending[0] is host_idx:
            self._host_gather_pending = None
            dl = self._worker_deadline_s()
            if dl > 0:
                if not pending[1].wait(dl):
                    t = getattr(self, "_host_scatter_thread", None)
                    raise WorkerStalled(StallReport(
                        worker=getattr(t, "name", "ff-scatter"),
                        waiting_for="chained host-table gather",
                        waited_s=dl, deadline_s=dl,
                        detail=f"step {self._step}",
                        alive=bool(t is not None and t.is_alive())))
            else:
                pending[1].wait()
            got = getattr(self, "_host_gather_next", None)
            self._host_gather_next = None
            if got is not None and got[0] is host_idx:
                return got[1]
            # the worker died before gathering — surface its error here
            # (the step boundary), then fall through to the inline path
            self._host_drain()
        return self._host_emb_forward(host_idx)

    def _host_emb_forward(self, host_idx):
        """Host-side gather for host-resident tables: numpy lookup on the
        already-read-back indices, rows shipped to the device at the op's
        output sharding.

        Only the table READ holds ``_host_lock`` (``host_lookup`` returns
        fresh arrays, never views into the table); the ``device_put`` H2D
        transfer happens after release — flexcheck's blocking-under-lock
        rule (FLX203) pins that a dispatch never stalls the async scatter
        worker contending for the same lock."""
        rows = {}
        with self._host_lock:
            for op in self._host_resident_list:
                rows[op.name] = op.host_lookup(self.host_params[op.name],
                                               host_idx[op.name])
        from ..analysis import sanitizer as _san
        _san.note_jax_dispatch("host-table row device_put")
        return {op.name: jax.device_put(
                    rows[op.name], self._out_sharding[op.outputs[0].guid])
                for op in self._host_resident_list}

    def _host_emb_update(self, host_idx, cts, step):
        opt = self.optimizer
        stateful = bool(opt.sparse_slab_names()) or (
            isinstance(opt, SGDOptimizer) and opt.weight_decay != 0.0)
        # the device readback happens OUTSIDE the table lock (it is the
        # slow part the async mode overlaps); only the table mutation
        # serializes against concurrent gathers
        cts_np = {op.name: np.asarray(cts[op.name], dtype=np.float32)
                  for op in self._host_resident_list}
        with self._host_lock:
            for op in self._host_resident_list:
                if stateful:
                    # lazy momentum/Adam on the host (same semantics as
                    # the device tile path)
                    op.host_opt_update(
                        self.host_params[op.name], host_idx[op.name],
                        cts_np[op.name], opt,
                        self.host_opt_state.get(op.name, {}), step)
                else:
                    op.host_sgd_update(self.host_params[op.name],
                                       host_idx[op.name],
                                       cts_np[op.name], opt.lr)
                pol = self._sr_policy_of(op.name)
                if pol is not None:
                    # stochastic_rounding: re-quantize exactly the rows
                    # this scatter touched (deterministic per step)
                    from ..quant.codec import fake_quant_stochastic_np
                    rows = np.unique(np.asarray(
                        op.host_delta_touched_rows(host_idx[op.name])))
                    kern = self.host_params[op.name]["kernel"]
                    v = kern.reshape(-1, kern.shape[-1])
                    rng = np.random.RandomState(
                        (self.config.seed ^ (int(step) * 2654435761))
                        & 0x7FFFFFFF)
                    v[rows] = fake_quant_stochastic_np(v[rows], pol.dtype,
                                                       rng)

    @staticmethod
    def to_logical(value, tensor):
        """Bring a raw _forward_env value into the tensor's logical (NCHW)
        dim order — conv-stack tensors are stored NHWC (Tensor.physical)."""
        if tensor.physical == "nhwc":
            return jnp.transpose(value, (0, 3, 1, 2))
        return value

    def forward_batch(self, batch: Dict[str, np.ndarray],
                      host_gather: Optional[Callable] = None):
        """Forward pass for one host batch (no labels). ``host_gather``
        overrides the host-resident-table row gather — the serving
        engine passes its LRU-cached gather (serve/cache.py) so hot rows
        skip the numpy table lookup; the default is the exact
        ``_host_emb_forward`` path."""
        db = self._device_batch(batch, with_label=False)
        hres = getattr(self, "_host_resident_list", None)
        if hres:
            self._host_drain()   # eval must see the last step's scatter
            db = dict(db)
            host_idx = {}
            for op in hres:
                name = op.inputs[0].name
                host_idx[op.name] = np.asarray(db[name])
                if name in getattr(self, "_host_only_inputs", set()):
                    db.pop(name)
            gather = host_gather or self._host_emb_forward
            return self._eval_dispatch(db, gather(host_idx))
        return self._eval_dispatch(db)

    # --- serving entry points (serve/engine.py) -----------------------
    def bucket_sizes(self, max_batch: int) -> tuple:
        """The power-of-two eval batch buckets this model admits, small
        to large. Serving pads every dynamic batch up to the smallest
        bucket so each dispatch hits one of a FIXED set of pre-compiled
        executables (warmup_buckets). The floor is the mesh size when
        the input shardings split the sample dim — a 3-row device_put
        against an 8-way sharded spec has no even shards."""
        ndev = max(int(self.mesh.size), 1) if self.mesh is not None else 1
        sharded = any(
            bool(self._out_sharding[t.guid].spec)
            for t in self.input_tensors
            if t.guid in getattr(self, "_out_sharding", {}))
        floor = ndev if sharded else 1
        out, b = [], 1
        while b <= max(int(max_batch), 1):
            if b >= floor:
                out.append(b)
            b *= 2
        if not out:
            out = [floor]
        return tuple(out)

    def forward_bucket(self, batch: Dict[str, np.ndarray],
                       bucket: Optional[int] = None,
                       host_gather: Optional[Callable] = None):
        """Bucketed eval entry: zero-pad the batch's rows up to `bucket`
        (default: the smallest admissible power-of-two), dispatch the
        padded batch through the AOT eval cache, and return predictions
        for ONLY the real rows. Row-wise graphs (every model in the zoo
        ends per-sample) make the unpadded rows bit-identical to a
        direct ``forward_batch`` of the same rows — tests/test_serve.py
        pins that contract."""
        from ..data.dataloader import pad_batch_rows
        n = int(next(iter(batch.values())).shape[0])
        if bucket is None:
            # smallest admissible power-of-two >= n
            bucket = self.bucket_sizes(1)[-1]
            while bucket < n:
                bucket *= 2
        if bucket < n:
            raise ValueError(f"bucket {bucket} < batch rows {n}")
        padded = pad_batch_rows(batch, bucket) if bucket > n else batch
        out = self.forward_batch(padded, host_gather=host_gather)
        return out[:n] if bucket > n else out

    def warmup_buckets(self, buckets: Sequence[int],
                       host_gather: Optional[Callable] = None) -> float:
        """AOT-compile the eval executable for every bucket size up
        front (synthetic zero batches from the input specs), so no live
        request ever pays a compile. Returns the warmup seconds."""
        t0 = time.perf_counter()
        for b in buckets:
            batch = {}
            for t in self.input_tensors:
                shape = (int(b),) + tuple(t.shape[1:])
                if jnp.issubdtype(jnp.dtype(t.dtype), jnp.integer):
                    batch[t.name] = np.zeros(shape, np.int32)
                else:
                    batch[t.name] = np.zeros(shape, np.float32)
            jax.block_until_ready(
                self.forward_batch(batch, host_gather=host_gather))
        return time.perf_counter() - t0

    # --- lowering hooks (analysis/hlo_audit.py) -----------------------
    def synthetic_device_batch(self) -> Dict:
        """A zero-filled, fully-staged device batch at the compiled
        shapes — the HLO auditor lowers against it (values never run;
        only shapes/dtypes/shardings reach the compiler)."""
        batch: Dict[str, np.ndarray] = {}
        for t in self.input_tensors:
            batch[t.name] = np.zeros(t.shape, dtype=np.dtype(t.dtype))
        lt = self.label_tensor
        if lt is not None:
            batch["label"] = np.zeros(lt.shape, dtype=np.dtype(lt.dtype))
        return self._device_batch(batch)

    def lowered_train_hlo(self, device_batch: Optional[Dict] = None
                          ) -> str:
        """Post-SPMD-partitioning HLO text of the (K=1) train step —
        the program GSPMD will actually run, with every inserted
        collective visible at its concrete per-device shapes. The HLO
        auditor (analysis/hlo_audit.py FLX511-513) scans this for
        table-scale collectives, missed donation, and cost-model drift;
        callers may also dump it for offline diffing. Requires
        compile() + init_layers(); host-resident-table models are
        rejected (their table traffic runs on the host, outside the
        lowered program)."""
        if getattr(self, "_host_resident_ops", None):
            raise ValueError(
                "host-resident-table models keep their table traffic on "
                "the host — the lowered device HLO has nothing to audit "
                "for them")
        if self.params is None:
            raise ValueError("call compile() + init_layers() first")
        self._ensure_step_state()
        db = device_batch if device_batch is not None \
            else self.synthetic_device_batch()
        args = (self.params, self.opt_state, self.op_state, self._msums,
                db, self._step_dev)
        return self._train_step.lower(*args).compile().as_text()

    def lowered_eval_hlo(self, device_batch: Optional[Dict] = None
                         ) -> str:
        """Post-SPMD HLO of the eval/serving forward step (see
        lowered_train_hlo); serving-bucket audits lower one batch per
        bucket size."""
        if self.params is None:
            raise ValueError("call compile() + init_layers() first")
        db = device_batch if device_batch is not None \
            else self.synthetic_device_batch()
        db = {k: v for k, v in db.items() if k != "label"}
        args = (self.params, self.op_state, db)
        return self._eval_step.lower(*args).compile().as_text()

    def swap_params(self, params=None, host_params=None, op_state=None):
        """Atomically install new inference state (the hot-reload hook).

        The serving engine calls this under its dispatch lock, BETWEEN
        dispatches: an executable already dispatched keeps computing on
        the old arrays (functional state — nothing is mutated in
        place), so in-flight requests finish on the old weights and the
        next dispatch sees the new ones — never a mix. Tree structures
        must match the compiled model (the cached AOT executables were
        compiled against these shapes/shardings); a mismatch raises
        before anything is replaced."""
        if params is not None:
            old = jax.tree.structure(self.params)
            new = jax.tree.structure(params)
            if old != new:
                raise ValueError(
                    f"swap_params: new params tree {new} does not match "
                    f"the compiled model's {old} — a snapshot from a "
                    f"differently-built model cannot hot-swap")
        self._host_drain()   # land any in-flight training scatter
        self._host_prefetch_invalidate()
        if params is not None:
            self.params = params
        if host_params is not None:
            self.host_params = host_params
        if op_state is not None:
            self.op_state = op_state

    def apply_delta(self, delta: Dict):
        """Incrementally install a delta snapshot (the continual-learning
        hot path; see ``utils/delta.py``).

        ``delta`` is a ``load_delta_file`` payload: ``rows[flat_key] =
        (idx, vals)`` replaces the given flattened-2D stored rows of a
        params/hostparams array, ``full[flat_key]`` replaces whole
        (dense/op-state) arrays, ``step`` becomes the new version. The
        serving engine calls this between dispatches exactly like
        ``swap_params`` — the caller already staged the device-param row
        payloads with ``stage_delta_rows`` OUTSIDE any dispatch lock, so
        the only device work here is the row scatter itself. Device
        params are updated functionally (in-flight executions keep their
        old arrays); host tables are updated in place under
        ``_host_lock`` (between dispatches nothing reads them).

        Everything is validated BEFORE anything is installed: an unknown
        key, an out-of-range row index, or a width mismatch raises with
        the key named and the model untouched — the engine turns that
        into a reject-with-reason and the watcher falls back to a full
        reload."""
        step = int(delta["step"])
        rows = delta.get("rows") or {}
        full = delta.get("full") or {}

        def _leaf(tree, key, what):
            parts = key.split("/")
            node = tree
            for p in parts[1:]:
                if not isinstance(node, dict) or p not in node:
                    raise ValueError(
                        f"delta {what} {key!r} does not exist in this "
                        f"model (differently-built model?)")
                node = node[p]
            return parts[1:], node

        sections = {"params": self.params, "state": self.op_state,
                    "hostparams": self.host_params}
        # ---- validate first, install second ----------------------------
        plan = []
        for key, (idx, vals) in rows.items():
            sec = key.split("/", 1)[0]
            tree = sections.get(sec)
            if tree is None or sec == "state":
                raise ValueError(
                    f"delta row update targets unsupported section "
                    f"{key!r}")
            path, cur = _leaf(tree, key, "row update")
            shape = tuple(np.asarray(cur).shape) if sec == "hostparams" \
                else tuple(cur.shape)
            if len(shape) < 2 or (np.asarray(vals).shape[-1]
                                  != shape[-1]):
                raise ValueError(
                    f"delta rows for {key!r} have width "
                    f"{np.asarray(vals).shape[-1:]} but the stored array "
                    f"is {shape}")
            nrows = int(np.prod(shape[:-1]))
            idx_np = np.asarray(idx)
            if idx_np.size and (int(idx_np.max()) >= nrows
                                or int(idx_np.min()) < 0):
                raise ValueError(
                    f"delta rows for {key!r} index up to "
                    f"{int(idx_np.max())} but the stored array has only "
                    f"{nrows} rows")
            plan.append((sec, key, path, idx, vals))
        for key in full:
            sec = key.split("/", 1)[0]
            tree = sections.get(sec)
            if tree is None:
                raise ValueError(
                    f"delta full update targets unknown section {key!r}")
            _leaf(tree, key, "full update")
        # ---- install ---------------------------------------------------
        self._host_drain()
        self._host_prefetch_invalidate()
        new_params = {op: dict(d) for op, d in self.params.items()}
        new_state = {op: (dict(d) if isinstance(d, dict) else d)
                     for op, d in self.op_state.items()}
        for sec, key, path, idx, vals in plan:
            if sec == "params":
                opname, pname = path[0], path[-1]
                cur = new_params[opname][pname]
                w = cur.shape[-1]
                new2d = jnp.reshape(cur, (-1, w)).at[
                    jnp.asarray(idx)].set(
                        jnp.asarray(vals, dtype=cur.dtype))
                new = jnp.reshape(new2d, cur.shape)
                shard = self._param_sharding.get(opname, {}).get(pname)
                if shard is not None:
                    new = jax.device_put(new, shard)
                new_params[opname][pname] = new
            else:   # hostparams: in-place row writes under the table lock
                opname, pname = path[0], path[-1]
                with self._host_lock:
                    tbl = self.host_params[opname][pname]
                    mi = np.unravel_index(np.asarray(idx),
                                          tbl.shape[:-1])
                    tbl[mi] = np.asarray(vals, dtype=tbl.dtype)
        for key, v in full.items():
            sec = key.split("/", 1)[0]
            parts = key.split("/")
            opname, pname = parts[1], parts[-1]
            if sec == "params":
                shard = self._param_sharding.get(opname, {}).get(pname)
                new_params[opname][pname] = (
                    jax.device_put(v, shard) if shard is not None
                    else jax.device_put(v))
            elif sec == "state":
                new_state[opname][pname] = jax.device_put(v)
            else:
                with self._host_lock:
                    self.host_params[opname][pname] = np.array(v)
        self.params = new_params
        self.op_state = new_state
        self._step = step
        self._step_dev = None
        self._msums = None
        return self

    def _eval_dispatch(self, db: Dict, host_emb=None):
        """Eval through the same AOT executable cache as the train path:
        calling the pjit wrapper re-validates the whole param pytree in
        python on EVERY call, which costs more than a fast model's
        forward itself — the cached `.lower().compile()` executable
        skips that, keyed by the batch signature (alternating shapes
        each compile once), with the usual GSPMD
        recompile-on-sharding-disagree fallback."""
        from collections import OrderedDict
        args = (self.params, self.op_state, db)
        key = self._exec_key(db)
        if host_emb is not None:
            args = args + (host_emb,)
            key = key + ("host_emb",) + self._exec_key(host_emb)
        from ..analysis import sanitizer as _san
        _san.note_jax_dispatch("eval executable")
        execs = getattr(self, "_eval_step_execs", None)
        if execs is None:
            execs = self._eval_step_execs = OrderedDict()
        exec_ = execs.get(key)
        if exec_ is None:
            exec_ = execs[key] = self._cached_compile(
                "eval", key, lambda: self._eval_step.lower(*args))
            # LRU-bound the cache: a serving engine fed many ad-hoc
            # shapes must not leak one compiled executable per shape
            # forever (config.eval_exec_cache, 0/negative = unbounded)
            cap = int(getattr(self.config, "eval_exec_cache", 0) or 0)
            while cap > 0 and len(execs) > cap:
                execs.popitem(last=False)
                self._eval_exec_evictions = getattr(
                    self, "_eval_exec_evictions", 0) + 1
        else:
            execs.move_to_end(key)
        try:
            return exec_(*args)
        except ValueError as e:
            if not _sharding_mismatch(e):
                raise
            exec_ = execs[key] = self._cached_compile(
                "eval", key, lambda: self._eval_step.lower(*args),
                fresh=True)
            return exec_(*args)

    def eval_exec_cache_stats(self) -> Dict[str, int]:
        """Occupancy of the eval-path AOT executable cache plus the
        CUMULATIVE eviction count (across recompiles/reshards) — the
        serving engine surfaces these in ``stats()`` so an executable
        leak or thrash shows up as a number, not an OOM."""
        execs = getattr(self, "_eval_step_execs", None) or {}
        return {"size": len(execs),
                "capacity": int(getattr(self.config, "eval_exec_cache", 0)
                                or 0),
                "evictions": int(getattr(self, "_eval_exec_evictions", 0))}

    def reset_metrics(self):
        """Reference FFModel::reset_metrics (model.cc:934-940)."""
        self.perf.reset()
        self._msums = None

    # --- parity verbs (eager, unfused) --------------------------------
    def forward(self, batch=None):
        if batch is not None:
            self._cur_batch = batch
        if getattr(self, "_cur_batch", None) is None:
            raise ValueError(
                "forward() needs a batch: call forward(batch) once (or use "
                "a DataLoader's next_batch) before parameterless forward()")
        return self.forward_batch(self._cur_batch)

    def zero_gradients(self):
        # gradients are functional values in JAX; nothing to zero
        # (reference model.cc:1146-1149 launches per-op ZERO_INIT tasks)
        pass

    def backward(self, batch=None):
        if batch is not None:
            self._cur_batch = batch
        if getattr(self, "_cur_batch", None) is None:
            raise ValueError("backward() needs a batch: call backward(batch)")
        # fused into train_batch in the perf path; parity verb recomputes
        self._pending_update = self._cur_batch

    def update(self):
        if getattr(self, "_pending_update", None) is not None:
            self.train_batch(self._pending_update)
            self._pending_update = None

    # ------------------------------------------------------------------
    # fit loop (reference keras base_model.py:367-431 / dlrm.cc:166-198)
    # ------------------------------------------------------------------
    def fit(self, inputs: Dict[str, np.ndarray], labels: np.ndarray,
            epochs: Optional[int] = None, batch_size: Optional[int] = None,
            verbose: bool = True,
            callbacks: Optional[List[Callable]] = None,
            checkpoint_dir: Optional[str] = None,
            save_every: Optional[int] = None,
            keep_last: Optional[int] = None,
            resume: bool = True):
        """Train; with `checkpoint_dir` the run is fault-tolerant:

        - rolling atomic snapshots every `save_every` optimizer steps
          (written on a background thread; keep-last-`keep_last` files
          plus a manifest), and a final one when training completes;
        - `resume=True` scans the manifest first and continues from the
          newest VALID snapshot — params, optimizer state, step counter,
          and the (epoch, batch) dataloader position; corrupt/truncated/
          foreign snapshots are skipped, so a run SIGKILLed mid-save
          restarts from the previous good one;
        - under `FFConfig.anomaly_policy == "rollback"`, a non-finite
          step restores the last good snapshot, re-winds, and continues
          (at most `FFConfig.max_rollbacks` times per fit call).

        All three arguments default from FFConfig (`--checkpoint-dir`,
        `--save-every`, `--keep-last`).
        """
        epochs = epochs or self.config.epochs
        bs = batch_size or self.config.batch_size
        checkpoint_dir = checkpoint_dir or (
            getattr(self.config, "checkpoint_dir", "") or None)
        save_every = (save_every if save_every is not None
                      else getattr(self.config, "save_every", 0))
        keep_last = (keep_last if keep_last is not None
                     else getattr(self.config, "keep_last", 3))
        if bs != self.config.batch_size:
            # the per-shape executable cache (train_batch_device) compiles
            # the step at the requested shape; ops whose shapes bake the
            # batch dimension (explicit Reshape targets) reject the trace
            # below with an actionable error. Reference keras fit() takes
            # whatever batch_size it is given (base_model.py:367-431).
            log_model.warning(
                "fit(batch_size=%d) differs from the compile-time batch "
                "%d; compiling the train step at the new shape",
                bs, self.config.batch_size)
        n = len(labels)
        if n < bs:
            raise ValueError(f"dataset has {n} samples < batch size {bs}")
        num_batches = n // bs
        # the remainder (n % bs samples) trains as its OWN smaller batch
        # through the same per-shape cache; if its shape cannot trace or
        # stage, it is dropped with a loud warning (the reference loop
        # silently trains only full batches)
        rem = n - num_batches * bs
        rem_ok = rem > 0
        if self.params is None:
            self.init_layers()

        # --- fused supersteps -------------------------------------------
        # K full batches train as ONE dispatch (lax.scan executable);
        # batches that can't align to a K boundary — the tail of an
        # epoch, a mid-group resume position, the odd-shaped remainder —
        # fall back to exact K=1 steps. K=1 IS the legacy path, bitwise.
        k_super = self.resolve_superstep(bs)
        if k_super > num_batches:
            if getattr(self.config, "superstep", 1) == "auto":
                # auto picked more lookahead than one epoch holds:
                # shrink to the largest power of two that fits
                while k_super > num_batches:
                    k_super //= 2
            else:
                log_model.warning(
                    "superstep K=%d exceeds the %d batches per epoch; "
                    "running per-step (K=1)", k_super, num_batches)
                k_super = 1
        if k_super > 1 and save_every and save_every % k_super != 0:
            raise ValueError(
                f"save_every={save_every} is not a multiple of the "
                f"superstep K={k_super}: snapshots can only land on "
                f"superstep boundaries (the K fused steps commit "
                f"atomically) — pick save_every % K == 0, or "
                f"--superstep 1 for exact per-step checkpointing")

        def _super_slice(b_, k_):
            # [K, batch, ...] stacked host views of K contiguous batches
            # (reshape of a contiguous slice: no copy)
            sl = slice(b_ * bs, (b_ + k_) * bs)
            out = {kk: np.asarray(v)[sl].reshape((k_, bs) + v.shape[1:])
                   for kk, v in inputs.items()}
            out["label"] = np.asarray(labels)[sl].reshape(
                (k_, bs) + labels.shape[1:])
            return out

        # --- fault tolerance: rolling checkpoints + auto-resume ---------
        mgr = None
        start_epoch = start_batch = 0
        self._attach_configured_caches(checkpoint_dir)
        if checkpoint_dir:
            from ..utils.checkpoint import CheckpointManager
            mgr = CheckpointManager(checkpoint_dir, keep_last=keep_last)
            cc = getattr(self, "_compile_cache", None)
            if cc is not None:
                # record the warm-cache location in the manifest so a
                # serving host that mounts only the checkpoint dir can
                # find the executables/plans published next to it
                import os as _os
                mgr.set_manifest_extra(
                    "warm_cache_dir",
                    _os.path.relpath(cc.directory, mgr.directory))
            if resume:
                entry = mgr.restore_latest(self)
                if entry is not None:
                    ls = entry.get("loader_state") or {}
                    start_epoch = int(ls.get("epoch", 0))
                    start_batch = min(int(ls.get("batch", 0)), num_batches)
                    if verbose:
                        print(f"resumed from checkpoint step "
                              f"{entry['step']} (epoch {start_epoch}, "
                              f"batch {start_batch})")
            if start_epoch >= epochs:
                log_model.warning(
                    "checkpoint in %s is already at epoch %d >= epochs=%d; "
                    "nothing to train", checkpoint_dir, start_epoch, epochs)
                return {"elapsed": 0.0, "throughput": 0.0,
                        "num_samples": 0, "rollbacks": 0,
                        "recoveries": 0, "expansions": 0,
                        "metrics": self.perf.report()}
            if (getattr(self, "_anomaly_policy", "none") == "rollback"
                    or getattr(self.config, "elastic", "off") == "resume") \
                    and mgr.latest_valid() is None:
                # rollback/elastic-resume need a target from step one:
                # seed the directory with the initial state
                mgr.save(self, {"epoch": start_epoch, "batch": start_batch})
        elif getattr(self, "_anomaly_policy", "none") == "rollback":
            raise ValueError(
                'anomaly_policy="rollback" needs fit(checkpoint_dir=...) '
                "(or FFConfig.checkpoint_dir) to roll back to")
        elif getattr(self.config, "elastic", "off") == "resume":
            log_model.warning(
                'elastic="resume" without fit(checkpoint_dir=...): a '
                "mesh degradation mid-run will have no snapshot to "
                "resume from and will re-raise")

        # AOT-compile the train step so the timed loop starts warm without
        # consuming a real optimizer step (the reference warms its Legion
        # trace during epoch 0 instead, dlrm.cc:178-185)
        first = {k: v[:bs] for k, v in inputs.items()}
        first["label"] = labels[:bs]
        try:
            staged_first = self._device_batch(first)
        except Exception as e:
            if bs != self.config.batch_size:
                raise ValueError(
                    f"fit(batch_size={bs}) cannot stage against this "
                    f"model's input shardings (compiled for batch "
                    f"{self.config.batch_size}): {e}") from e
            raise
        db, hidx = self._split_host_idx(staged_first)
        self._ensure_step_state()
        wargs = (self.params, self.opt_state, self.op_state, self._msums,
                 db, self._step_dev)
        if hidx is not None:
            wargs = wargs + (self._host_emb_forward(hidx),)
        # cache the warmup executable under the SAME key the hot loop
        # uses, so the first timed step doesn't recompile it
        execs = getattr(self, "_train_step_execs", None)
        if execs is None:
            execs = self._train_step_execs = {}
        wkey = self._exec_key(db)
        if wkey not in execs:
            try:
                execs[wkey] = self._cached_compile(
                    "train", wkey,
                    lambda: self._train_step.lower(*wargs))
            except Exception as e:
                if bs != self.config.batch_size:
                    raise ValueError(
                        f"fit(batch_size={bs}) cannot compile against this "
                        f"graph (an op bakes the compile-time batch "
                        f"{self.config.batch_size} into its shape): {e}"
                    ) from e
                raise
        if k_super > 1:
            # warm the fused-scan executable too, so the timed loop's
            # first superstep doesn't pay its (K-body) compile
            sdb = self._device_superbatch(_super_slice(0, k_super))
            skey = (k_super,) + self._exec_key(sdb)
            sexecs = getattr(self, "_superstep_execs", None)
            if sexecs is None:
                sexecs = self._superstep_execs = {}
            if skey not in sexecs:
                sargs = (self.params, self.opt_state, self.op_state,
                         self._msums, sdb, self._step_dev)
                sexecs[skey] = self._cached_compile(
                    "superstep", skey,
                    lambda: self._superstep_fn.lower(*sargs))

        if self.config.profiling:
            # per-op timing report (reference --profiling cudaEvent prints,
            # linear.cu:499-531)
            from ..utils.profiling import format_profile, profile_ops
            print(format_profile(profile_ops(self)))

        # stage the whole dataset's batches on device once when it fits —
        # the reference's design (the ENTIRE dataset lives in zero-copy
        # memory and the hot loop scatters device-side, dlrm.cc:384-589);
        # otherwise fall back to per-batch host→device staging
        # staging budget = per-chip HBM capacity minus what already lives
        # there (params + optimizer state + op state), with 30% headroom
        # for activations/workspace. Per-chip cost of a staged input is its
        # full size when its sharding is replicated, size/ndev when the
        # sample dim is sharded (matches _build_shardings' input specs).
        # Off-TPU there is no HBM; keep a modest host-RAM cap so fit() on a
        # virtual CPU mesh never device_puts a huge dataset a second time.
        from ..search.cost_model import TPUSpec
        ndev = max(self.mesh.size, 1)

        def _per_chip(arr, sharded: bool) -> float:
            return arr.nbytes / ndev if sharded else float(arr.nbytes)

        in_sharded = {
            t.name: bool(self._out_sharding[t.guid].spec)
            for t in self.input_tensors}
        if jax.default_backend() == "tpu":
            staging_cost = sum(
                _per_chip(v, in_sharded.get(k, False))
                for k, v in inputs.items())
            staging_cost += _per_chip(labels,
                                      bool(self._label_sharding.spec))

            def _resident_per_chip(leaf) -> float:
                # per-chip bytes of a (possibly sharded) device array —
                # .nbytes alone is the GLOBAL logical size
                try:
                    shard = leaf.sharding.shard_shape(leaf.shape)
                    import math as _m
                    return float(_m.prod(shard)) * leaf.dtype.itemsize
                except Exception:
                    return float(getattr(leaf, "nbytes", 0))

            resident = sum(_resident_per_chip(v) for v in jax.tree.leaves(
                (self.params, self.opt_state, self.op_state)))
            budget = max(0.0, 0.7 * TPUSpec.detect().hbm_capacity_bytes
                         - resident)
        else:
            # all virtual CPU "chips" share one host's RAM: cap the TOTAL
            # second copy of the dataset, not the per-chip share
            staging_cost = float(sum(v.nbytes for v in inputs.values())
                                 + labels.nbytes)
            budget = 2e9
        staged = None
        staged_rem = None
        staged_super = None
        # --stage-dataset: "never" forces the streaming/prefetch path
        # (bench_pipeline compares the two); "always" trusts the caller
        # on capacity
        stage_mode = getattr(self.config, "stage_dataset", "auto")
        if stage_mode == "never":
            staging_cost = float("inf")
        elif stage_mode == "always":
            staging_cost = 0.0
        def _stage_all():
            # (re)build the device-resident batches against the model's
            # CURRENT input shardings — called once up front, and again
            # by elastic recovery (arrays staged on the old mesh must
            # not feed an executable compiled on the new one; megabatches
            # are re-staged the same way). With a superstep, aligned full
            # groups stage as [K, bs, ...] megabatches (one put each) and
            # only the unaligned tail stages per-batch.
            nonlocal staged, staged_rem, staged_super, rem_ok
            staged = {}
            staged_super = {} if k_super > 1 else None
            tail0 = 0
            if k_super > 1:
                tail0 = (num_batches // k_super) * k_super
                for g in range(0, tail0, k_super):
                    staged_super[g] = self._device_superbatch(
                        _super_slice(g, k_super))
            for b in range(tail0, num_batches):
                sl = slice(b * bs, (b + 1) * bs)
                batch = {k: v[sl] for k, v in inputs.items()}
                batch["label"] = labels[sl]
                staged[b] = self._device_batch(batch)
            staged_rem = None
            if rem_ok:
                # the remainder already fit the staging budget (the cost
                # counted the whole dataset) — stage it once instead of
                # re-transferring it every epoch
                batch = {k: v[num_batches * bs:n] for k, v in inputs.items()}
                batch["label"] = labels[num_batches * bs:n]
                try:
                    staged_rem = self._device_batch(batch)
                except Exception as e:
                    rem_ok = False
                    log_model.warning(
                        "dropping the remainder batch (%d samples): it "
                        "cannot stage at its own shape (%s)", rem, e)

        if staging_cost <= budget:
            _stage_all()

        from ..utils.profiling import TraceContext
        # --- unified observability (dlrm_flexflow_tpu/obs/) -----------
        # --obs on: process-wide metrics + span tracing + the drift
        # monitor comparing measured step time (and lowered collective
        # bytes, once) against the simulator's predictions — the
        # runtime twin of shardcheck FLX513. Off (default): drift_mon
        # stays None and the loop pays one pointer compare per step.
        from ..obs import configure as _obs_configure
        from ..obs import trace as _obstrace
        drift_mon = None
        if _obs_configure(self.config):
            from ..obs.drift import DriftMonitor
            drift_mon = DriftMonitor.from_model(self, name="fit")
            drift_mon.audit_collectives()
        # bound in-flight async steps: XLA CPU's in-process collectives can
        # starve when many multi-device executions queue up on few host
        # cores (on TPU the device is the bottleneck; a deep pipeline is
        # safe) — same throttle as examples/native/dlrm.py
        throttle = 1 if jax.default_backend() == "cpu" else 32
        from collections import deque
        inflight = deque()
        start = time.time()
        mets = None
        num_samples = 0
        rollbacks = 0
        max_rollbacks = getattr(self.config, "max_rollbacks", 3)
        recoveries = 0
        expansions = 0
        max_recoveries = getattr(self.config, "max_recoveries", 3)
        elastic_mode = getattr(self.config, "elastic", "off")

        def _maybe_save(next_epoch, next_batch):
            # position = the NEXT (epoch, batch) to train; snapshots are
            # written off-thread (the device→host gather is inline)
            if mgr is not None and save_every and \
                    self._step % save_every == 0:
                mgr.save_async(self, {"epoch": next_epoch,
                                      "batch": next_batch})

        # --- streaming prefetch pipeline ------------------------------
        # When the dataset is NOT pre-staged, a background staging thread
        # slices + device_puts (and host-index-splits) up to
        # `prefetch_depth` future batches while the device trains the
        # current one (data/prefetch.py) — the reference's DataLoader
        # tasks staging batch N+1 under batch N's compute. With async
        # host-resident tables, the scatter worker additionally chains
        # the NEXT step's host gather using the staged item's indices.
        # The pipeline drains (and re-stages, deterministically) around
        # rollback and remainder-shape failures.
        depth = max(int(getattr(self.config, "prefetch_depth", 2) or 0), 0)
        use_pipe = staged is None and depth > 0
        pipe = None
        nxt = None          # staged item fetched ahead by the peek hook
        pipe_exc: List[BaseException] = []

        def _host_slice(e, b):
            if b == "rem":
                sl = slice(num_batches * bs, n)
            else:
                sl = slice(b * bs, (b + 1) * bs)
            batch = {k: v[sl] for k, v in inputs.items()}
            batch["label"] = labels[sl]
            return batch

        def _close_pipe():
            nonlocal pipe, nxt
            if pipe is not None:
                pipe.close()
                pipe = None
            nxt = None
            pipe_exc.clear()
            self._host_prefetch_invalidate()

        def _build_pipe(e0, b0_):
            nonlocal pipe
            _close_pipe()
            # one schedule entry per DISPATCH: (epoch, batch, k) — k>1
            # entries stage a whole superstep megabatch in one ring slot
            # (one device_put feeding K fused steps); unaligned batches
            # and the remainder stay k=1. The consumer loop walks batches
            # with the same alignment rule, so the two stay in lockstep.
            sched = []
            for e in range(e0, epochs):
                b = b0_ if e == e0 else 0
                while b < num_batches:
                    if (k_super > 1 and b % k_super == 0
                            and b + k_super <= num_batches):
                        sched.append((e, b, k_super))
                        b += k_super
                    else:
                        sched.append((e, b, 1))
                        b += 1
                if rem_ok:
                    sched.append((e, "rem", 1))
            if not sched:
                return
            from ..data.prefetch import PrefetchPipeline

            def produce(i):
                e, b, kk = sched[i]
                if kk > 1:
                    return self._stage_superstep(_super_slice(b, kk))
                return self._stage_step(_host_slice(e, b))

            pipe = PrefetchPipeline(
                produce, depth=depth, num_items=len(sched), name="fit",
                deadline_s=self._worker_deadline_s() or None)

        hres_async = bool(getattr(self, "_host_resident_list", None)
                          and getattr(self.config, "host_tables_async",
                                      True))

        def _peek_next_host_idx():
            # runs inside the train step at scatter-launch time (the
            # device already executes this step): fetch the NEXT staged
            # item so the async worker can chain its host gather after
            # this step's scatter. A staging error here must not skip
            # this step's scatter — defer it to the next consume.
            nonlocal nxt
            try:
                nxt = pipe.get()
                return nxt.host_idx
            except IndexError:        # end of schedule
                return None
            except BaseException as e:
                pipe_exc.append(e)
                return None

        def _next_staged():
            nonlocal nxt
            if pipe_exc:
                raise pipe_exc.pop()
            if nxt is not None:
                cur, nxt = nxt, None
                return cur
            return pipe.get()

        def _train_streamed():
            m = self.train_batch_staged(
                _next_staged(),
                next_host_idx=_peek_next_host_idx if hres_async else None)
            # same in-flight bound as the pre-staged path: the producer
            # keeps the dispatch queue fed, so the throttle is what
            # keeps XLA-CPU collectives from starving
            inflight.append(m["loss"])
            if len(inflight) > throttle:
                jax.block_until_ready(inflight.popleft())
            return m

        if use_pipe:
            _build_pipe(start_epoch, start_batch)

        import contextlib

        @contextlib.contextmanager
        def _pipe_guard():
            # the staging thread must not outlive fit() on ANY exit path
            # (an AnomalyError under policy "raise" included)
            try:
                yield
            finally:
                _close_pipe()

        with TraceContext(self.config.profile_dir or None), _pipe_guard():
            epoch, b0 = start_epoch, start_batch
            # resume position for the elastic "inplace" path: the batch
            # about to train, plus whether its optimizer step actually
            # applied before the degradation surfaced
            cur = (start_epoch, start_batch)
            step0 = self._step
            while epoch < epochs:
                if b0 == 0:
                    self.reset_metrics()
                try:
                    b = b0
                    while b < num_batches:
                        # a group of K batches anchored on a K boundary
                        # trains as ONE fused dispatch; everything else
                        # (epoch tail, mid-group resume position) is an
                        # exact K=1 step
                        k = (k_super if (k_super > 1 and b % k_super == 0
                                         and b + k_super <= num_batches)
                             else 1)
                        cur, step0 = (epoch, b), self._step
                        _t_drift = (time.perf_counter()
                                    if drift_mon is not None else 0.0)
                        if k > 1:
                            if staged is not None:
                                mets = self.train_superstep_device(
                                    staged_super[b])
                                inflight.append(mets["loss"])
                                if len(inflight) > throttle:
                                    jax.block_until_ready(
                                        inflight.popleft())
                            elif pipe is not None:
                                mets = _train_streamed()
                            else:
                                mets = self.train_superstep_device(
                                    self._device_superbatch(
                                        _super_slice(b, k)))
                        elif staged is not None:
                            db_b = staged.get(b)
                            if db_b is None:
                                # a resume position inside a megabatch-
                                # staged group: stage this one batch on
                                # the fly (one-off until re-aligned)
                                sl = slice(b * bs, (b + 1) * bs)
                                batch = {kk: v[sl]
                                         for kk, v in inputs.items()}
                                batch["label"] = labels[sl]
                                db_b = self._device_batch(batch)
                            mets = self.train_batch_device(db_b)
                            # bound the pipeline without draining it: block
                            # on the step issued `throttle` iterations AGO
                            inflight.append(mets["loss"])
                            if len(inflight) > throttle:
                                jax.block_until_ready(inflight.popleft())
                        elif pipe is not None:
                            mets = _train_streamed()
                        else:
                            sl = slice(b * bs, (b + 1) * bs)
                            batch = {kk: v[sl] for kk, v in inputs.items()}
                            batch["label"] = labels[sl]
                            mets = self.train_batch(batch)
                        num_samples += bs * k
                        if drift_mon is not None:
                            # per-step wall clock the dispatch loop
                            # observed (async pipelining amortized by
                            # the inflight throttle); a superstep
                            # spreads its window over its K steps
                            drift_mon.observe_step(
                                (time.perf_counter() - _t_drift) / k)
                        _maybe_save(epoch, b + k)
                        b += k
                    if rem_ok:
                        # degradation during the remainder resumes at the
                        # next epoch (the odd-shaped batch is not worth a
                        # dedicated resume position; "resume" mode re-
                        # winds exactly via the snapshot regardless)
                        cur, step0 = (epoch + 1, 0), None
                        try:
                            if staged_rem is not None:
                                mets = self.train_batch_device(staged_rem)
                            elif pipe is not None:
                                mets = _train_streamed()
                            else:
                                sl = slice(num_batches * bs, n)
                                batch = {k: v[sl]
                                         for k, v in inputs.items()}
                                batch["label"] = labels[sl]
                                mets = self.train_batch(batch)
                            num_samples += rem
                            _maybe_save(epoch + 1, 0)
                        except AnomalyError:
                            raise   # recovery, not a shape problem
                        except Exception as e:
                            rem_ok = False
                            log_model.warning(
                                "dropping the remainder batch (%d "
                                "samples): it cannot train at its own "
                                "shape (%s) — pad the dataset or pick a "
                                "batch size dividing %d", rem, e, n)
                            if use_pipe:
                                # the ring may hold later rem items (and
                                # a dead producer, if staging raised) —
                                # re-stage the rest without them
                                _build_pipe(epoch + 1, 0)
                except AnomalyError as exc:
                    if (getattr(self, "_anomaly_policy", "none")
                            != "rollback" or mgr is None
                            or rollbacks >= max_rollbacks):
                        raise
                    rollbacks += 1
                    inflight.clear()
                    mgr.wait()
                    entry = mgr.restore_latest(self)
                    if entry is None:
                        raise
                    ls = entry.get("loader_state") or {}
                    epoch = int(ls.get("epoch", 0))
                    b0 = min(int(ls.get("batch", 0)), num_batches)
                    log_model.warning(
                        "anomaly at step %d (%s); rolled back to step %d "
                        "(epoch %d, batch %d) — recovery %d/%d",
                        exc.step, exc, entry["step"], epoch, b0,
                        rollbacks, max_rollbacks)
                    if use_pipe:
                        # drop staged-ahead batches and re-stage from the
                        # rewound position (deterministic, so exact)
                        _build_pipe(epoch, b0)
                    continue
                except (MeshDegraded, WorkerStalled,
                        MeshReturned) as exc:
                    grow = isinstance(exc, MeshReturned)
                    if elastic_mode == "off" or (
                            recoveries if not grow else
                            expansions) >= max_recoveries:
                        raise
                    if grow:
                        expansions += 1
                    else:
                        recoveries += 1
                    inflight.clear()
                    _close_pipe()
                    if mgr is not None:
                        try:
                            mgr.wait()   # land/flush the in-flight save
                        except Exception as save_exc:
                            log_model.warning(
                                "background checkpoint save failed "
                                "during elastic recovery (%s); older "
                                "snapshots remain usable", save_exc)
                    from ..parallel.elastic import expand, recover
                    if grow:
                        # scale-UP: capacity came back — regrow the mesh
                        # (the inverse of the shrink below; resume
                        # position logic is shared)
                        report = expand(
                            self, returned=getattr(exc, "returned", []),
                            mode=elastic_mode, manager=mgr)
                    else:
                        report = recover(
                            self, lost=getattr(exc, "lost", []),
                            mode=elastic_mode, manager=mgr)
                    if elastic_mode == "resume":
                        ls = (report.entry or {}).get("loader_state") or {}
                        epoch = int(ls.get("epoch", 0))
                        b0 = min(int(ls.get("batch", 0)), num_batches)
                    else:
                        # inplace: continue at the batch about to train;
                        # skip however many optimizer steps actually
                        # applied before the stall surfaced (post-step
                        # drain) — a fused superstep commits its K steps
                        # atomically, so this is 0, 1, or K batches
                        e_, b_ = cur
                        if step0 is not None and self._step > step0:
                            b_ += self._step - step0
                        if b_ >= num_batches:
                            e_, b_ = e_ + 1, 0
                        epoch, b0 = e_, b_
                    log_model.warning(
                        "%s (%s); elastic %s %d/%d (%s) onto %d "
                        "device(s) — resuming at epoch %d, batch %d",
                        "mesh growth" if grow else "mesh degradation",
                        exc, "expansion" if grow else "recovery",
                        expansions if grow else recoveries,
                        max_recoveries, elastic_mode, report.surviving,
                        epoch, b0)
                    if staged is not None:
                        # re-stage the dataset against the NEW mesh's
                        # input shardings (old-mesh arrays must not feed
                        # the recompiled executable)
                        _stage_all()
                    if use_pipe:
                        _build_pipe(epoch, b0)
                    continue
                if verbose and mets is not None:
                    # host sync happens here only (metrics are async)
                    print(f"epoch {epoch}: loss={float(mets['loss']):.6f} "
                          + self.perf.summary_line())
                if callbacks:
                    for cb in callbacks:
                        cb(self, epoch, self.perf.report())
                epoch += 1
                b0 = 0
            if mets is not None:
                # dependent readback = true completion (block_until_ready
                # does not wait on some experimental PJRT backends)
                float(mets["loss"])
        self._host_drain()   # land the last async host scatter, if any
        if mgr is not None:
            mgr.wait()        # surface any background-save error
            mgr.save(self, {"epoch": epochs, "batch": 0})  # final snapshot
        elapsed = time.time() - start
        throughput = num_samples / elapsed if elapsed > 0 else float("inf")
        if verbose:
            # same report format intent as reference dlrm.cc:197-198
            print(f"ELAPSED TIME = {elapsed:.4f}s, "
                  f"THROUGHPUT = {throughput:.2f} samples/s")
        out = {"elapsed": elapsed, "throughput": throughput,
               "num_samples": num_samples, "rollbacks": rollbacks,
               "recoveries": recoveries, "expansions": expansions,
               "metrics": self.perf.report()}
        if drift_mon is not None:
            out["drift"] = drift_mon.report()
            _obstrace.export_to_dir()   # no-op without --obs-trace-dir
        return out

    # ------------------------------------------------------------------
    # skew statistics (utils/histogram.py)
    # ------------------------------------------------------------------
    def attach_id_histograms(self, sketches) -> None:
        """Attach per-op id-frequency sketches ({op name ->
        IdFrequencySketch}, e.g. loaded from a published
        ``id_histogram.npz``) so the strategy search can price the
        skew-aware exchanges (dedup-before-exchange, hot/cold hybrid —
        ops/embedding.expected_routed_lookups). Without an attached
        histogram the cost model assumes uniform ids, under which
        neither mode looks attractive."""
        self._id_histograms = dict(sketches or {})

    # ------------------------------------------------------------------
    # streaming fit: the continual train->serve loop (utils/delta.py)
    # ------------------------------------------------------------------
    def fit_stream(self, source, steps: Optional[int] = None,
                   publisher=None, publish_every: Optional[int] = None,
                   verbose: bool = True,
                   callbacks: Optional[List[Callable]] = None,
                   resume: bool = False):
        """Train indefinitely off a streaming source, publishing delta
        snapshots for the serving fleet.

        ``source`` is a callable ``source(i) -> batch`` returning the
        i-th host batch as a feature dict INCLUDING ``"label"``
        (:class:`~..data.stream.ArrayStream` wraps in-memory arrays;
        any deterministic callable works). Returning ``None`` or
        raising ``StopIteration``/``IndexError`` ends the stream;
        ``steps`` bounds it explicitly (None = until the source ends).

        Batches ride the SAME depth-K prefetch ring as ``fit()`` — the
        staging thread slices + device_puts batch N+1 while the device
        trains batch N — and every batch is shown to the publisher's
        :class:`~..utils.delta.TouchedRowTracker` BEFORE staging, so at
        publish time the per-table touched-row candidates cover every
        trained step. Every ``publish_every`` optimizer steps the
        publisher emits a delta snapshot (or a full checkpoint when the
        chain compacts), inline on the training thread — the gather
        must see a quiesced step anyway.

        ``resume=True`` restores the newest valid full checkpoint from
        the publisher's directory first and continues the stream at the
        recorded position (``loader_state["stream_step"]``). The
        restarted publisher always re-anchors on a fresh full base —
        a dead trainer's delta chain is unextendable by design.

        Anomaly policy ``rollback`` is not supported here (there is no
        epoch to re-wind); use ``skip_step`` or ``raise``.
        """
        if getattr(self, "_anomaly_policy", "none") == "rollback":
            raise ValueError(
                'anomaly_policy="rollback" is not supported by '
                "fit_stream (no epoch position to re-wind); use "
                '"skip_step" or "raise"')
        if publish_every is None:
            publish_every = int(getattr(self.config, "publish_every", 0))
        if publisher is not None and publish_every < 1:
            raise ValueError(
                "fit_stream(publisher=...) needs publish_every >= 1 "
                "(--publish-every N)")
        if self.params is None:
            self.init_layers()
        start = 0
        if resume and publisher is not None:
            entry = publisher.mgr.restore_latest(self)
            if entry is not None:
                start = int((entry.get("loader_state") or {})
                            .get("stream_step", 0))
                if verbose:
                    print(f"resumed stream from checkpoint step "
                          f"{entry['step']} (stream position {start})")

        from ..data.prefetch import PrefetchPipeline

        def produce(i):
            try:
                batch = source(start + i)
            except (StopIteration, IndexError):
                raise IndexError("stream exhausted") from None
            if batch is None:
                raise IndexError("stream exhausted")
            if publisher is not None:
                publisher.observe_batch(batch)
            return self._stage_step(batch)

        # --obs on: drift monitor + trace export, same wiring as fit()
        from ..obs import configure as _obs_configure
        from ..obs import trace as _obstrace
        drift_mon = None
        if _obs_configure(self.config):
            from ..obs.drift import DriftMonitor
            drift_mon = DriftMonitor.from_model(self, name="fit_stream")
            drift_mon.audit_collectives()

        depth = max(int(getattr(self.config, "prefetch_depth", 2) or 0),
                    1)
        pipe = PrefetchPipeline(
            produce, depth=depth, num_items=steps, name="fit_stream",
            deadline_s=self._worker_deadline_s() or None)
        throttle = 1 if jax.default_backend() == "cpu" else 32
        from collections import deque as _deque
        inflight = _deque()
        trained = 0
        publishes = 0
        mets = None
        t0 = time.time()
        try:
            while steps is None or trained < steps:
                try:
                    staged = pipe.get()
                except IndexError:
                    break
                _t_drift = (time.perf_counter()
                            if drift_mon is not None else 0.0)
                mets = self.train_batch_staged(staged)
                inflight.append(mets["loss"])
                if len(inflight) > throttle:
                    jax.block_until_ready(inflight.popleft())
                if drift_mon is not None:
                    drift_mon.observe_step(
                        time.perf_counter() - _t_drift)
                trained += 1
                if (publisher is not None and publish_every
                        and trained % publish_every == 0):
                    publisher.publish(
                        {"stream_step": start + trained})
                    publishes += 1
                if callbacks and mets is not None:
                    for cb in callbacks:
                        cb(self, trained, mets)
        finally:
            pipe.close()
        self._host_drain()
        if publisher is not None and trained and (
                not publish_every or trained % publish_every):
            # final partial interval: the fleet should not miss the tail
            publisher.publish({"stream_step": start + trained})
            publishes += 1
        elapsed = time.time() - t0
        bs = int(self.config.batch_size)
        if verbose and mets is not None:
            print(f"fit_stream: {trained} steps, "
                  f"loss={float(mets['loss']):.6f}, "
                  f"{trained * bs / max(elapsed, 1e-9):.2f} samples/s, "
                  f"{publishes} publish(es)")
        out = {"steps": trained, "elapsed": elapsed,
               "throughput": trained * bs / max(elapsed, 1e-9),
               "publishes": publishes,
               "publisher": (publisher.stats()
                             if publisher is not None else None)}
        if drift_mon is not None:
            out["drift"] = drift_mon.report()
            _obstrace.export_to_dir()   # no-op without --obs-trace-dir
        return out
