"""Profiling hooks.

Parity with the reference's two profiling layers (SURVEY.md §5.1):
- per-op timing under `--profiling` (reference FFConfig::profiling →
  cudaEvent timing + prints inside fwd/bwd tasks, linear.cu:499-531,
  embedding.cu:257-262): here each op's compiled XLA subgraph is timed on
  the real device (CostModel.measure_op — the same machinery the strategy
  search calibrates with) and reported as a table, plus a roofline estimate
  so kernel-vs-model gaps are visible.
- whole-run tracing (reference Legion Prof via -lg:prof): here
  `jax.profiler.trace(dir)` captures an xprof/TensorBoard trace of the
  jitted train step — set FFConfig.profile_dir (CLI `--profile-dir`)
  before calling fit().

Per-iteration trace *replay* (reference begin_trace/end_trace(111),
dlrm.cc:179-185) needs no hook: jit compile-once/execute-many subsumes it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


def profile_ops(model, measure: bool = True) -> List[Dict]:
    """Per-op profile of `model` (must be compiled): measured fwd time of
    each op's compiled subgraph at its strategy's shard shape, plus the
    roofline estimate and FLOPs. Returns a list of row dicts, heaviest
    first."""
    from ..core.op import InputOp
    from ..search.cost_model import CostModel

    cm = CostModel(compute_dtype=model.compute_dtype, measure=measure)
    rows = []
    for op in model.ops:
        if isinstance(op, InputOp):
            continue
        pc = model._op_pc.get(op.name) if hasattr(model, "_op_pc") else None
        if pc is None:
            continue
        est = cm.op_compute_time(op, pc)
        meas = cm.measure_op(op, pc) if measure else None
        batch = op.outputs[0].shape[0] if op.outputs[0].num_dims else 1
        rows.append({
            "op": op.name,
            "type": type(op).__name__,
            "degrees": tuple(pc.degrees),
            "flops": op.flops_per_sample() * batch / max(pc.num_parts, 1),
            "roofline_ms": est * 1e3,
            "measured_ms": None if meas is None else meas * 1e3,
        })
    rows.sort(key=lambda r: -(r["measured_ms"] or r["roofline_ms"]))
    return rows


def format_profile(rows: List[Dict]) -> str:
    head = (f"{'op':<28}{'type':<14}{'degrees':<12}"
            f"{'measured_ms':>12}{'roofline_ms':>13}{'GFLOP':>9}")
    lines = [head, "-" * len(head)]
    for r in rows:
        meas = ("-" if r["measured_ms"] is None
                else f"{r['measured_ms']:.4f}")
        lines.append(
            f"{r['op']:<28}{r['type']:<14}{str(r['degrees']):<12}"
            f"{meas:>12}{r['roofline_ms']:>13.4f}"
            f"{r['flops'] / 1e9:>9.3f}")
    return "\n".join(lines)


def superstep_annotation(step: int, num_steps: int = 1,
                         enabled: bool = True):
    """Wrap one (super)step dispatch in a `jax.profiler.
    StepTraceAnnotation` so `--profile-dir` traces show superstep
    boundaries and per-K timing instead of one undifferentiated blob:
    xprof groups device work under step markers, and the `superstep`
    metadata key carries K so a trace reader can divide a fused span
    into per-trained-step time.

    `enabled=False` returns a no-op context — the hot loop must not pay
    even a TraceMe when no trace is being captured (this PR exists to
    delete per-step host overhead)."""
    if not enabled:
        import contextlib
        return contextlib.nullcontext()
    import jax
    return jax.profiler.StepTraceAnnotation(
        "ff_superstep", step_num=int(step), superstep=int(num_steps))


class TraceContext:
    """jax.profiler.trace wrapper that no-ops when dir is empty."""

    def __init__(self, profile_dir: Optional[str]):
        self.profile_dir = profile_dir
        self._cm = None

    def __enter__(self):
        if self.profile_dir:
            import jax
            self._cm = jax.profiler.trace(self.profile_dir)
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm is not None:
            return self._cm.__exit__(*exc)
        return False
