"""Crash-safe delta publication for the continual train→serve loop.

Production recsys retrains forever, and a full-checkpoint publish makes
the serving fleet's freshness (train-step → servable) checkpoint-sized.
The bulk of a DLRM snapshot is embedding rows, and one publish interval
touches only the rows its batches gathered — so the trainer publishes
**delta snapshots**: the touched table rows plus the (small) dense
params, chained off a rolling full checkpoint. The serving
:class:`~..serve.watcher.SnapshotWatcher` applies deltas incrementally
via ``FFModel.apply_delta`` instead of a full-param reload.

Crash-consistency discipline (the CheckFreq-style rules):

- every delta file is written **atomically** (same temp + fsync +
  ``os.replace`` as checkpoints) — a trainer SIGKILLed mid-publish never
  leaves a torn file at a published path;
- the chain lives in the SAME ``manifest.json`` the rolling checkpoints
  use, under a separate ``"deltas"`` list, each entry carrying the base
  snapshot's identity (step + CRC-32), its own CRC-32, the previous
  chain step, and per-array touched-row counts — a watcher can validate
  the whole chain read-only, and ANY inconsistency (gap, torn file,
  replaced base, foreign fingerprint) is detectable before a single row
  is applied;
- the file is written BEFORE its manifest entry: a crash between the
  two leaves an unlisted (harmless) file, never a listed-but-missing
  one;
- when the accumulated chain outgrows ``compact_frac`` of its base (or
  ``max_chain`` links), the next publish is a **compaction**: a fresh
  full checkpoint becomes the new base and the old chain is retired.

Touched-row tracking: the streaming ``fit_stream`` loop shows every
batch to :class:`TouchedRowTracker` before staging it; embedding ops map
the lookup indices to stored-kernel rows (``delta_touched_rows``). The
publisher diffs only those candidate rows against the last published
state — and falls back to a full-array row diff whenever candidates are
unavailable or provably incomplete (a dense-update table, a batch it
never saw), so the delta is ALWAYS exact; tracking is an optimization,
never a correctness dependency.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import faults
from ..obs import metrics as obsm
from ..obs import trace as obstrace
from .checkpoint import (CheckpointManager, _file_crc32, _model_flat,
                         _write_npz_atomic, config_fingerprint, mesh_meta)
from .logging import get_logger

log_delta = get_logger("delta")

# arrays below this element count are cheaper to ship whole than to
# row-diff + index; only params/hostparams arrays at or above it (and
# with >= 2 dims) get the touched-rows treatment
ROW_DELTA_MIN_ELEMS = 16384

_SERVING_SECTIONS = ("params", "state", "hostparams")


class ChainError(ValueError):
    """A delta chain failed validation (gap, torn file, replaced or
    missing base, foreign fingerprint). The watcher treats this as
    reject-with-reason and degrades to a full-param reload."""


def serving_flat(model) -> Dict[str, np.ndarray]:
    """The serving-relevant slice of a model's flattened state: params,
    op state, host tables — what ``load_params_for_swap`` reads —
    keyed exactly like the checkpoint npz. Host tables are deep-copied
    (the trainer keeps scattering into them in place)."""
    flat = _model_flat(model, copy_host=True)
    return {k: v for k, v in flat.items()
            if k.split("/", 1)[0] in _SERVING_SECTIONS}


def _row_view(arr: np.ndarray) -> np.ndarray:
    """Stored array -> 2-D (rows, width) view over all-but-last axes."""
    return arr.reshape(-1, arr.shape[-1])


def _row_eligible(arr: np.ndarray, min_elems: int) -> bool:
    return arr.ndim >= 2 and arr.size >= min_elems and arr.shape[-1] > 0


class TouchedRowTracker:
    """Accumulates, per flat state key, the stored-kernel rows the
    training batches since the last publish MAY have updated.

    ``observe(batch)`` runs on the staging thread (cheap numpy); the
    publisher ``snapshot()``\\ s on the training thread. Accumulation is
    CUMULATIVE over the tracker's life: the prefetch ring stages (and
    observes) batches ahead of training, so per-interval bookkeeping
    could never tell which observations were actually trained — a
    cumulative set is always a superset of the rows updated since any
    publish, which is exactly the safe direction for restricting the
    publish-time diff (a candidate that did not change is never
    shipped; a changed row is never missed). Over a long stream the set
    converges on the table's hot working set — still far smaller than
    the table. Keys are only tracked when the op's update is provably
    row-local (sparse device update active, or a host-resident table);
    everything else diffs all rows at publish.
    """

    def __init__(self, model):
        self.model = model
        from ..analysis.sanitizer import make_lock
        self._lock = make_lock("TouchedRowTracker._lock")
        self._merged: Dict[str, np.ndarray] = {}
        self._pending: Dict[str, List[np.ndarray]] = {}
        self._batches = 0
        # (op, input name, flat key, host?) tuples resolved once
        self._tracked = self._resolve_tracked()
        # id-frequency sketches ride the same staging-thread observe():
        # one per embedding op, over its flat lookup-id space — the
        # skew signal the cost model / serving cache warm consume
        # (utils/histogram.py)
        from .histogram import IdFrequencySketch
        self._sketch_ops = []
        self._sketches: Dict[str, "IdFrequencySketch"] = {}
        for op in getattr(model, "ops", []):
            if (op.inputs and hasattr(op, "flat_lookup_ids")
                    and hasattr(op, "_row_shard_geometry")):
                rows, _pack, tables = op._row_shard_geometry()
                self._sketches[op.name] = IdFrequencySketch(rows * tables)
                self._sketch_ops.append((op, op.inputs[0].name))

    def _resolve_tracked(self) -> List[Tuple[Any, str, str, bool]]:
        from ..ops.embedding import _sparse_update_active
        out = []
        hres = getattr(self.model, "_host_resident_ops", set())
        for op in getattr(self.model, "ops", []):
            if not op.inputs or not hasattr(op, "delta_touched_rows"):
                continue
            in_name = op.inputs[0].name
            if op.name in hres:
                # host updates are always touched-rows-only
                out.append((op, in_name,
                            f"hostparams/{op.name}/kernel", True))
            elif _sparse_update_active(op):
                out.append((op, in_name,
                            f"params/{op.name}/kernel", False))
            # dense-update device tables: every row may change
            # (e.g. dense Adam moments) — leave untracked, diff-all
        return out

    def observe(self, batch: Dict[str, np.ndarray]) -> None:
        """Record one (about to be trained) host batch's candidates."""
        adds = []
        for op, in_name, key, host in self._tracked:
            idx = batch.get(in_name)
            if idx is None:
                continue
            rows = (op.host_delta_touched_rows(idx) if host
                    else op.delta_touched_rows(idx))
            adds.append((key, rows))
        flats = [(op.name, op.flat_lookup_ids(batch[in_name]))
                 for op, in_name in self._sketch_ops
                 if batch.get(in_name) is not None]
        with self._lock:
            self._batches += 1
            for key, rows in adds:
                self._pending.setdefault(key, []).append(rows)
            for name, ids in flats:
                self._sketches[name].observe(ids)

    def id_histograms(self) -> Dict[str, object]:
        """The per-op id-frequency sketches observed so far (live
        references — callers persisting them should do so under a
        quiesced stream, which publish-time is)."""
        with self._lock:
            return dict(self._sketches)

    def snapshot(self) -> Tuple[Dict[str, np.ndarray], int]:
        """Merge pending observations and return (a copy of) the
        cumulative candidate sets plus the total batches observed.
        Nothing is cleared — a failed publish needs the same candidates
        again, and the next publish's interval is covered regardless."""
        with self._lock:
            pending, self._pending = self._pending, {}
            batches = self._batches
        for k, v in pending.items():
            prev = self._merged.get(k)
            parts = ([prev] if prev is not None else []) + v
            self._merged[k] = np.unique(np.concatenate(parts))
        return dict(self._merged), batches


def _diff_flat(prev: Dict[str, np.ndarray], cur: Dict[str, np.ndarray],
               candidates: Optional[Dict[str, np.ndarray]],
               min_elems: int):
    """Exact diff of two serving_flat states.

    Returns (rows, full, counts): ``rows[key] = (idx, vals)`` for
    row-eligible arrays (idx into the flattened-2D stored layout),
    ``full[key]`` for everything else that changed, ``counts`` for the
    manifest. Restricting to ``candidates[key]`` is only an optimization
    — the equality compare is still performed on the candidate rows, so
    a candidate that did NOT change is never shipped."""
    rows: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    full: Dict[str, np.ndarray] = {}
    counts: Dict[str, int] = {}
    for key, cv in cur.items():
        pv = prev.get(key)
        if pv is None or pv.shape != cv.shape or pv.dtype != cv.dtype:
            full[key] = cv           # new/reshaped array: ship whole
            continue
        if _row_eligible(cv, min_elems):
            p2, c2 = _row_view(pv), _row_view(cv)
            cand = candidates.get(key) if candidates else None
            if cand is not None:
                cand = cand[(cand >= 0) & (cand < c2.shape[0])]
                sub = np.any(p2[cand] != c2[cand], axis=1)
                idx = cand[sub]
            else:
                idx = np.flatnonzero(np.any(p2 != c2, axis=1))
            if idx.size:
                rows[key] = (idx.astype(np.int64),
                             np.ascontiguousarray(c2[idx]))
                counts[key] = int(idx.size)
        elif not np.array_equal(pv, cv):
            full[key] = cv
    return rows, full, counts


# ---------------------------------------------------------------------
# delta file round trip
# ---------------------------------------------------------------------
def write_delta_file(path: str, step: int, prev_step: int, base_step: int,
                     rows, full,
                     quant: Optional[Dict[str, str]] = None) -> int:
    """Atomically write one delta npz; returns its CRC-32. The
    publish-abort injection fires inside the atomic writer (before the
    rename — exactly the mid-publish crash window), the torn-delta
    injection truncates AFTER the rename (bit rot on a published
    file).

    ``quant`` maps flat keys to a quantized storage dtype (quant/): row
    payloads for those keys ship as codes + per-row fp32 scales
    (``rows/`` at 1 B/elem, ``scl/`` beside it, ``qdt/`` the dtype,
    ``sbd/`` the publish-time max-scale bound the loader validates
    against) — the ~4x delta-publish-bytes lever. Unlisted keys keep the
    legacy fp32 layout, so unquantized models write byte-identical
    files."""
    from ..quant.codec import encode_q, quantize_rows_np
    flat: Dict[str, np.ndarray] = {
        "meta/step": np.asarray(step, np.int64),
        "meta/prev_step": np.asarray(prev_step, np.int64),
        "meta/base_step": np.asarray(base_step, np.int64),
    }
    for key, (idx, vals) in rows.items():
        flat[f"idx/{key}"] = idx
        dt = (quant or {}).get(key)
        if dt:
            q, scales = quantize_rows_np(vals, dt)
            flat[f"rows/{key}"] = encode_q(q, dt)
            flat[f"scl/{key}"] = scales
            flat[f"qdt/{key}"] = np.asarray(dt)
            flat[f"sbd/{key}"] = np.asarray(
                float(scales.max()) if scales.size else 0.0, np.float32)
        else:
            flat[f"rows/{key}"] = vals
    for key, v in full.items():
        flat[f"full/{key}"] = v
    faults.maybe_abort_publish(path)
    crc = _write_npz_atomic(path, flat)
    if faults.maybe_torn_delta(path):
        pass                      # published file torn post-rename
    return crc


def load_delta_file(path: str) -> Dict[str, Any]:
    """Read a delta npz into an apply_delta payload (host arrays; the
    caller device_puts the row payloads outside any dispatch lock).

    Quantized row payloads are VALIDATED (scales finite, non-negative,
    within the publish-time bound — a garbage scale is a
    reject-with-reason :class:`ChainError`, and the watcher degrades to
    the newest valid full snapshot instead of serving amplified rows)
    then dequantized into ``rows`` for the fp32 appliers; the raw
    codes + scales stay available under ``qrows`` for consumers that
    store quantized (the shard tier, bit-exact round-trip tests)."""
    from ..quant.codec import (decode_q, dequantize_rows_np,
                               validate_scales)
    data = np.load(path)
    rows: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
    qrows: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray, str]] = {}
    full: Dict[str, np.ndarray] = {}
    for k in data.files:
        if k.startswith("idx/"):
            key = k[len("idx/"):]
            vals = data[f"rows/{key}"]
            if f"scl/{key}" in data.files:
                dt = str(data[f"qdt/{key}"])
                scales = faults.maybe_corrupt_quant_scale(
                    key, data[f"scl/{key}"])
                bound = float(data[f"sbd/{key}"]) \
                    if f"sbd/{key}" in data.files else None
                try:
                    validate_scales(key, scales, bound)
                except ValueError as e:
                    raise ChainError(str(e)) from None
                q = decode_q(vals, dt)
                qrows[key] = (data[k], q, scales, dt)
                vals = dequantize_rows_np(q, scales, dt)
            rows[key] = (data[k], vals)
        elif k.startswith("full/"):
            full[k[len("full/"):]] = data[k]
    out = {"step": int(data["meta/step"]),
           "prev_step": int(data["meta/prev_step"]),
           "base_step": int(data["meta/base_step"]),
           "rows": rows, "full": full}
    if qrows:
        out["qrows"] = qrows
    return out


def stage_delta_rows(model, payload: Dict[str, Any]) -> Dict[str, Any]:
    """Device_put a loaded delta's device-param row payloads — the slow
    H2D half of an incremental reload, run on the watcher thread OUTSIDE
    any dispatch lock (host-table rows stay numpy; they are applied on
    the host). Returns a new payload; the input is not modified."""
    import jax
    staged = dict(payload)
    staged["rows"] = dict(payload["rows"])
    for key, (idx, vals) in payload["rows"].items():
        if key.startswith("params/"):
            staged["rows"][key] = (jax.device_put(idx),
                                   jax.device_put(vals))
    return staged


# ---------------------------------------------------------------------
# per-shard routing (the serving shard tier, serve/shardtier.py)
# ---------------------------------------------------------------------
#
# A row-sharded serving tier splits every host table's flat row space
# over N lookup shards; a delta publish must then touch ONLY the shards
# that own its rows, and each shard must be able to validate exactly its
# own slice. ``split_host_rows_by_shard`` is the router: it cuts a
# loaded delta payload's ``hostparams/`` updates along the shard ranges
# (the same owner math as ``parallel.alltoall.row_owners``) and stamps
# each slice with a CRC the owning shard recomputes before applying —
# the per-shard half of the chain discipline above. Slices for shards an
# interval never touched are ``None`` (the publish costs them a version
# bump, no row work).


def shard_slice_crc(sub: Dict[str, Any]) -> int:
    """Deterministic CRC-32 over one shard's delta slice (sorted keys,
    index bytes, row bytes). Computed at split time and recomputed by
    the shard at apply time: corruption anywhere between the two is a
    reject-with-reason, never silently-wrong rows."""
    import zlib
    crc = 0
    for key in sorted(sub.get("rows", {})):
        idx, vals = sub["rows"][key]
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(idx, np.int64), crc)
        crc = zlib.crc32(np.ascontiguousarray(vals), crc)
    for key in sorted(sub.get("full", {})):
        crc = zlib.crc32(key.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(sub["full"][key]), crc)
    return crc


def shard_chain_crc(prev_crc: int, step: int, slice_crc: int) -> int:
    """One link of a shard's publish chain: CRC over (previous link,
    step, this slice's CRC). Two shards that applied the same publishes
    in the same order agree on it; a replacement shard booting from the
    warm cache proves lineage by matching it."""
    import zlib
    blob = np.asarray([prev_crc, step, slice_crc], np.int64)
    return zlib.crc32(blob.tobytes())


def split_host_rows_by_shard(payload: Dict[str, Any],
                             ranges_by_op: Dict[str, list],
                             ) -> Dict[int, Optional[Dict[str, Any]]]:
    """Split an ``apply_delta`` payload's host-table updates into
    per-shard slices.

    ``ranges_by_op`` maps op name -> the shard tier's ``[(lo, hi), ...]``
    flat-row ranges (``EmbeddingShardSet.ranges``). Row updates
    (``rows["hostparams/<op>/kernel"]``) are routed by owner; full-array
    host replacements (small tables below the row-delta threshold) are
    sliced along the same ranges. Returns ``{slot: slice | None}`` where
    each non-None slice carries its ``crc`` (:func:`shard_slice_crc`);
    ``None`` means this publish has nothing for that shard. Non-host
    keys are the ranker tier's business and are ignored here."""
    from ..parallel.alltoall import row_owners
    nshards = max((len(r) for r in ranges_by_op.values()), default=0)
    subs: Dict[int, Dict[str, Any]] = {}

    def _sub(slot):
        return subs.setdefault(slot, {"rows": {}, "full": {}})

    for key, (idx, vals) in (payload.get("rows") or {}).items():
        if not key.startswith("hostparams/"):
            continue
        op_name = key.split("/")[1]
        ranges = ranges_by_op.get(op_name)
        if ranges is None:
            continue
        rows_total = ranges[-1][1]
        owners = row_owners(idx, rows_total, len(ranges))
        for slot in np.unique(owners):
            m = owners == slot
            _sub(int(slot))["rows"][key] = (np.asarray(idx)[m],
                                            np.asarray(vals)[m])
    for key, arr in (payload.get("full") or {}).items():
        if not key.startswith("hostparams/"):
            continue
        op_name = key.split("/")[1]
        ranges = ranges_by_op.get(op_name)
        if ranges is None:
            continue
        flat = np.asarray(arr).reshape(-1, arr.shape[-1])
        for slot, (lo, hi) in enumerate(ranges):
            if hi > lo:
                _sub(slot)["full"][key] = flat[lo:hi]
    out: Dict[int, Optional[Dict[str, Any]]] = {}
    for slot in range(nshards):
        sub = subs.get(slot)
        if sub is not None:
            sub["crc"] = shard_slice_crc(sub)
        out[slot] = sub
    return out


# ---------------------------------------------------------------------
# chain validation (shared: publisher sanity + watcher read-only path)
# ---------------------------------------------------------------------
def resolve_chain(manifest: Dict[str, Any], fingerprint: Optional[str],
                  directory: str,
                  check_files: bool = True
                  ) -> Optional[Tuple[Dict[str, Any], List[Dict[str, Any]]]]:
    """Validate the manifest's delta chain newest-tip-first.

    Returns ``(base_entry, ordered_delta_entries)`` for the newest tip,
    or None when no deltas are listed. Raises :class:`ChainError` with
    the reason on ANY inconsistency: a gap in the prev links, a delta or
    base written by a differently-built model, a base snapshot that was
    replaced (CRC identity mismatch) or is missing from the manifest, a
    listed delta file that is missing or fails its CRC-32.
    """
    deltas = manifest.get("deltas") or []
    if not deltas:
        return None
    entries = manifest.get("entries") or []
    tip = max(deltas, key=lambda e: e.get("step", -1))
    base_step = tip.get("base_step")
    chain = sorted((e for e in deltas
                    if e.get("base_step") == base_step),
                   key=lambda e: e.get("step", -1))
    if len(chain) != len(deltas):
        strays = [e.get("file") for e in deltas if e not in chain]
        raise ChainError(
            f"delta chain mixes bases: {strays} do not chain off base "
            f"step {base_step} (stale chain from a previous run)")
    base_entry = next((e for e in entries
                       if e.get("step") == base_step), None)
    if base_entry is None:
        raise ChainError(
            f"chain base snapshot (step {base_step}) is not in the "
            f"manifest (pruned or never published)")
    if (fingerprint is not None and base_entry.get("fingerprint")
            not in (None, fingerprint)):
        raise ChainError(
            f"chain base {base_entry.get('file')} fingerprint "
            f"{base_entry.get('fingerprint')} != this model's "
            f"{fingerprint} (differently-built model)")
    prev = base_step
    for e in chain:
        if e.get("prev_step") != prev:
            raise ChainError(
                f"chain gap: delta {e.get('file')} links to step "
                f"{e.get('prev_step')} but the chain is at step {prev} "
                f"(lost manifest entry / partial publish)")
        if (fingerprint is not None
                and e.get("fingerprint") not in (None, fingerprint)):
            raise ChainError(
                f"delta {e.get('file')} fingerprint "
                f"{e.get('fingerprint')} != this model's {fingerprint}")
        if (e.get("base_crc32") is not None
                and base_entry.get("crc32") is not None
                and e.get("base_crc32") != base_entry.get("crc32")):
            raise ChainError(
                f"delta {e.get('file')} was published against base "
                f"step {base_step} crc {e.get('base_crc32')}, but the "
                f"manifest's base {base_entry.get('file')} has crc "
                f"{base_entry.get('crc32')} (base was replaced)")
        if check_files:
            path = os.path.join(directory, e.get("file", ""))
            if not os.path.isfile(path):
                raise ChainError(
                    f"delta {e.get('file')} is listed in the manifest "
                    f"but missing on disk")
            crc = e.get("crc32")
            if crc is not None and _file_crc32(path) != crc:
                raise ChainError(
                    f"delta {e.get('file')} fails its CRC-32 (torn "
                    f"write / corruption)")
        prev = e.get("step")
    return base_entry, chain


# ---------------------------------------------------------------------
# publisher
# ---------------------------------------------------------------------
class DeltaPublisher:
    """Interleaves delta snapshots with rolling full checkpoints.

    Owns (or adopts) a :class:`CheckpointManager` on `directory`. The
    first publish is always a FULL checkpoint (the chain base — also
    what crash-resume restores from); subsequent publishes are deltas
    until compaction triggers: accumulated delta bytes exceeding
    ``compact_frac`` of the base file, ``max_chain`` links, or an
    explicit ``full_every`` cadence. Construction retires any chain a
    previous (crashed) trainer left behind — its in-memory base state is
    gone, so the chain can never be extended; the watcher degrades to
    the full snapshots until the new chain starts.

    A failed delta publish (IO error, injected abort) is non-fatal: the
    chain is untouched (the file write is atomic and the manifest entry
    never happened), the cumulative tracker still holds the interval's
    candidate rows, and the next interval publishes the union.
    ``stats()`` counts it.
    """

    def __init__(self, model, directory: str, keep_last: int = 3,
                 compact_frac: float = 0.5, full_every: int = 0,
                 max_chain: int = 64,
                 row_delta_min_elems: int = ROW_DELTA_MIN_ELEMS,
                 manager: Optional[CheckpointManager] = None):
        if compact_frac <= 0:
            raise ValueError(
                f"compact_frac must be > 0, got {compact_frac}")
        self.model = model
        self.mgr = manager or CheckpointManager(directory,
                                                keep_last=keep_last)
        self.compact_frac = float(compact_frac)
        self.full_every = int(full_every)
        self.max_chain = int(max_chain)
        self.row_delta_min_elems = int(row_delta_min_elems)
        self.tracker = TouchedRowTracker(model)
        # quantized-storage policies (quant/): flat keys whose row
        # payloads publish as codes + row scales instead of fp32 — the
        # ~4x delta-bytes lever; empty for unquantized models (legacy
        # file layout, byte-identical)
        self._quant_keys: Dict[str, str] = {}
        for op_name, pol in (getattr(model, "quant_policies", dict)()
                             or {}).items():
            if getattr(pol, "is_quantized", False):
                for pname in ("kernel", "hot_kernel"):
                    self._quant_keys[f"params/{op_name}/{pname}"] = \
                        pol.dtype
                    self._quant_keys[f"hostparams/{op_name}/{pname}"] = \
                        pol.dtype
        # candidates are trustworthy only if the tracker saw every batch
        # trained after this point (fit_stream observes at staging time)
        self._track_origin = int(getattr(model, "_step", 0) or 0)
        self._fingerprint = config_fingerprint(model)
        # a previous run's chain is unextendable — retire it
        removed = self.mgr.reset_deltas()
        if removed:
            log_delta.info("retired %d stale delta(s) from a previous "
                           "run in %s", removed, self.mgr.directory)
        self._last_flat: Optional[Dict[str, np.ndarray]] = None
        self._last_step = -1
        self._base_step = -1
        self._base_file = ""
        self._base_crc: Optional[int] = None
        self._base_bytes = 0
        self._chain_bytes = 0
        self._chain_len = 0
        self._deltas_since_full = 0
        self.publishes = 0
        self.full_publishes = 0
        self.delta_publishes = 0
        self.compactions = 0
        self.publish_errors = 0
        self.last_publish_error = ""
        self._untracked_warned = False

    # --- tracking ------------------------------------------------------
    def observe_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """Show the tracker a host batch about to be staged/trained."""
        self.tracker.observe(batch)

    # --- publish decision ----------------------------------------------
    def _compaction_due(self) -> Optional[str]:
        if self._last_flat is None:
            return "no base yet"
        if self.full_every and self._deltas_since_full >= self.full_every:
            return f"full_every={self.full_every} cadence"
        if self._chain_len >= self.max_chain:
            return f"chain length {self._chain_len} >= {self.max_chain}"
        if (self._base_bytes
                and self._chain_bytes > self.compact_frac
                * self._base_bytes):
            return (f"chain bytes {self._chain_bytes} > "
                    f"{self.compact_frac:g} x base {self._base_bytes}")
        return None

    def publish(self, loader_state: Optional[Dict[str, Any]] = None
                ) -> Optional[Dict[str, Any]]:
        """Publish the model's current state: a delta when a live chain
        can absorb it, a full checkpoint otherwise (first publish /
        compaction). Returns the manifest entry, or None when a delta
        publish failed non-fatally (retried next interval)."""
        reason = self._compaction_due()
        if reason is None:
            return self.publish_delta(loader_state)
        if self._last_flat is not None:
            self.compactions += 1
            log_delta.info("compacting delta chain -> full checkpoint "
                           "(%s)", reason)
        return self.publish_full(loader_state)

    # --- full (chain base) publish --------------------------------------
    def publish_full(self, loader_state: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
        """Blocking full checkpoint; becomes the new chain base."""
        with obstrace.span("publish/full", step=int(self.model._step)):
            return self._publish_full(loader_state)

    def _publish_full(self, loader_state: Optional[Dict[str, Any]]
                      ) -> Dict[str, Any]:
        self.mgr.wait()
        model = self.model
        step = int(model._step)
        flat = _model_flat(model, copy_host=True)
        entry = self.mgr._write_snapshot(
            flat, step, self._fingerprint, dict(loader_state or {}),
            mesh_meta(model))
        removed = self.mgr.reset_deltas()
        if removed:
            log_delta.info("retired %d delta(s) of the previous chain",
                           removed)
        self._last_flat = {
            k: v for k, v in flat.items()
            if k.split("/", 1)[0] in _SERVING_SECTIONS}
        self._last_step = step
        self._base_step = step
        self._base_file = entry["file"]
        self._base_crc = entry.get("crc32")
        try:
            self._base_bytes = os.path.getsize(
                os.path.join(self.mgr.directory, entry["file"]))
        except OSError:
            self._base_bytes = 0
        self._chain_bytes = 0
        self._chain_len = 0
        self._deltas_since_full = 0
        self.publishes += 1
        self.full_publishes += 1
        obsm.counter("ff_publishes_total",
                     "snapshot publications by kind",
                     labelnames=("kind",)).inc(kind="full")
        self._publish_histograms()
        return entry

    def _publish_histograms(self) -> None:
        """Persist the observed id-frequency sketches next to the chain
        base (the `id_histogram.npz` sidecar + a manifest pointer):
        the offline strategy search reads them to price the skew-aware
        exchanges, and a fresh serving replica pre-warms its
        EmbeddingCache from the same file (--serve-cache-warm).
        Non-fatal — traffic statistics must never fail a publish."""
        from .histogram import HISTOGRAM_FILE, save_histograms
        sketches = self.tracker.id_histograms()
        observed = {n: s for n, s in sketches.items() if s.total > 0}
        if not observed:
            return
        try:
            path = os.path.join(self.mgr.directory, HISTOGRAM_FILE)
            save_histograms(path, observed)
            self.mgr.set_manifest_extra("id_histogram", {
                "file": HISTOGRAM_FILE,
                "total_lookups": {n: int(s.total)
                                  for n, s in observed.items()}})
        except (IOError, OSError) as e:
            log_delta.warning("id-histogram publish failed (%s); "
                              "will retry at the next full publish", e)

    # --- delta publish ---------------------------------------------------
    def publish_delta(self, loader_state: Optional[Dict[str, Any]] = None
                      ) -> Optional[Dict[str, Any]]:
        model = self.model
        step = int(model._step)
        if self._last_flat is None:
            return self.publish_full(loader_state)
        with obstrace.span("publish/delta", step=step):
            return self._publish_delta(loader_state, step)

    def _publish_delta(self, loader_state: Optional[Dict[str, Any]],
                       step: int) -> Optional[Dict[str, Any]]:
        model = self.model
        if step <= self._last_step:
            return None           # nothing trained since the last publish
        cur = serving_flat(model)
        cand, batches = self.tracker.snapshot()
        # candidates are only trustworthy when the tracker saw at least
        # every batch trained since it started watching (fit_stream
        # observes at staging time, which runs AHEAD of training; ad-hoc
        # train_batch calls in between break the invariant)
        if batches < step - self._track_origin:
            if cand and not self._untracked_warned:
                self._untracked_warned = True
                log_delta.warning(
                    "tracker observed %d batch(es) for %d trained "
                    "step(s); falling back to full-array row diffs",
                    batches, step - self._track_origin)
            cand = None
        try:
            rows, full, counts = _diff_flat(self._last_flat, cur, cand,
                                            self.row_delta_min_elems)
            fname = f"delta-{step:08d}.npz"
            path = os.path.join(self.mgr.directory, fname)
            crc = write_delta_file(path, step, self._last_step,
                                   self._base_step, rows, full,
                                   quant=self._quant_keys)
        except (IOError, OSError) as e:
            # non-fatal: the atomic writer left no torn file and the
            # manifest never saw an entry; the cumulative tracker still
            # holds the candidates, so the NEXT delta covers this
            # interval's rows too.
            self.publish_errors += 1
            self.last_publish_error = str(e)
            log_delta.warning("delta publish at step %d failed (%s); "
                              "will retry next interval", step, e)
            return None
        entry = {
            "file": fname, "kind": "delta", "step": step,
            "prev_step": self._last_step, "base_step": self._base_step,
            "base_file": self._base_file, "base_crc32": self._base_crc,
            "fingerprint": self._fingerprint, "crc32": crc,
            "bytes": os.path.getsize(path),
            "touched_rows": counts, "full_arrays": sorted(full),
            "loader_state": dict(loader_state or {}),
            "time": time.time(),
        }
        if faults.take_delta_gap():
            log_delta.warning("injected delta gap: %s published without "
                              "a manifest entry", fname)
        else:
            self.mgr.append_delta_entry(entry)
        self._last_flat = cur
        self._last_step = step
        self._chain_bytes += entry["bytes"]
        self._chain_len += 1
        self._deltas_since_full += 1
        self.publishes += 1
        self.delta_publishes += 1
        obsm.counter("ff_publishes_total",
                     "snapshot publications by kind",
                     labelnames=("kind",)).inc(kind="delta")
        return entry

    def stats(self) -> Dict[str, Any]:
        return {
            "publishes": self.publishes,
            "full_publishes": self.full_publishes,
            "delta_publishes": self.delta_publishes,
            "compactions": self.compactions,
            "publish_errors": self.publish_errors,
            "last_publish_error": self.last_publish_error,
            "base_step": self._base_step,
            "last_step": self._last_step,
            "chain_len": self._chain_len,
            "chain_bytes": self._chain_bytes,
            "base_bytes": self._base_bytes,
        }
