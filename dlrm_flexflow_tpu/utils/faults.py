"""Deterministic fault injection for resilience testing.

The reference has no fault tolerance to inherit (FlexFlow persists only
strategy files; a preempted Legion run restarts from scratch), so the
recovery paths built on top of it here — atomic rolling checkpoints,
auto-resume, the anomaly sentinel, dataloader read retries — need a way to
be EXERCISED, not just written. This module injects failures at fixed,
reproducible points so every recovery branch runs under test:

- **NaN gradients** (`nan_grad_steps`): poison the batch fed to the jitted
  train step at chosen global step indices, driving the loss/grad-norm
  non-finite through the real autodiff path (not a mocked flag), which the
  anomaly sentinel in ``FFModel.train_batch_device`` must then catch.
- **Checkpoint truncation** (`truncate_checkpoints`): truncate the next N
  checkpoint files right after their atomic rename — simulating torn disk
  writes / bit rot — so ``CheckpointManager.latest_valid`` must fall back
  to the previous snapshot via the manifest checksum.
- **Write aborts** (`abort_writes`): raise mid-save between the temp-file
  write and the ``os.replace``, proving a crashed save never corrupts the
  final path.
- **Write delays** (`write_delay_s`): stretch the window between temp
  write and rename so a kill-mid-checkpoint test can SIGKILL inside it
  deterministically.
- **Transient IO errors** (`io_errors`): raise ``IOError`` from dataloader
  reads for the first N attempts at a named site, exercised against the
  retry/backoff in ``FFBinDataLoader``.
- **Device loss** (`drop_device_steps`): at a chosen global step, report N
  devices as lost so the elastic recovery layer sees a typed
  ``MeshDegraded`` on a healthy CPU mesh (the devices stay physically
  alive — only the runtime's view shrinks, which is exactly what a TPU
  preemption looks like from the surviving hosts).
- **Device return** (`return_device_steps`): the inverse — at a chosen
  global step, report N devices as having come BACK (a preempted host
  re-admitted to the fleet), so the elastic scale-UP path
  (``parallel.elastic.expand`` via a typed ``MeshReturned``) runs on a
  healthy CPU mesh whose runtime view previously shrank.
- **Cache corruption** (`corrupt_cache_entries`): truncate the next N
  persistent warm-cache files (compile/plan cache, ``utils/warmcache``)
  at the moment they are read, so the reject-with-reason →
  fresh-compile degradation path is test-driven, not just written.
- **Stalled workers/collectives** (`stall_s`): sleep a named site once —
  ``"collective"`` freezes the mesh-liveness probe
  (``parallel.distributed.probe_mesh``), ``"scatter"`` wedges the async
  host-table scatter worker — so the deadline watchdogs
  (``utils/watchdog.py``) must detect the stall, not a human.
- **Serving dispatch delay** (`serve_delay_s`): sleep EVERY serving-engine
  batch dispatch (``serve.engine.InferenceEngine``) by a fixed amount —
  NOT consume-once, so a hot-reload test can hold a steady stream of
  slow in-flight batches while the snapshot watcher swaps params
  underneath them (the old-or-new-never-a-mix contract).
- **Corrupt snapshot mid-reload** (`corrupt_reloads`): truncate a
  snapshot file at the moment the serving hot-reload path opens it —
  after the manifest listed it as valid — so the reload must reject the
  torn file (CRC/load failure) and KEEP SERVING the old weights with
  zero failed requests.
- **Replica crash** (`replica_down`): report a serving-fleet replica as
  dead — every dispatch (and probe) against that replica's engine raises
  a typed ``ReplicaDown`` — so the router's circuit breaker must eject
  it, drain its queued futures onto the survivors, and (with a finite
  budget, ``rid:N``) re-admit it once a probe finally succeeds. Unlike
  the other hooks this one is NOT consume-once by default: a crashed
  process stays crashed until the budget (if any) runs out.
- **Slow replica** (per-replica `serve_delay_replica`): stretch ONE
  replica's dispatches while its siblings stay fast, so queue-depth load
  balancing, tail-latency hedging, and heartbeat ejection can be driven
  deterministically.
- **Poisoned snapshot** (`poison_reloads`): scale the params of the next
  snapshot the serving hot-reload path loads — the file is VALID (CRC
  clean, fingerprint matches) but the weights are garbage, the
  bad-deploy case no checksum catches. The canary controller must see
  the score divergence and auto-roll the canary cohort back with zero
  client-visible errors.

Faults are consume-once: each injection decrements its budget, so a
recovery path that retries the same step does not re-fault (rollback would
otherwise loop forever). Activate programmatically::

    from dlrm_flexflow_tpu.utils import faults
    with faults.active_plan(faults.FaultPlan(nan_grad_steps={5})):
        model.fit(...)

or from the environment (read once, at the first hook call — the hooks a
subprocess kill-test needs):

- ``FF_FAULT_NAN_STEPS=3,7``       NaN gradients at global steps 3 and 7
- ``FF_FAULT_TRUNCATE_CKPTS=1``    truncate the next 1 checkpoint file
- ``FF_FAULT_ABORT_WRITES=1``      abort the next 1 checkpoint save
- ``FF_FAULT_WRITE_DELAY=0.5``     sleep 0.5s between temp write and rename
- ``FF_FAULT_IO_ERRORS=ffbin_read:2``  2 transient IOErrors at that site
- ``FF_FAULT_DROP_DEVICE=4:2``     lose 2 devices at global step 4
  (``=4`` alone loses 1 device at step 4)
- ``FF_FAULT_RETURN_DEVICE=6:2``   2 lost devices come back at global
  step 6 (``=6`` alone returns 1 device at step 6)
- ``FF_FAULT_CACHE_CORRUPT=1``     truncate the next 1 warm-cache entry
  file (compile/plan cache) as it is read
- ``FF_FAULT_STALL_COLLECTIVE=3``  stall the next collective probe 3s
- ``FF_FAULT_SERVE_DELAY=0.05``    sleep 50 ms inside EVERY serving batch
  dispatch (not consume-once); ``1:0.2`` delays only replica 1, and the
  forms combine: ``0.05,1:0.2`` is 50 ms everywhere but 200 ms on
  replica 1
- ``FF_FAULT_CORRUPT_RELOAD=1``    truncate the next 1 snapshot file as
  the serving hot-reload opens it
- ``FF_FAULT_REPLICA_DOWN=1``      serving replica 1 is dead (every
  dispatch/probe raises); ``1:8`` fails its next 8 attempts then
  recovers, so the probe/re-admit path runs
- ``FF_FAULT_DELTA_TORN=1``        truncate the next 1 published delta
                                   snapshot after its rename (torn chain)
- ``FF_FAULT_PUBLISH_ABORT=2``     abort the next 2 delta publishes
                                   before the rename (mid-publish crash)
- ``FF_FAULT_QUANT_SCALE=emb:1e3`` corrupt op ``emb``'s quantized-row
  scales by 1e3 on the next load/reload (the serving path must
  reject-with-reason, never serve the amplified rows)
- ``FF_FAULT_DELTA_GAP=1``         drop the next 1 delta's manifest
                                   entry (chain gap the watcher must
                                   reject)
- ``FF_FAULT_POISON_RELOAD=1``     scale the params of the next 1
  snapshot the hot-reload loads (valid file, garbage weights — the
  canary auto-rollback trigger)
- ``FF_FAULT_FEEDBACK_LOSS=0.2``   drop 20% of feedback records before
  they land in the feedback spool (the serve->train loop must keep
  converging on the surviving stream; probability in 0..1)
- ``FF_FAULT_SKETCH_SKEW=emb:10``  scale the hot head of op ``emb``'s
  LIVE id-frequency sketch by 10x (consume-once per op) — the online
  re-placement trigger reads a lying sketch and must still only ever
  install correct plans
- ``FF_FAULT_INDEX_STALE=0:2``     shard 0 answers its next 2 retrieval
  top-k calls from the PREVIOUS index version (the block the last
  publish displaced) — strictly ``sid:n``, a bare sid is rejected; the
  cascade must serve real-but-stale candidates with a truthful version
  vector (degraded-not-garbage)
- ``FF_FAULT_TOPK_DROP=1``         shard 1's retrieval top-k raises
  ``ShardDown`` forever (lookups keep serving); ``1:3`` fails its next
  3 topk calls then recovers — the cascade drops that shard's
  candidates and flags ``degraded``, zero failed requests

Unknown ``FF_FAULT_*`` keys are a WARNING, not a silent no-op: a typo'd
key used to disable injection entirely, which made a passing resilience
test meaningless. Malformed VALUES are harder errors still: a bad
``rid:secs`` pair or non-integer count raises a ``ValueError`` naming
the variable and the expected shape (``FF_FAULT_REPLICA_DOWN='1:x'``
used to half-parse or blow up frames away from any mention of the env
var that caused it).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .logging import get_logger

log_faults = get_logger("faults")


@dataclass
class FaultPlan:
    """A deterministic schedule of failures. All budgets are consume-once
    and protected by a lock (checkpoint writes happen on a background
    thread)."""

    # global step indices at which the train batch is poisoned to NaN
    nan_grad_steps: Set[int] = field(default_factory=set)
    # number of future checkpoint files to truncate after their rename
    truncate_checkpoints: int = 0
    # bytes to leave when truncating (small enough to corrupt the zip)
    truncate_bytes: int = 64
    # number of future checkpoint saves to abort before the rename
    abort_writes: int = 0
    # seconds to sleep between temp-file write and rename (kill window)
    write_delay_s: float = 0.0
    # site name -> number of transient IOErrors to raise there
    io_errors: Dict[str, int] = field(default_factory=dict)
    # global step -> number of devices to report lost at that step
    # (consume-once; drives parallel.elastic recovery on CPU meshes)
    drop_device_steps: Dict[int, int] = field(default_factory=dict)
    # global step -> number of devices to report RETURNED at that step
    # (consume-once; drives parallel.elastic.expand scale-UP — the
    # inverse of drop_device_steps)
    return_device_steps: Dict[int, int] = field(default_factory=dict)
    # number of future warm-cache entry reads to corrupt (truncate the
    # compile/plan cache file being opened; the read must reject with a
    # reason and degrade to a fresh search/compile)
    corrupt_cache_entries: int = 0
    corrupt_cache_bytes: int = 16
    # site name ("collective", "scatter", "prefetch", ...) -> seconds to
    # sleep there once (consume-once; the watchdog deadline must fire)
    stall_s: Dict[str, float] = field(default_factory=dict)
    # seconds to sleep inside EVERY serving batch dispatch (NOT
    # consume-once — a reload-atomicity test needs a steady stream of
    # slow in-flight batches)
    serve_delay_s: float = 0.0
    # replica id -> seconds: per-replica dispatch delay overriding the
    # global one (drives load balancing / hedging / heartbeat tests with
    # ONE slow replica in an otherwise fast fleet)
    serve_delay_replica: Dict[int, float] = field(default_factory=dict)
    # replica id -> remaining failed attempts: every dispatch/probe
    # against that replica reports it dead (engine raises ReplicaDown).
    # -1 = dead forever (a crashed process); N > 0 = the next N attempts
    # fail, then the replica recovers (the probe/re-admit path)
    replica_down: Dict[int, int] = field(default_factory=dict)
    # embedding-shard id -> remaining failed lookups: every lookup/probe
    # against that SERVING shard (serve/shardtier.py) reports it dead
    # (the shard raises ShardDown; rankers degrade to cache + default
    # rows). Same budget semantics as replica_down: -1 = dead until the
    # plan clears, N > 0 = the next N attempts fail then it recovers
    shard_down: Dict[int, int] = field(default_factory=dict)
    # embedding-shard id -> seconds to sleep inside EVERY lookup against
    # that shard (NOT consume-once — deadline/hedging tests need a
    # steadily slow shard); a bare value slows every shard
    lookup_delay_s: float = 0.0
    lookup_delay_shard: Dict[int, float] = field(default_factory=dict)
    # embedding-shard id -> remaining STALE topk answers: the shard
    # serves retrieval top-k from the index version the last publish
    # displaced (serve/shardtier.py keeps the displaced block), so the
    # cascade's degraded-not-garbage contract — real candidates, one
    # version behind, version vector telling the truth — is drillable.
    # Consume-once per answer; -1 = stale until the plan clears
    index_stale: Dict[int, int] = field(default_factory=dict)
    # embedding-shard id -> remaining failed topk calls: ONLY the
    # retrieval surface of that shard dies (lookups keep serving) — the
    # cascade must drop the shard's candidates and flag degraded, never
    # fail the request. Same budget semantics as shard_down
    topk_drop: Dict[int, int] = field(default_factory=dict)
    # number of future hot-reload snapshot loads whose params are scaled
    # by poison_reload_scale: the file is valid, the weights are garbage
    # — the bad deploy a canary must catch by score divergence
    poison_reloads: int = 0
    poison_reload_scale: float = 1e3
    # number of future hot-reload snapshot opens to corrupt (truncate the
    # file the watcher is about to load; the reload must reject it and
    # keep serving the old weights)
    corrupt_reloads: int = 0
    # bytes to leave when corrupting a reload snapshot
    corrupt_reload_bytes: int = 64
    # number of future DELTA snapshot files to truncate right after their
    # atomic rename (a torn delta left on disk — the watcher's chain CRC
    # validation must reject it and fall back to a full reload)
    torn_deltas: int = 0
    torn_delta_bytes: int = 64
    # number of future delta PUBLISHES to abort before the rename (the
    # trainer crashing mid-publish: no torn file may ever be visible at
    # the final path, and the chain manifest must not list the victim)
    publish_aborts: int = 0
    # number of future delta publishes whose manifest entry is silently
    # dropped AFTER the file lands (simulated lost manifest update: the
    # next delta still chains to the unlisted step, so the watcher sees
    # a chain GAP and must degrade to a full reload)
    delta_gaps: int = 0
    # op name -> scale factor: corrupt ONE table's quantized-row scales
    # on the next load/reload touching that op (consume-once per op) —
    # the serving path must reject-with-reason, never serve garbage
    # amplitudes (quant/codec.validate_scales is the gate)
    quant_scale: Dict[str, float] = field(default_factory=dict)
    # --- network-level injection (serve/transport.py applies these
    # INSIDE the wire transport, against real frames) ------------------
    # seam name ("lookup", "dispatch", "publish", "manifest", or "any")
    # -> probability each frame is dropped before it is sent (the client
    # sees a transient error and retries within its budget)
    net_drop: Dict[str, float] = field(default_factory=dict)
    # seam -> remaining frames to DUPLICATE (consume-once): the client
    # sends the same request-id twice; the server's request-id dedup
    # must prove the second delivery a no-op
    net_dup: Dict[str, int] = field(default_factory=dict)
    # seam -> remaining frames to REORDER (consume-once): the server
    # defers the frame until a later arrival has been processed, so a
    # delta chain is delivered out of order — the version vector must
    # stay monotonic (a late publish is an idempotent no-op)
    net_reorder: Dict[str, int] = field(default_factory=dict)
    # seam -> milliseconds added to EVERY frame on that seam (NOT
    # consume-once — deadline/RTT-budget tests need a steadily slow
    # link)
    net_slow_ms: Dict[str, float] = field(default_factory=dict)
    # probability each offered feedback record (a served batch joined
    # with its click labels) is DROPPED before it lands in the feedback
    # spool (the serve->train loop loses a slice of its click stream;
    # the trainer must keep converging on what survives). Probabilistic
    # per offer, drawn from a dedicated seeded RNG
    feedback_loss_p: float = 0.0
    # op name -> scale factor: corrupt the LIVE id-frequency sketch the
    # online re-placement trigger reads (consume-once per op) — a skewed
    # trigger may fire a spurious (or miss a due) re-placement, but any
    # plan it installs must still be correct: never garbage answers
    sketch_skew: Dict[str, float] = field(default_factory=dict)
    # record of (hook, detail) actually fired, for test assertions
    fired: List[tuple] = field(default_factory=list)

    def __post_init__(self):
        from ..analysis.sanitizer import make_lock
        self._lock = make_lock("FaultPlan._lock")
        # deterministic drop draws: the same plan drops the same frames
        # in the same order (seeded, not wall-clock entropy)
        import random as _random
        self._net_rng = _random.Random(0xF0F0)
        # feedback-loss draws get their own stream so wire-level drops
        # and spool-level drops stay independently deterministic
        self._fb_rng = _random.Random(0xFEED)

    def _record(self, hook: str, detail) -> None:
        self.fired.append((hook, detail))
        log_faults.warning("injected fault %s (%s)", hook, detail)


_ACTIVE: Optional[FaultPlan] = None
_ENV_CHECKED = False


_KNOWN_ENV_KEYS = ("FF_FAULT_NAN_STEPS", "FF_FAULT_TRUNCATE_CKPTS",
                   "FF_FAULT_ABORT_WRITES", "FF_FAULT_WRITE_DELAY",
                   "FF_FAULT_IO_ERRORS", "FF_FAULT_DROP_DEVICE",
                   "FF_FAULT_RETURN_DEVICE",
                   "FF_FAULT_STALL_COLLECTIVE", "FF_FAULT_SERVE_DELAY",
                   "FF_FAULT_CORRUPT_RELOAD", "FF_FAULT_REPLICA_DOWN",
                   "FF_FAULT_POISON_RELOAD", "FF_FAULT_DELTA_TORN",
                   "FF_FAULT_PUBLISH_ABORT", "FF_FAULT_DELTA_GAP",
                   "FF_FAULT_CACHE_CORRUPT", "FF_FAULT_SHARD_DOWN",
                   "FF_FAULT_LOOKUP_DELAY", "FF_FAULT_QUANT_SCALE",
                   "FF_FAULT_NET_DROP", "FF_FAULT_NET_DUP",
                   "FF_FAULT_NET_REORDER", "FF_FAULT_NET_SLOW",
                   "FF_FAULT_FEEDBACK_LOSS", "FF_FAULT_SKETCH_SKEW",
                   "FF_FAULT_INDEX_STALE", "FF_FAULT_TOPK_DROP")


# --- strict env parsing ----------------------------------------------
# A malformed value must fail LOUDLY with the variable named: a fault
# schedule that half-parses (or ValueErrors three frames away from any
# mention of FF_FAULT_*) leaves a resilience test silently exercising
# nothing. flexcheck's FLX401 rule keeps all env parsing routed through
# these helpers.
def _env_int(key: str, raw: str) -> int:
    try:
        return int(raw.strip())
    except ValueError:
        raise ValueError(
            f"{key}={raw!r}: expected an integer "
            f"(e.g. {key}=2)") from None


def _env_float(key: str, raw: str) -> float:
    try:
        return float(raw.strip())
    except ValueError:
        raise ValueError(
            f"{key}={raw!r}: expected a number of seconds "
            f"(e.g. {key}=0.5)") from None


def _env_int_set(key: str, raw: str) -> Set[int]:
    return {_env_int(key, s) for s in raw.split(",") if s.strip()}


def _env_pairs(key: str, raw: str, val,
               bare=None) -> list:
    """Parse 'a:b,c:d' lists: each item is (int(a), val(b)); a bare item
    (no colon) maps through `bare` (None = reject it)."""
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            head, tail = part.split(":", 1)
            if ":" in tail:
                raise ValueError(
                    f"{key}={raw!r}: item {part!r} has more than one "
                    f"':' — expected 'id:value'")
            out.append((_env_int(key, head), val(key, tail)))
        elif bare is None:
            raise ValueError(
                f"{key}={raw!r}: item {part!r} is missing its ':' "
                f"(expected 'id:value')")
        else:
            out.append((None, bare(key, part)))
    return out


# The serving seams the transport layer tags its frames with.  A typo'd
# seam head would otherwise parse fine and inject nothing — the chaos
# test it was driving passes without exercising anything — so the parser
# rejects unknown heads outright.  (Kept here, not imported from
# serve.transport: faults must stay import-light so every layer can use
# it.)
NET_SEAMS = ("lookup", "dispatch", "publish", "manifest", "any")


def _env_seam_pairs(key: str, raw: str, val) -> Dict[str, float]:
    """Parse 'seam:value,seam:value' lists for the FF_FAULT_NET_* vars.
    Seam heads are strings (``lookup``, ``dispatch``, ``publish``,
    ``manifest``, or ``any``), so this cannot reuse ``_env_pairs``' int
    heads; strict all the same — a missing ':', an empty seam, or an
    unknown seam names the variable."""
    out: Dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"{key}={raw!r}: item {part!r} is missing its ':' "
                f"(expected 'seam:value', e.g. {key}=lookup:0.3)")
        seam, tail = part.rsplit(":", 1)
        seam = seam.strip()
        if not seam:
            raise ValueError(
                f"{key}={raw!r}: item {part!r} has an empty seam name "
                f"(expected 'seam:value', e.g. {key}=lookup:0.3)")
        if seam not in NET_SEAMS:
            raise ValueError(
                f"{key}={raw!r}: unknown seam {seam!r} — valid seams "
                f"are {', '.join(NET_SEAMS)}")
        out[seam] = val(key, tail)
    return out


def plan_from_env() -> Optional[FaultPlan]:
    """Build a plan from FF_FAULT_* env vars; None when none are set.

    Unknown ``FF_FAULT_*`` keys warn loudly: a typo
    (``FF_FAULT_NAN_STEP=3``) used to silently disable injection, so the
    resilience test it was driving passed without exercising anything.
    """
    unknown = sorted(k for k in os.environ
                     if k.startswith("FF_FAULT_")
                     and k not in _KNOWN_ENV_KEYS)
    if unknown:
        log_faults.warning(
            "ignoring unknown fault-injection env key(s) %s — known keys "
            "are %s (typo? the fault you meant to inject is NOT active)",
            unknown, list(_KNOWN_ENV_KEYS))
    nan = os.environ.get("FF_FAULT_NAN_STEPS", "")
    trunc = os.environ.get("FF_FAULT_TRUNCATE_CKPTS", "")
    aborts = os.environ.get("FF_FAULT_ABORT_WRITES", "")
    delay = os.environ.get("FF_FAULT_WRITE_DELAY", "")
    ioerrs = os.environ.get("FF_FAULT_IO_ERRORS", "")
    drop = os.environ.get("FF_FAULT_DROP_DEVICE", "")
    ret = os.environ.get("FF_FAULT_RETURN_DEVICE", "")
    cache_corrupt = os.environ.get("FF_FAULT_CACHE_CORRUPT", "")
    stall_coll = os.environ.get("FF_FAULT_STALL_COLLECTIVE", "")
    serve_delay = os.environ.get("FF_FAULT_SERVE_DELAY", "")
    corrupt_reload = os.environ.get("FF_FAULT_CORRUPT_RELOAD", "")
    replica_down = os.environ.get("FF_FAULT_REPLICA_DOWN", "")
    poison_reload = os.environ.get("FF_FAULT_POISON_RELOAD", "")
    delta_torn = os.environ.get("FF_FAULT_DELTA_TORN", "")
    publish_abort = os.environ.get("FF_FAULT_PUBLISH_ABORT", "")
    delta_gap = os.environ.get("FF_FAULT_DELTA_GAP", "")
    shard_down = os.environ.get("FF_FAULT_SHARD_DOWN", "")
    lookup_delay = os.environ.get("FF_FAULT_LOOKUP_DELAY", "")
    quant_scale = os.environ.get("FF_FAULT_QUANT_SCALE", "")
    net_drop = os.environ.get("FF_FAULT_NET_DROP", "")
    net_dup = os.environ.get("FF_FAULT_NET_DUP", "")
    net_reorder = os.environ.get("FF_FAULT_NET_REORDER", "")
    net_slow = os.environ.get("FF_FAULT_NET_SLOW", "")
    feedback_loss = os.environ.get("FF_FAULT_FEEDBACK_LOSS", "")
    sketch_skew = os.environ.get("FF_FAULT_SKETCH_SKEW", "")
    index_stale = os.environ.get("FF_FAULT_INDEX_STALE", "")
    topk_drop = os.environ.get("FF_FAULT_TOPK_DROP", "")
    if not any((nan, trunc, aborts, delay, ioerrs, drop, ret,
                cache_corrupt, stall_coll,
                serve_delay, corrupt_reload, replica_down,
                poison_reload, delta_torn, publish_abort, delta_gap,
                shard_down, lookup_delay, quant_scale,
                net_drop, net_dup, net_reorder, net_slow,
                feedback_loss, sketch_skew, index_stale, topk_drop)):
        return None
    plan = FaultPlan()
    if nan:
        plan.nan_grad_steps = _env_int_set("FF_FAULT_NAN_STEPS", nan)
    if trunc:
        plan.truncate_checkpoints = _env_int("FF_FAULT_TRUNCATE_CKPTS",
                                             trunc)
    if aborts:
        plan.abort_writes = _env_int("FF_FAULT_ABORT_WRITES", aborts)
    if delay:
        plan.write_delay_s = _env_float("FF_FAULT_WRITE_DELAY", delay)
    for part in ioerrs.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"FF_FAULT_IO_ERRORS={ioerrs!r}: item {part!r} is "
                f"missing its ':' (expected 'site:count', e.g. "
                f"ffbin_read:2)")
        site, n = part.rsplit(":", 1)
        plan.io_errors[site.strip()] = _env_int("FF_FAULT_IO_ERRORS", n)
    for step, cnt in _env_pairs("FF_FAULT_DROP_DEVICE", drop, _env_int,
                                bare=_env_int):
        if step is None:                      # "=4" — one device, step 4
            plan.drop_device_steps[cnt] = 1
        else:                                 # "4:2" — 2 devices, step 4
            plan.drop_device_steps[step] = cnt
    for step, cnt in _env_pairs("FF_FAULT_RETURN_DEVICE", ret, _env_int,
                                bare=_env_int):
        if step is None:                      # "=6" — one device, step 6
            plan.return_device_steps[cnt] = 1
        else:                                 # "6:2" — 2 devices, step 6
            plan.return_device_steps[step] = cnt
    if cache_corrupt:
        plan.corrupt_cache_entries = _env_int("FF_FAULT_CACHE_CORRUPT",
                                              cache_corrupt)
    if stall_coll:
        plan.stall_s["collective"] = _env_float(
            "FF_FAULT_STALL_COLLECTIVE", stall_coll)
    for rid, secs in _env_pairs("FF_FAULT_SERVE_DELAY", serve_delay,
                                _env_float, bare=_env_float):
        if rid is None:                       # bare seconds — everyone
            plan.serve_delay_s = secs
        else:                                 # "rid:secs" — one replica
            plan.serve_delay_replica[rid] = secs
    for rid, n in _env_pairs("FF_FAULT_REPLICA_DOWN", replica_down,
                             _env_int, bare=_env_int):
        if rid is None:                       # bare rid — dead forever
            plan.replica_down[n] = -1
        else:                                 # "rid:N" — N failures
            plan.replica_down[rid] = n
    for sid, n in _env_pairs("FF_FAULT_SHARD_DOWN", shard_down,
                             _env_int, bare=_env_int):
        if sid is None:                       # bare sid — dead forever
            plan.shard_down[n] = -1
        else:                                 # "sid:N" — N failed lookups
            plan.shard_down[sid] = n
    for sid, secs in _env_pairs("FF_FAULT_LOOKUP_DELAY", lookup_delay,
                                _env_float, bare=_env_float):
        if sid is None:                       # bare seconds — every shard
            plan.lookup_delay_s = secs
        else:                                 # "sid:secs" — one shard
            plan.lookup_delay_shard[sid] = secs
    # strict 'sid:n' ONLY (bare=None): a bare sid is ambiguous between
    # "stale once" and "stale forever", and a half-guessed stale budget
    # makes a freshness drill meaningless
    for sid, n in _env_pairs("FF_FAULT_INDEX_STALE", index_stale,
                             _env_int):
        plan.index_stale[sid] = n
    for sid, n in _env_pairs("FF_FAULT_TOPK_DROP", topk_drop,
                             _env_int, bare=_env_int):
        if sid is None:                       # bare sid — drop forever
            plan.topk_drop[n] = -1
        else:                                 # "sid:N" — N failed topks
            plan.topk_drop[sid] = n
    for part in quant_scale.split(","):
        # 'op:factor' — op names are strings, so this cannot reuse
        # _env_pairs' int heads; strict all the same (missing ':' or a
        # non-numeric factor names the variable)
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"FF_FAULT_QUANT_SCALE={quant_scale!r}: item {part!r} "
                f"is missing its ':' (expected 'op:factor', e.g. "
                f"emb_stack:1e3)")
        op_name, factor = part.rsplit(":", 1)
        plan.quant_scale[op_name.strip()] = _env_float(
            "FF_FAULT_QUANT_SCALE", factor)
    if corrupt_reload:
        plan.corrupt_reloads = _env_int("FF_FAULT_CORRUPT_RELOAD",
                                        corrupt_reload)
    if poison_reload:
        plan.poison_reloads = _env_int("FF_FAULT_POISON_RELOAD",
                                       poison_reload)
    if delta_torn:
        plan.torn_deltas = _env_int("FF_FAULT_DELTA_TORN", delta_torn)
    if publish_abort:
        plan.publish_aborts = _env_int("FF_FAULT_PUBLISH_ABORT",
                                       publish_abort)
    if delta_gap:
        plan.delta_gaps = _env_int("FF_FAULT_DELTA_GAP", delta_gap)
    if net_drop:
        plan.net_drop = _env_seam_pairs("FF_FAULT_NET_DROP", net_drop,
                                        _env_float)
        for seam, p in plan.net_drop.items():
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"FF_FAULT_NET_DROP={net_drop!r}: drop probability "
                    f"for seam {seam!r} is {p} (expected 0..1)")
    if net_dup:
        plan.net_dup = _env_seam_pairs("FF_FAULT_NET_DUP", net_dup,
                                       _env_int)
    if net_reorder:
        plan.net_reorder = _env_seam_pairs("FF_FAULT_NET_REORDER",
                                           net_reorder, _env_int)
    if net_slow:
        plan.net_slow_ms = _env_seam_pairs("FF_FAULT_NET_SLOW",
                                           net_slow, _env_float)
    if feedback_loss:
        plan.feedback_loss_p = _env_float("FF_FAULT_FEEDBACK_LOSS",
                                          feedback_loss)
        if not 0.0 <= plan.feedback_loss_p <= 1.0:
            raise ValueError(
                f"FF_FAULT_FEEDBACK_LOSS={feedback_loss!r}: drop "
                f"probability is {plan.feedback_loss_p} (expected 0..1)")
    for part in sketch_skew.split(","):
        # 'op:factor' — op names are strings, mirroring QUANT_SCALE
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(
                f"FF_FAULT_SKETCH_SKEW={sketch_skew!r}: item {part!r} "
                f"is missing its ':' (expected 'op:factor', e.g. "
                f"emb_stack:10)")
        op_name, factor = part.rsplit(":", 1)
        plan.sketch_skew[op_name.strip()] = _env_float(
            "FF_FAULT_SKETCH_SKEW", factor)
    return plan


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Set (or clear, with None) the process-wide active plan."""
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = plan
    _ENV_CHECKED = True   # an explicit install overrides env discovery
    return plan


def clear() -> None:
    install(None)


def active() -> Optional[FaultPlan]:
    """The active plan; lazily adopts FF_FAULT_* env vars once."""
    global _ACTIVE, _ENV_CHECKED
    if _ACTIVE is None and not _ENV_CHECKED:
        _ENV_CHECKED = True
        _ACTIVE = plan_from_env()
    return _ACTIVE


@contextlib.contextmanager
def active_plan(plan: FaultPlan):
    """Scoped installation for tests."""
    global _ACTIVE, _ENV_CHECKED
    prev, prev_checked = _ACTIVE, _ENV_CHECKED
    install(plan)
    try:
        yield plan
    finally:
        _ACTIVE, _ENV_CHECKED = prev, prev_checked


# ---------------------------------------------------------------------
# hooks (called from the training/checkpoint/data layers; all are no-ops
# when no plan is active)
# ---------------------------------------------------------------------
def take_nan_grad(step: int) -> bool:
    """True exactly once for each scheduled NaN-gradient step."""
    plan = active()
    if plan is None:
        return False
    with plan._lock:
        if step in plan.nan_grad_steps:
            plan.nan_grad_steps.discard(step)
            plan._record("nan_grad", step)
            return True
    return False


def take_drop_device(step: int) -> int:
    """Number of devices to report lost at this global step (0 = none).
    Consume-once: the same step never drops devices twice, so a recovery
    that re-winds through the step does not re-degrade."""
    plan = active()
    if plan is None:
        return 0
    with plan._lock:
        n = plan.drop_device_steps.pop(step, 0)
        if n:
            plan._record("drop_device", (step, n))
    return n


def take_return_device(step: int) -> int:
    """Number of devices reported RETURNED at this global step (0 =
    none). Consume-once, like :func:`take_drop_device`: a recovery that
    re-winds through the step does not re-grow."""
    plan = active()
    if plan is None:
        return 0
    with plan._lock:
        n = plan.return_device_steps.pop(step, 0)
        if n:
            plan._record("return_device", (step, n))
    return n


def maybe_corrupt_cache(path: str) -> bool:
    """Truncate a warm-cache entry file at the moment it is read
    (simulated torn write / bit rot in the persistent compile/plan
    cache). The reader must reject-with-reason and degrade to a fresh
    search/compile — never crash, never load garbage."""
    plan = active()
    if plan is None:
        return False
    with plan._lock:
        if plan.corrupt_cache_entries <= 0:
            return False
        if not os.path.isfile(path):
            return False    # nothing to corrupt yet; keep the budget
        plan.corrupt_cache_entries -= 1
        plan._record("cache_corrupt", path)
    try:
        with open(path, "r+b") as f:
            f.truncate(plan.corrupt_cache_bytes)
    except OSError:
        return False
    return True


def maybe_stall(site: str) -> None:
    """Sleep once at a named site (simulated wedged worker / stuck
    collective). The sleep happens OUTSIDE the plan lock so a stalled
    worker cannot block other hooks."""
    plan = active()
    if plan is None:
        return
    with plan._lock:
        secs = plan.stall_s.pop(site, 0.0)
        if secs > 0:
            plan._record("stall", (site, secs))
    if secs > 0:
        time.sleep(secs)


def maybe_abort_write(path: str) -> None:
    """Raise IOError before the atomic rename (simulated save crash)."""
    plan = active()
    if plan is None:
        return
    with plan._lock:
        if plan.abort_writes > 0:
            plan.abort_writes -= 1
            plan._record("abort_write", path)
            raise IOError(f"injected checkpoint write abort: {path}")


def maybe_delay_write() -> None:
    """Sleep inside the temp-write→rename window (kill-test window)."""
    plan = active()
    if plan is not None and plan.write_delay_s > 0:
        time.sleep(plan.write_delay_s)


def maybe_truncate_file(path: str) -> bool:
    """Truncate a just-written checkpoint file (simulated torn write)."""
    plan = active()
    if plan is None:
        return False
    with plan._lock:
        if plan.truncate_checkpoints <= 0:
            return False
        plan.truncate_checkpoints -= 1
        plan._record("truncate", path)
    with open(path, "r+b") as f:
        f.truncate(plan.truncate_bytes)
    return True


def maybe_io_error(site: str) -> None:
    """Raise a transient IOError at a named read site while its budget
    lasts (the dataloader retry loop must absorb these)."""
    plan = active()
    if plan is None:
        return
    with plan._lock:
        left = plan.io_errors.get(site, 0)
        if left > 0:
            plan.io_errors[site] = left - 1
            plan._record("io_error", site)
            raise IOError(f"injected transient IO error at {site!r} "
                          f"({left - 1} left)")


def maybe_serve_delay(replica_id: Optional[int] = None) -> None:
    """Sleep inside a serving batch dispatch (EVERY dispatch while the
    plan is active — not consume-once — so reload-atomicity tests hold a
    stream of slow in-flight batches). A per-replica entry overrides the
    global delay for that replica, so a fleet test can slow exactly one
    replica and watch the router route around it."""
    plan = active()
    if plan is None:
        return
    secs = plan.serve_delay_s
    if replica_id is not None:
        secs = plan.serve_delay_replica.get(replica_id, secs)
    if secs > 0:
        time.sleep(secs)


def take_replica_down(replica_id: Optional[int]) -> bool:
    """True while a serving replica is scheduled dead: the engine raises
    a typed ``ReplicaDown`` from its dispatch (and from probes), which
    the router's circuit breaker must absorb. A ``-1`` budget is a crash
    (dead until the plan is cleared); a positive budget fails that many
    attempts then recovers — the re-admit path."""
    plan = active()
    if plan is None or replica_id is None:
        return False
    with plan._lock:
        left = plan.replica_down.get(replica_id)
        if left is None or left == 0:
            return False
        if left > 0:
            plan.replica_down[replica_id] = left - 1
        # record the transition once, not every refused dispatch — a
        # hammered dead replica would otherwise flood `fired`
        if ("replica_down", replica_id) not in plan.fired:
            plan._record("replica_down", replica_id)
    return True


def take_shard_down(shard_id: Optional[int]) -> bool:
    """True while a serving EMBEDDING SHARD is scheduled dead: the shard
    raises a typed ``ShardDown`` from its lookup (and from admission
    probes), which the shard tier's circuit breaker must absorb — the
    ranker degrades to cache hits + per-table default rows instead of
    failing the request. Budget semantics mirror
    :func:`take_replica_down`: ``-1`` = dead until the plan clears,
    ``N > 0`` = the next N lookups fail then the shard recovers."""
    plan = active()
    if plan is None or shard_id is None:
        return False
    with plan._lock:
        left = plan.shard_down.get(shard_id)
        if left is None or left == 0:
            return False
        if left > 0:
            plan.shard_down[shard_id] = left - 1
        if ("shard_down", shard_id) not in plan.fired:
            plan._record("shard_down", shard_id)
    return True


def take_topk_drop(shard_id: Optional[int]) -> bool:
    """True while a shard's RETRIEVAL surface is scheduled dead: its
    ``topk`` raises ``ShardDown`` while ordinary lookups keep serving —
    the cascade must drop that shard's candidates and flag ``degraded``,
    never fail the request. Budget semantics mirror
    :func:`take_shard_down` (``-1`` = dead until the plan clears)."""
    plan = active()
    if plan is None or shard_id is None:
        return False
    with plan._lock:
        left = plan.topk_drop.get(shard_id)
        if left is None or left == 0:
            return False
        if left > 0:
            plan.topk_drop[shard_id] = left - 1
        if ("topk_drop", shard_id) not in plan.fired:
            plan._record("topk_drop", shard_id)
    return True


def take_index_stale(shard_id: Optional[int]) -> bool:
    """True when this topk answer should come from the PREVIOUS index
    version (the block the last publish displaced) — consume-once per
    answer, so ``sid:n`` yields exactly n stale answers. The shard
    reports the stale version in its answer: degraded-not-garbage means
    the version vector tells the truth about what was read."""
    plan = active()
    if plan is None or shard_id is None:
        return False
    with plan._lock:
        left = plan.index_stale.get(shard_id)
        if left is None or left == 0:
            return False
        if left > 0:
            plan.index_stale[shard_id] = left - 1
        if ("index_stale", shard_id) not in plan.fired:
            plan._record("index_stale", shard_id)
    return True


def maybe_lookup_delay(shard_id: Optional[int] = None) -> None:
    """Sleep inside a shard lookup (EVERY lookup while the plan is
    active — deadline/retry/hedging tests need a steadily slow shard).
    A per-shard entry overrides the global delay for that shard."""
    plan = active()
    if plan is None:
        return
    secs = plan.lookup_delay_s
    if shard_id is not None:
        secs = plan.lookup_delay_shard.get(shard_id, secs)
    if secs > 0:
        time.sleep(secs)


def _net_value(table: Dict[str, float], seam: str):
    """Per-seam entry with an ``any`` wildcard fallback (the exact seam
    wins, mirroring the per-replica/per-shard override pattern)."""
    if seam in table:
        return seam, table[seam]
    if "any" in table:
        return "any", table["any"]
    return None, None


def take_net_drop(seam: str) -> bool:
    """True when this seam's next frame should be DROPPED before it is
    sent (``FF_FAULT_NET_DROP=seam:p``): the transport raises a
    transient wire error without touching the socket, and its bounded
    retry/backoff must absorb the loss. Probabilistic per frame, drawn
    from the plan's seeded RNG (deterministic across runs)."""
    plan = active()
    if plan is None or not plan.net_drop:
        return False
    with plan._lock:
        key, p = _net_value(plan.net_drop, seam)
        if key is None or p <= 0:
            return False
        if plan._net_rng.random() >= p:
            return False
        if ("net_drop", seam) not in plan.fired:
            plan._record("net_drop", seam)
    return True


def take_net_dup(seam: str) -> bool:
    """True when this seam's next frame should be sent TWICE with the
    same request-id (``FF_FAULT_NET_DUP=seam:n``, consume-once): the
    server's request-id dedup must answer the duplicate from its cache
    without re-invoking the handler — delivered-twice proven a no-op."""
    plan = active()
    if plan is None or not plan.net_dup:
        return False
    with plan._lock:
        key, left = _net_value(plan.net_dup, seam)
        if key is None or not left:
            return False
        if left > 0:
            plan.net_dup[key] = left - 1
        plan._record("net_dup", seam)
    return True


def take_net_reorder(seam: str) -> bool:
    """True when this seam's next RECEIVED frame should be REORDERED
    (``FF_FAULT_NET_REORDER=seam:n``, consume-once): the server defers
    processing it until a later frame has been handled (bounded by a
    timeout so a lone frame cannot deadlock), delivering e.g. a delta
    chain out of order — version-vector monotonicity must hold because
    a late publish is an idempotent no-op."""
    plan = active()
    if plan is None or not plan.net_reorder:
        return False
    with plan._lock:
        key, left = _net_value(plan.net_reorder, seam)
        if key is None or not left:
            return False
        if left > 0:
            plan.net_reorder[key] = left - 1
        plan._record("net_reorder", seam)
    return True


def maybe_net_slow(seam: str) -> None:
    """Sleep before sending a frame on this seam
    (``FF_FAULT_NET_SLOW=seam:ms``, EVERY frame while the plan is
    active — deadline and RTT-budget tests need a steadily slow
    link)."""
    plan = active()
    if plan is None or not plan.net_slow_ms:
        return
    _key, ms = _net_value(plan.net_slow_ms, seam)
    if ms and ms > 0:
        time.sleep(ms / 1e3)


def maybe_corrupt_quant_scale(key: str, scales):
    """Corrupt a quantized payload's row scales at load/reload time
    (``FF_FAULT_QUANT_SCALE=op:factor``): the key is matched by op name
    (any flat key mentioning the op fires), the budget is consume-once
    per op. The caller's validation (quant/codec.validate_scales) must
    reject the payload with a reason — a corrupted scale serves rows
    amplified by `factor` with no NaN to trip any sentinel, the
    quantized analog of the poison-reload drill."""
    plan = active()
    if plan is None or not plan.quant_scale:
        return scales
    with plan._lock:
        hit = None
        for op_name, factor in plan.quant_scale.items():
            if op_name and op_name in key:
                hit = (op_name, factor)
                break
        if hit is None:
            return scales
        del plan.quant_scale[hit[0]]
        plan._record("quant_scale", f"{key}:{hit[1]:g}")
    import numpy as np
    return np.asarray(scales, np.float32) * np.float32(hit[1])


def take_feedback_loss() -> bool:
    """True when the next offered feedback record should be DROPPED
    before it lands in the feedback spool
    (``FF_FAULT_FEEDBACK_LOSS=p``): the serve->train loop loses a slice
    of its click stream and the trainer must keep converging on what
    survives. Probabilistic per offer, drawn from a dedicated seeded
    RNG (deterministic across runs; recorded once in ``fired``)."""
    plan = active()
    if plan is None or plan.feedback_loss_p <= 0:
        return False
    with plan._lock:
        if plan._fb_rng.random() >= plan.feedback_loss_p:
            return False
        if ("feedback_loss", "spool") not in plan.fired:
            plan._record("feedback_loss", "spool")
    return True


def maybe_skew_sketch(op_name: str, counts):
    """Corrupt a LIVE id-frequency sketch's bucket counts
    (``FF_FAULT_SKETCH_SKEW=op:factor``, matched by op-name substring,
    consume-once per op): the hot head of the sketch (its first 1% of
    buckets) is scaled by ``factor``, faking (> 1) or hiding (< 1) hot
    mass. The online re-placement trigger reads this sketch — a skewed
    trigger may fire a spurious (or miss a due) re-placement, but any
    plan it installs must still serve correct answers. Returns the
    (possibly skewed) counts in the caller's dtype."""
    plan = active()
    if plan is None or not plan.sketch_skew:
        return counts
    with plan._lock:
        hit = None
        for name, factor in plan.sketch_skew.items():
            if name and name in op_name:
                hit = (name, factor)
                break
        if hit is None:
            return counts
        del plan.sketch_skew[hit[0]]
        plan._record("sketch_skew", f"{op_name}:{hit[1]:g}")
    import numpy as np
    arr = np.asarray(counts)
    out = arr.astype(np.float64, copy=True)
    head = max(1, out.size // 100)
    out[:head] *= float(hit[1])
    if np.issubdtype(arr.dtype, np.integer):
        out = np.maximum(np.rint(out), 0).astype(arr.dtype)
    return out


def maybe_poison_reload(state: dict) -> dict:
    """Scale the float params of a freshly-loaded snapshot state (the
    output of ``load_params_for_swap``) while the poison budget lasts —
    a snapshot that passes every integrity check but computes garbage,
    i.e. a bad deploy. Returns the (possibly poisoned) state. Shardings
    are preserved so the swapped params still feed the cached AOT
    executables."""
    plan = active()
    if plan is None:
        return state
    with plan._lock:
        if plan.poison_reloads <= 0:
            return state
        plan.poison_reloads -= 1
        scale = plan.poison_reload_scale
        plan._record("poison_reload", scale)
    import jax
    import numpy as np

    def _scale(v):
        a = np.asarray(v)
        if not np.issubdtype(a.dtype, np.floating):
            return v
        sharding = getattr(v, "sharding", None)
        poisoned = (a * np.dtype(a.dtype).type(scale)).astype(a.dtype)
        return (jax.device_put(poisoned, sharding)
                if sharding is not None else poisoned)

    out = dict(state)
    if out.get("params") is not None:
        out["params"] = jax.tree.map(_scale, out["params"])
    if out.get("host_params") is not None:
        out["host_params"] = jax.tree.map(_scale, out["host_params"])
    return out


def maybe_abort_publish(path: str) -> None:
    """Raise IOError before a delta snapshot's atomic rename (the
    trainer crashing mid-publish). The temp file is cleaned up by the
    writer; no torn file may ever be visible at the final path and the
    chain manifest must not gain the victim's entry."""
    plan = active()
    if plan is None:
        return
    with plan._lock:
        if plan.publish_aborts > 0:
            plan.publish_aborts -= 1
            plan._record("publish_abort", path)
            raise IOError(f"injected delta publish abort: {path}")


def maybe_torn_delta(path: str) -> bool:
    """Truncate a just-published delta file (torn write / bit rot after
    the rename). The watcher's chain CRC validation must reject the
    whole chain and degrade to a full reload."""
    plan = active()
    if plan is None:
        return False
    with plan._lock:
        if plan.torn_deltas <= 0:
            return False
        plan.torn_deltas -= 1
        plan._record("torn_delta", path)
    with open(path, "r+b") as f:
        f.truncate(plan.torn_delta_bytes)
    return True


def take_delta_gap() -> bool:
    """True once per budgeted gap: the publisher drops this delta's
    manifest entry after the file lands, so the NEXT delta's prev link
    points at an unlisted step — the watcher must detect the chain gap
    and degrade to a full reload."""
    plan = active()
    if plan is None:
        return False
    with plan._lock:
        if plan.delta_gaps <= 0:
            return False
        plan.delta_gaps -= 1
        plan._record("delta_gap", None)
    return True


def maybe_corrupt_reload(path: str) -> bool:
    """Truncate a snapshot file at the moment the serving hot-reload is
    about to load it (after the manifest already listed it as valid) —
    the torn-file-discovered-mid-reload race. The reload must reject it
    (CRC/zip failure) and keep serving the old weights."""
    plan = active()
    if plan is None:
        return False
    with plan._lock:
        if plan.corrupt_reloads <= 0:
            return False
        plan.corrupt_reloads -= 1
        plan._record("corrupt_reload", path)
    try:
        with open(path, "r+b") as f:
            f.truncate(plan.corrupt_reload_bytes)
    except OSError:
        return False
    return True


def poison_batch(device_batch: dict, row: Optional[int] = None) -> dict:
    """Return a copy of a staged batch with its float label (or, when the
    label is integer, the first float input) replaced by NaNs — same
    shapes/dtypes/shardings, so the cached step executable still applies
    and the NaN flows through the real autodiff.

    With ``row`` given, only that leading-axis index is poisoned: a
    superstep megabatch (``[K, batch, ...]`` stacked arrays) gets NaNs in
    exactly ONE of its K fused steps, so a mid-superstep anomaly drives
    the sentinel inside the scan while the sibling steps stay clean."""
    import jax
    import numpy as np

    out = dict(device_batch)
    target = None
    lab = out.get("label")
    if lab is not None and np.issubdtype(np.dtype(lab.dtype), np.floating):
        target = "label"
    else:
        for k, v in out.items():
            if k != "label" and np.issubdtype(np.dtype(v.dtype),
                                              np.floating):
                target = k
                break
    if target is None:
        raise ValueError("no float tensor in batch to poison with NaNs")
    v = out[target]
    if row is None:
        nan = np.full(v.shape, np.nan, dtype=np.dtype(v.dtype))
    else:
        nan = np.asarray(v).copy()
        nan[row] = np.nan
    sharding = getattr(v, "sharding", None)
    out[target] = (jax.device_put(nan, sharding)
                   if sharding is not None else nan)
    return out
