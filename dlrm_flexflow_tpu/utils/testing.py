"""Host-platform device virtualization for tests and multi-chip dry runs.

The reference can only test multi-GPU behavior on real GPUs grabbed via
SLURM (reference: src/ops/tests/test_bootstrap.sh:2). A design goal here
(SURVEY.md §4) is that distribution logic is testable WITHOUT hardware:
`ensure_cpu_devices(n)` forces the JAX host platform with n virtual CPU
devices so the full GSPMD mesh/collective path compiles and runs anywhere.

Must run before JAX initializes its backends (it mutates XLA_FLAGS and the
platform config); it is a no-op if enough devices already exist.
"""

from __future__ import annotations

import os
import re


def ensure_cpu_devices(n: int) -> None:
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        import warnings
        if len(jax.devices()) < n:
            warnings.warn(
                f"JAX backends already initialized with "
                f"{len(jax.devices())} device(s); cannot virtualize {n} "
                f"CPU devices. Call ensure_cpu_devices() before any JAX "
                f"computation.")
        return

    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    # The axon sitecustomize pins jax_platforms to the TPU plugin
    # programmatically, so the JAX_PLATFORMS env var alone is not enough.
    jax.config.update("jax_platforms", "cpu")
