"""Persistent compile + plan caches: make recovery and cold starts warm.

Every recovery and cold start in this framework used to re-pay work whose
inputs had not changed: elastic ``recover()``/``expand()`` re-ran the MCMC
strategy search and recompiled every step executable, a serving replica
AOT-warmed every bucket from scratch at boot, and ``shardcheck`` re-derived
plans it had already verified. ROADMAP item 4 calls this out: what should
be millisecond failover is seconds of search + XLA compile + bucket warmup.

Two caches, both living in one directory NEXT TO the checkpoint manifest
(``<checkpoint_dir>/cache/`` by convention — the snapshot and the
executables that can serve it travel together):

- :class:`PlanCache` — MCMC strategy maps keyed by (graph fingerprint,
  device count, mesh-axis signature, search budget, seed). The search is
  deterministic for that key, so a hit returns EXACTLY the plan a fresh
  search would produce — the elastic bit-identity contract survives the
  cache. Stored as one human-readable ``plans.json``.
- :class:`CompileCache` — AOT executables (train / eval / superstep /
  serving buckets) serialized via ``jax.experimental.serialize_executable``,
  keyed by (kind, code fingerprint, strategy signature, mesh signature,
  shape signature). One file per entry, written atomically.

A third cache, :class:`ShardCache`, serves the SERVING shard tier
(serve/shardtier.py): per-shard embedding row blocks persisted on every
publish so the autoscaler's replace-dead path can boot a replacement
lookup shard warm (version + chain-CRC validated) instead of re-slicing
a full checkpoint.

Both caches fail OPEN with a named reason: a corrupt, truncated, stale
(code-fingerprint mismatch), or wrong-topology entry is rejected and the
caller falls back to a fresh search/compile — the same
reject-with-reason-then-degrade contract as PR 10's delta chains. A cache
can make a cold start slow again; it can never make it wrong.

Entry validity:

- every compile-cache entry embeds the FULL key string and a CRC-32 of the
  executable payload; a hash-collision, torn write, or bit rot is caught
  before ``deserialize_and_load`` runs;
- the code fingerprint digests the step-builder sources + jax version, so
  an upgraded checkout silently ignores (does not load) executables
  compiled by old code;
- the mesh signature includes the concrete device ids — an executable
  compiled for one replica's device is never handed to another's
  (shardcheck FLX506 audits the same hazard statically for plans).

Fault injection: ``FF_FAULT_CACHE_CORRUPT=n`` truncates the next n cache
entry files at the moment they are read, driving the graceful-degradation
path deterministically (tests/test_elastic.py).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
import zlib
from typing import Any, Dict, Optional, Tuple

from .logging import get_logger

log_cache = get_logger("warmcache")


def _obs_cache_event(cache: str, event: str) -> None:
    """Warm-cache hit/miss counter for the obs registry (no-op when
    --obs off): a fleet whose cold boots stopped hitting the compile
    cache shows up as a climbing miss series, not a mystery."""
    from ..obs import metrics as obsm
    obsm.counter("ff_warmcache_events_total",
                 "plan/compile warm-cache lookups by outcome",
                 labelnames=("cache", "event")).inc(cache=cache,
                                                    event=event)


# cache-layout version: bump to orphan every existing entry when the
# on-disk format changes (old files are simply never matched)
_FORMAT = 1

PLANS_FILE = "plans.json"


# ---------------------------------------------------------------------
# fingerprints / signatures
# ---------------------------------------------------------------------
def _sha1(blob: str) -> str:
    return hashlib.sha1(blob.encode()).hexdigest()


def code_fingerprint() -> str:
    """Digest of everything an AOT executable's VALIDITY depends on that a
    shape/strategy key cannot see: the jax/jaxlib versions and the source
    bytes of the step-builder modules. A checkout upgrade makes every old
    entry a clean miss instead of a wrong load."""
    import jax

    import dlrm_flexflow_tpu
    h = hashlib.sha1()
    h.update(jax.__version__.encode())
    h.update(getattr(dlrm_flexflow_tpu, "__version__", "?").encode())
    pkg = os.path.dirname(os.path.abspath(dlrm_flexflow_tpu.__file__))
    for rel in ("core/model.py", "parallel/alltoall.py",
                "parallel/sharding.py", "ops/embedding.py"):
        try:
            with open(os.path.join(pkg, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(f"missing:{rel}".encode())
    return h.hexdigest()[:16]


def graph_fingerprint(model) -> str:
    """Mesh-independent digest of the op graph: names, types, and tensor
    shapes. Two models with the same fingerprint accept the same strategy
    map — the PlanCache key's first component."""
    desc = [(op.name, type(op).__name__,
             [tuple(int(x) for x in t.shape) for t in op.inputs],
             [tuple(int(x) for x in t.shape) for t in op.outputs])
            for op in model.ops]
    return _sha1(json.dumps(desc, sort_keys=True))[:16]


def mesh_signature(mesh) -> str:
    """Concrete mesh identity: axis names/sizes, platform, AND device ids.
    Device ids matter — a fleet's replicas sit on disjoint single-device
    meshes, and an executable compiled against one device cannot run
    against another's arrays."""
    devs = list(mesh.devices.flat)
    return json.dumps({
        "axes": {a: int(mesh.shape[a]) for a in mesh.axis_names},
        "platform": getattr(devs[0], "platform", "?") if devs else "?",
        "device_ids": [int(getattr(d, "id", -1)) for d in devs],
    }, sort_keys=True)


def strategy_signature(strategies) -> str:
    """Stable digest of a strategy map (every field that changes the
    lowered program)."""
    desc = {name: [list(pc.degrees), pc.device_type,
                   list(pc.memory_types),
                   int(getattr(pc, "param_degree", 1)),
                   getattr(pc, "exchange", "dense"),
                   float(getattr(pc, "hot_fraction", 0.0)),
                   bool(getattr(pc, "overlap", False))]
            for name, pc in (strategies or {}).items()}
    return _sha1(json.dumps(desc, sort_keys=True))[:16]


# ---------------------------------------------------------------------
# plan cache
# ---------------------------------------------------------------------
def _pc_to_json(pc) -> Dict[str, Any]:
    return {"degrees": list(map(int, pc.degrees)),
            "device_type": pc.device_type,
            "memory_types": list(pc.memory_types),
            "param_degree": int(getattr(pc, "param_degree", 1)),
            "exchange": getattr(pc, "exchange", "dense"),
            "hot_fraction": float(getattr(pc, "hot_fraction", 0.0)),
            "overlap": bool(getattr(pc, "overlap", False))}


def _pc_from_json(d: Dict[str, Any]):
    from ..parallel.pconfig import ParallelConfig
    return ParallelConfig(tuple(d["degrees"]),
                          device_type=d.get("device_type", "TPU"),
                          memory_types=tuple(d.get("memory_types", ())),
                          param_degree=int(d.get("param_degree", 1)),
                          exchange=d.get("exchange", "dense"),
                          hot_fraction=float(d.get("hot_fraction", 0.0)),
                          overlap=bool(d.get("overlap", False)))


class PlanCache:
    """MCMC plans keyed by (graph, topology, budget, seed) in one JSON
    file. Thread-safe for the read-modify-replace write; concurrent
    writers last-win per key (entries are deterministic per key, so a
    lost update rewrites identical content)."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        from ..analysis.sanitizer import make_lock
        self._lock = make_lock("PlanCache._lock")
        self.hits = 0
        self.misses = 0
        self.rejects = 0
        self.last_reject = ""

    def _path(self) -> str:
        return os.path.join(self.directory, PLANS_FILE)

    @staticmethod
    def key(graph_fp: str, ndev: int, axis_sizes, budget: int,
            seed: int) -> str:
        axes = "x".join(str(int(a)) for a in axis_sizes)
        return f"{graph_fp}|ndev={int(ndev)}|axes={axes}|" \
               f"budget={int(budget)}|seed={int(seed)}"

    def _read(self) -> Dict[str, Any]:
        from . import faults
        path = self._path()
        try:
            faults.maybe_corrupt_cache(path)
            with open(path) as f:
                m = json.load(f)
            if isinstance(m, dict) and m.get("format") == _FORMAT:
                return m
            if os.path.exists(path):
                self._reject(f"{PLANS_FILE} has format "
                             f"{m.get('format') if isinstance(m, dict) else '?'}"
                             f" != {_FORMAT}; ignoring")
        except FileNotFoundError:
            pass
        except (json.JSONDecodeError, OSError, ValueError) as e:
            self._reject(f"unreadable {PLANS_FILE} ({e}); treating as empty")
        return {"format": _FORMAT, "plans": {}}

    def _reject(self, reason: str) -> None:
        self.rejects += 1
        self.last_reject = reason
        log_cache.warning("plan cache: %s", reason)

    def get(self, key: str, ndev: int) -> Optional[Dict[str, Any]]:
        """The cached strategy map for `key`, or None. A hit whose
        recorded device count disagrees with `ndev` (a corrupt or
        hand-edited entry — the silent correctness hazard shardcheck
        FLX506 exists for) is rejected, not returned."""
        out = self._get(key, ndev)
        _obs_cache_event("plan", "hit" if out is not None else "miss")
        return out

    def _get(self, key: str, ndev: int) -> Optional[Dict[str, Any]]:
        entry = self._read()["plans"].get(key)
        if entry is None:
            self.misses += 1
            return None
        if int(entry.get("ndev", -1)) != int(ndev):
            self._reject(
                f"entry {key!r} records ndev={entry.get('ndev')} but the "
                f"target mesh has {ndev} device(s) — a plan cached for "
                f"one topology must not ship on another")
            self.misses += 1
            return None
        try:
            strategies = {name: _pc_from_json(d)
                          for name, d in entry["strategies"].items()}
        except (KeyError, TypeError, ValueError) as e:
            self._reject(f"entry {key!r} failed to decode ({e})")
            self.misses += 1
            return None
        self.hits += 1
        return {"strategies": strategies, "ndev": int(entry["ndev"]),
                "searched": bool(entry.get("searched", False))}

    def put(self, key: str, strategies, ndev: int,
            searched: bool = False) -> None:
        entry = {"ndev": int(ndev), "searched": bool(searched),
                 "time": time.time(),
                 "strategies": {name: _pc_to_json(pc)
                                for name, pc in strategies.items()}}
        path = self._path()
        with self._lock:
            m = self._read()
            m["plans"][key] = entry
            tmp = f"{path}.tmp-{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(m, f, indent=1, sort_keys=True)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError as e:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                # best-effort: a cache that cannot write costs the next
                # recovery a search, never correctness
                log_cache.warning("plan cache write failed (%s)", e)

    def entries(self) -> Dict[str, Dict[str, Any]]:
        """Raw {key: entry} view (shardcheck's --plan-cache audit reads
        this to re-verify every cached plan against its recorded mesh)."""
        return dict(self._read()["plans"])

    def stats(self) -> Dict[str, Any]:
        return {"hits": self.hits, "misses": self.misses,
                "rejects": self.rejects, "last_reject": self.last_reject}


# ---------------------------------------------------------------------
# compile cache
# ---------------------------------------------------------------------
class CompileCache:
    """Serialized AOT executables, one file per (kind, fingerprint,
    strategy, mesh, shape) key.

    ``get`` returns a loaded ``jax.stages.Compiled`` or None; EVERY
    failure mode (missing, torn, CRC mismatch, stale code fingerprint,
    key collision, deserialize error, backend without serialization
    support) is a miss with a recorded reason — never an exception on
    the caller's hot path."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._code_fp = code_fingerprint()
        self.hits = 0
        self.misses = 0
        self.rejects = 0
        self.puts = 0
        self.put_errors = 0
        self.last_reject = ""

    # --- keys ----------------------------------------------------------
    def exec_key(self, kind: str, model, shape_key) -> str:
        """Full executable identity: kind (train/eval/superstep/...),
        code fingerprint, strategy signature, mesh signature (device ids
        included), and the caller's shape/sharding signature."""
        return "|".join((
            f"fmt={_FORMAT}", f"kind={kind}", f"code={self._code_fp}",
            f"strat={strategy_signature(getattr(model, 'strategies', None))}",
            f"mesh={mesh_signature(model.mesh)}",
            f"shape={shape_key!r}"))

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, f"exec-{_sha1(key)}.bin")

    def _reject(self, reason: str) -> None:
        self.rejects += 1
        self.last_reject = reason
        log_cache.warning("compile cache: %s — falling back to a fresh "
                          "compile", reason)

    # --- read ----------------------------------------------------------
    def get(self, key: str):
        out = self._get(key)
        _obs_cache_event("compile", "hit" if out is not None else "miss")
        return out

    def _get(self, key: str):
        from . import faults
        path = self._path(key)
        if not os.path.isfile(path):
            self.misses += 1
            return None
        name = os.path.basename(path)
        try:
            faults.maybe_corrupt_cache(path)
            with open(path, "rb") as f:
                blob = pickle.load(f)
        except Exception as e:   # noqa: BLE001 — torn pickle, IO error:
            self._reject(f"{name} unreadable ({type(e).__name__}: {e})")
            self.misses += 1
            return None
        try:
            if blob.get("key") != key:
                raise ValueError(
                    f"key mismatch (hash collision or renamed file): "
                    f"cached {blob.get('key')!r:.80}")
            if blob.get("code") != self._code_fp:
                raise ValueError(
                    f"stale code fingerprint {blob.get('code')} != "
                    f"{self._code_fp} (checkout changed since compile)")
            payload = blob["payload"]
            if zlib.crc32(payload) != blob.get("crc32"):
                raise ValueError("payload CRC mismatch (bit rot)")
            from jax.experimental import serialize_executable
            exec_ = serialize_executable.deserialize_and_load(
                payload, blob["in_tree"], blob["out_tree"])
        except Exception as e:   # noqa: BLE001 — stale/corrupt/unsupported
            self._reject(f"{name}: {e}")
            self.misses += 1
            return None
        self.hits += 1
        return exec_

    # --- write ---------------------------------------------------------
    def put(self, key: str, compiled) -> bool:
        """Best-effort serialize+store; False (with a counted error) when
        the executable does not support serialization or the write
        fails. The caller already holds the compiled executable — a
        failed put costs the NEXT boot a compile, nothing else."""
        try:
            from jax.experimental import serialize_executable
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
        except Exception as e:   # noqa: BLE001 — backend w/o support
            self.put_errors += 1
            log_cache.info("compile cache: executable not serializable "
                           "(%s); entry skipped", e)
            return False
        blob = {"format": _FORMAT, "key": key, "code": self._code_fp,
                "payload": payload, "crc32": zlib.crc32(payload),
                "in_tree": in_tree, "out_tree": out_tree,
                "time": time.time()}
        path = self._path(key)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(blob, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception as e:   # noqa: BLE001 — full disk, perms
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self.put_errors += 1
            log_cache.warning("compile cache write failed (%s)", e)
            return False
        self.puts += 1
        return True

    def stats(self) -> Dict[str, Any]:
        return {"hits": self.hits, "misses": self.misses,
                "rejects": self.rejects, "puts": self.puts,
                "put_errors": self.put_errors,
                "last_reject": self.last_reject}


# ---------------------------------------------------------------------
# serving shard cache
# ---------------------------------------------------------------------
class ShardCache:
    """Persisted embedding-shard row blocks for the serving shard tier
    (serve/shardtier.py): one npz per (nshards, slot) carrying the
    shard's per-op row blocks, its applied version, and its publish
    chain CRC.

    This is the shard tier's replace-dead warm start: when a lookup
    shard is ejected and replaced, the replacement boots from its slot's
    cached blocks (milliseconds) instead of re-slicing a full checkpoint
    — and is re-admitted only when its version + chain CRC match what
    the live set expects AND its admission probe succeeds. Every failure
    mode (missing, torn, CRC mismatch, foreign fingerprint, wrong slot
    geometry) is a miss with a recorded reason, exactly like the
    plan/compile caches above."""

    def __init__(self, directory: str, fingerprint: str = ""):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.fingerprint = fingerprint
        self.hits = 0
        self.misses = 0
        self.rejects = 0
        self.puts = 0
        self.put_errors = 0
        self.last_reject = ""

    def _path(self, nshards: int, slot: int) -> str:
        return os.path.join(self.directory,
                            f"shard-{nshards}x-{slot}.npz")

    def _reject(self, reason: str) -> None:
        self.rejects += 1
        self.last_reject = reason
        log_cache.warning("shard cache: %s — replacement shard must "
                          "rebuild cold", reason)

    def put(self, nshards: int, slot: int, blocks: Dict[str, "np.ndarray"],
            version: int, chain_crc: int) -> bool:
        """Atomically persist one shard's blocks (temp + fsync +
        os.replace, the checkpoint discipline). Best-effort: a failed
        put costs the next replacement a cold rebuild, nothing else."""
        import numpy as np

        from ..quant.store import QuantTable
        flat = {}
        for k, v in blocks.items():
            if isinstance(v, QuantTable):
                # quantized blocks persist as codes + row scales +
                # dtype — bit-exact round trip at ~1/4 the fp32 bytes;
                # the max-scale bound lets get() reject in-memory
                # scale corruption the file CRC cannot see
                flat[f"block/{k}"] = v.encoded()
                flat[f"scale/{k}"] = v.scales
                flat[f"qdt/{k}"] = np.asarray(v.dtype)
                flat[f"sbd/{k}"] = np.asarray(
                    float(v.scales.max()) if v.scales.size else 0.0,
                    np.float32)
            else:
                flat[f"block/{k}"] = np.ascontiguousarray(v)
        flat["meta/version"] = np.asarray(version, np.int64)
        flat["meta/chain_crc"] = np.asarray(chain_crc & 0xFFFFFFFF,
                                            np.int64)
        flat["meta/nshards"] = np.asarray(nshards, np.int64)
        flat["meta/slot"] = np.asarray(slot, np.int64)
        if self.fingerprint:
            flat["meta/fingerprint"] = np.frombuffer(
                self.fingerprint.encode(), np.uint8)
        crc = 0
        for k in sorted(flat):
            crc = zlib.crc32(k.encode(), crc)
            crc = zlib.crc32(np.ascontiguousarray(flat[k]), crc)
        flat["meta/crc32"] = np.asarray(crc, np.int64)
        path = self._path(nshards, slot)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.savez(f, **flat)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception as e:   # noqa: BLE001 — full disk, perms
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self.put_errors += 1
            log_cache.warning("shard cache write failed (%s)", e)
            return False
        self.puts += 1
        return True

    def get(self, nshards: int, slot: int):
        """(blocks, version, chain_crc) or None with the reason
        recorded. The corrupt-cache fault hook fires here so chaos tests
        can prove a torn entry degrades to a cold rebuild."""
        import numpy as np

        from . import faults
        path = self._path(nshards, slot)
        if not os.path.isfile(path):
            self.misses += 1
            return None
        name = os.path.basename(path)
        try:
            faults.maybe_corrupt_cache(path)
            data = np.load(path)
            files = set(data.files)
            stored_crc = int(data["meta/crc32"])
            crc = 0
            for k in sorted(files - {"meta/crc32"}):
                crc = zlib.crc32(k.encode(), crc)
                crc = zlib.crc32(np.ascontiguousarray(data[k]), crc)
            if crc != stored_crc:
                raise ValueError("entry CRC mismatch (torn write / "
                                 "bit rot)")
            if self.fingerprint and "meta/fingerprint" in files:
                fp = bytes(data["meta/fingerprint"]).decode()
                if fp != self.fingerprint:
                    raise ValueError(
                        f"foreign fingerprint {fp} != "
                        f"{self.fingerprint} (differently-built model)")
            if (int(data["meta/nshards"]) != nshards
                    or int(data["meta/slot"]) != slot):
                raise ValueError(
                    f"geometry mismatch: entry is shard "
                    f"{int(data['meta/slot'])}/{int(data['meta/nshards'])}"
                    f", wanted {slot}/{nshards}")
            from ..quant.codec import validate_scales
            from ..quant.store import QuantTable
            blocks = {}
            for k in files:
                if not k.startswith("block/"):
                    continue
                op = k[len("block/"):]
                if f"scale/{op}" in files:
                    dt = str(data[f"qdt/{op}"])
                    scales = faults.maybe_corrupt_quant_scale(
                        op, np.array(data[f"scale/{op}"]))
                    # a corrupt scale must reject the ENTRY (cold
                    # rebuild), never boot a shard serving amplified
                    # rows (FF_FAULT_QUANT_SCALE drills this)
                    bound = float(data[f"sbd/{op}"]) \
                        if f"sbd/{op}" in files else None
                    validate_scales(op, scales, bound)
                    blocks[op] = QuantTable.from_encoded(
                        np.array(data[k]), scales, dt)
                else:
                    blocks[op] = np.array(data[k])
            version = int(data["meta/version"])
            chain_crc = int(data["meta/chain_crc"])
        except Exception as e:   # noqa: BLE001 — torn npz, bad meta
            self._reject(f"{name}: {e}")
            self.misses += 1
            return None
        self.hits += 1
        return blocks, version, chain_crc

    # --- the tier-geometry meta sidecar --------------------------------
    # Everything a shard PROCESS (serve/shard_server.py) or a connect()-
    # built set needs that is NOT row blocks: per-op slot ranges, row
    # widths, per-table default rows, quant policies, fingerprint. One
    # JSON per shard count, next to the slot entries.

    def _meta_path(self, nshards: int) -> str:
        return os.path.join(self.directory,
                            f"shard-{nshards}x.meta.json")

    def put_meta(self, nshards: int, meta: Dict[str, Any]) -> bool:
        """Atomically persist the tier geometry (temp + fsync +
        os.replace). Best-effort, like :meth:`put`."""
        doc = dict(meta)
        if self.fingerprint:
            doc.setdefault("fingerprint", self.fingerprint)
        path = self._meta_path(nshards)
        tmp = f"{path}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception as e:   # noqa: BLE001 — full disk, perms
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self.put_errors += 1
            log_cache.warning("shard meta write failed (%s)", e)
            return False
        self.puts += 1
        return True

    def get_meta(self, nshards: int) -> Optional[Dict[str, Any]]:
        """The tier geometry, or None with the reason recorded (torn
        JSON, foreign fingerprint, wrong shard count — same
        reject-with-reason contract as :meth:`get`)."""
        path = self._meta_path(nshards)
        if not os.path.isfile(path):
            self.misses += 1
            return None
        name = os.path.basename(path)
        try:
            with open(path) as f:
                meta = json.load(f)
            if not isinstance(meta, dict):
                raise ValueError("meta is not a JSON object")
            if int(meta.get("nshards", nshards)) != nshards:
                raise ValueError(
                    f"meta is for {meta.get('nshards')} shard(s), "
                    f"wanted {nshards}")
            fp = str(meta.get("fingerprint", ""))
            if self.fingerprint and fp and fp != self.fingerprint:
                raise ValueError(
                    f"foreign fingerprint {fp} != {self.fingerprint} "
                    f"(differently-built model)")
        except Exception as e:   # noqa: BLE001 — torn/invalid JSON
            self._reject(f"{name}: {e}")
            self.misses += 1
            return None
        self.hits += 1
        return meta

    def stats(self) -> Dict[str, Any]:
        return {"hits": self.hits, "misses": self.misses,
                "rejects": self.rejects, "puts": self.puts,
                "put_errors": self.put_errors,
                "last_reject": self.last_reject}


# ---------------------------------------------------------------------
# directory convention
# ---------------------------------------------------------------------
def cache_dir_for(checkpoint_dir: Optional[str],
                  configured: str = "") -> Optional[str]:
    """Resolve the warm-cache directory from the config knob:

    - ``""`` (default) — caching OFF;
    - ``"auto"`` — ``<checkpoint_dir>/cache`` when a checkpoint dir is in
      play (the caches live next to the manifest), else off;
    - any other string — that path, verbatim.
    """
    if not configured:
        return None
    if configured == "auto":
        if not checkpoint_dir:
            return None
        from .checkpoint import CheckpointManager
        return os.path.join(os.path.abspath(checkpoint_dir),
                            CheckpointManager.CACHE_DIR)
    return os.path.abspath(configured)


def open_caches(checkpoint_dir: Optional[str], configured: str = ""
                ) -> Tuple[Optional[PlanCache], Optional[CompileCache]]:
    """(PlanCache, CompileCache) for the resolved directory, or (None,
    None) when caching is off. Never raises: an unusable directory logs
    and disables caching (cold behavior, not a dead job)."""
    d = cache_dir_for(checkpoint_dir, configured)
    if d is None:
        return None, None
    try:
        return PlanCache(d), CompileCache(d)
    except OSError as e:
        log_cache.warning("cannot open warm cache at %s (%s); running "
                          "cold", d, e)
        return None, None
