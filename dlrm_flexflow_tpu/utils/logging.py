"""Category logging channels.

Parity with the reference's Legion logger categories (reference:
`LegionRuntime::Logger::Category log_model` model.cc:22, `log_app`
dlrm.cc:22, `log_ff_mapper` mapper.cc:18, `log_nmt`; Python `fflogger`,
python/flexflow/core/flexflow_logger.py). Channels are stdlib loggers
under the ``ff.`` namespace; verbosity comes from ``$FF_LOG`` ("debug",
"info", "warning", default "warning") or per-channel
``$FF_LOG_<CHANNEL>``.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR,
           "spew": logging.DEBUG}

_configured = False


def _configure_root():
    global _configured
    if _configured:
        return
    root = logging.getLogger("ff")
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("[ff.%(name)s] %(levelname)s: %(message)s"))
    # logger name minus the "ff." prefix for compact channel tags
    class _Strip(logging.Filter):
        def filter(self, record):
            record.name = record.name.removeprefix("ff.")
            return True
    handler.addFilter(_Strip())
    root.addHandler(handler)
    root.propagate = False
    root.setLevel(_LEVELS.get(os.environ.get("FF_LOG", "warning").lower(),
                              logging.WARNING))
    _configured = True


def get_logger(channel: str) -> logging.Logger:
    """Channel logger, e.g. get_logger("model") ~ reference log_model."""
    _configure_root()
    lg = logging.getLogger(f"ff.{channel}")
    env = os.environ.get(f"FF_LOG_{channel.upper()}")
    if env:
        lg.setLevel(_LEVELS.get(env.lower(), logging.WARNING))
    return lg


# pre-declared channels mirroring the reference's categories
log_model = get_logger("model")
log_app = get_logger("app")
log_mapper = get_logger("mapper")
log_sim = get_logger("sim")
fflogger = get_logger("python")
