"""Checkpoint / resume.

The reference has NO checkpointing — only Parameter::set_weights/get_weights
host copies (reference: src/runtime/model.cu:260-334, exposed via
flexflow_c.h / flexflow_cbinding.py); strategy files are the only persisted
artifact. Per SURVEY.md §5.4 this module is a strict superset: full params +
optimizer state + step counter, saved as a single .npz (portable; arrays are
gathered to host, so checkpoints are host-memory-bound — for truly sharded
async multi-host snapshots wire `model.params` into orbax yourself; this
module deliberately has no orbax dependency).

Fault tolerance (the part long preemptible-pod runs actually need):

- every write is **atomic** — temp file in the target directory, fsync,
  ``os.replace`` — so a crash mid-save can never corrupt an existing
  snapshot (only ever leaves a ``*.tmp-<pid>`` orphan, which the manager
  sweeps);
- :class:`CheckpointManager` adds **rolling keep-last-K snapshots** with a
  JSON manifest per directory carrying step, a model/config fingerprint
  (op graph + param shapes + compute dtype, so fuse/lane-packing mismatches
  are caught before any shape error), a CRC-32 content checksum, and an
  opaque ``loader_state`` (``fit()`` stores its epoch/batch position there);
- saves can run on a **background thread** (`save_async`) so the hot loop
  never blocks on host file I/O — the device→host gather happens inline
  (it must, for consistency), the compression+write+rename+manifest update
  happen off-thread;
- restore scans the manifest **newest-first and skips corrupt, truncated,
  missing or foreign-fingerprint snapshots**, so a run killed mid-write
  resumes from the last valid one.

Fault-injection hooks from `utils.faults` are threaded through the write
path so tests exercise each branch (abort-mid-save, torn file, kill window).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from . import faults
from .logging import get_logger

log_ckpt = get_logger("checkpoint")


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat):
    tree = {}
    for k, v in flat.items():
        parts = k.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def _model_flat(model, copy_host: bool = False) -> Dict[str, np.ndarray]:
    """Flatten a model's full training state into npz-ready host arrays.

    `copy_host` deep-copies the host-resident tables: a background save
    thread writes while the training loop keeps scattering into them
    in-place, so the snapshot must own its bytes (device arrays already
    do — np.asarray gathers them to fresh host memory)."""
    if hasattr(model, "_host_drain"):
        model._host_drain()   # land any in-flight async host scatter
    flat: Dict[str, np.ndarray] = {}
    flat.update({f"params/{k}": v
                 for k, v in _flatten(model.params).items()})
    flat.update({f"opt/{k}": v
                 for k, v in _flatten(model.opt_state).items()})
    flat.update({f"state/{k}": v
                 for k, v in _flatten(model.op_state).items()})
    host = _flatten(getattr(model, "host_params", {}) or {})
    hostopt = _flatten(getattr(model, "host_opt_state", {}) or {})
    if copy_host:
        host = {k: np.array(v) for k, v in host.items()}
        hostopt = {k: np.array(v) for k, v in hostopt.items()}
    flat.update({f"hostparams/{k}": v for k, v in host.items()})
    flat.update({f"hostopt/{k}": v for k, v in hostopt.items()})
    flat["meta/step"] = np.asarray(model._step)
    # mesh provenance: arrays above are host-gathered (mesh-agnostic
    # bytes), but the WRITER's topology is recorded so a restore onto a
    # different mesh is an explicit decision (elastic mode), not an
    # accident silently inheriting stale parallelism assumptions
    mesh = getattr(model, "mesh", None)
    if mesh is not None:
        flat["meta/mesh_axes"] = np.asarray(
            [mesh.shape[a] for a in mesh.axis_names], np.int64)
        flat["meta/num_devices"] = np.asarray(mesh.size, np.int64)
    return flat


def mesh_meta(model) -> Dict[str, Any]:
    """Manifest-ready description of the mesh + per-op partition degrees
    a snapshot was written under (JSON-serializable)."""
    mesh = getattr(model, "mesh", None)
    meta: Dict[str, Any] = {}
    if mesh is not None:
        meta["axes"] = {a: int(mesh.shape[a]) for a in mesh.axis_names}
        meta["num_devices"] = int(mesh.size)
    strategies = getattr(model, "strategies", None) or {}
    meta["degrees"] = {name: list(map(int, pc.degrees))
                       for name, pc in strategies.items()}
    # PARAM-axis (row-shard) degrees, only where active — a reader can
    # tell a row-sharded snapshot's layout without loading the model
    pds = {name: int(getattr(pc, "param_degree", 1))
           for name, pc in strategies.items()
           if getattr(pc, "param_degree", 1) > 1}
    if pds:
        meta["param_degrees"] = pds
    # skew-aware placement policies, only where non-default (same
    # round-trip discipline as param_degrees: a hybrid snapshot's
    # hot/cold split is layout — hot_kernel shapes depend on it)
    hots = {name: float(getattr(pc, "hot_fraction", 0.0))
            for name, pc in strategies.items()
            if getattr(pc, "hot_fraction", 0.0) > 0.0}
    if hots:
        meta["hot_fractions"] = hots
    exch = {name: pc.exchange for name, pc in strategies.items()
            if getattr(pc, "exchange", "dense") != "dense"}
    if exch:
        meta["exchanges"] = exch
    # quantized-storage policies RESOLVED at compile (strategy override
    # OR --emb-dtype default), only where non-default — what shardcheck
    # FLX508 compares a strategy file against: a snapshot written under
    # int8 policy served by an fp32-planned deployment (or vice versa)
    # is a silent 4x byte-accounting lie
    quant = {name: {"dtype": pol.dtype, "update_rule": pol.update_rule}
             for name, pol in (getattr(model, "_quant_policies", {})
                               or {}).items()}
    if quant:
        meta["quant"] = quant
    return meta


def _write_npz_atomic(path: str, flat: Dict[str, np.ndarray]) -> int:
    """Write `flat` to `path` atomically; returns the file's CRC-32.

    Temp file lives in the SAME directory (os.replace must not cross
    filesystems); fsync before rename so the rename never publishes a
    file whose bytes are still in flight. A crash at ANY point leaves
    either the previous file or the complete new one at `path`."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
            f.flush()
            os.fsync(f.fileno())
        crc = _file_crc32(tmp)
        faults.maybe_abort_write(path)   # injected save crash (pre-rename)
        faults.maybe_delay_write()       # injected kill window
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if faults.maybe_truncate_file(path):   # injected torn write / bit rot
        pass
    return crc


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def config_fingerprint(model) -> str:
    """Short digest of everything a checkpoint must agree with the model
    on: the op graph (names+types), every parameter's shape (embedding
    lane-packing / fuse options change these), and the compute dtype.
    Stored per manifest entry; a mismatch means the snapshot was written
    by a differently-built model and is skipped on resume."""
    import hashlib

    desc: List[Any] = [str(np.dtype(model.compute_dtype))]
    desc.append(sorted((op.name, type(op).__name__) for op in model.ops))
    for attr in ("params", "host_params"):
        tree = getattr(model, attr, None) or {}
        desc.append(sorted(
            (k, tuple(np.asarray(v).shape) if not hasattr(v, "shape")
             else tuple(v.shape))
            for k, v in _flatten(tree).items()))
    blob = json.dumps(desc, sort_keys=True, default=str).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


def save_checkpoint(model, path: str):
    """Save params + optimizer state + step to `path` (.npz), atomically
    (temp file + os.replace — a crash mid-save never leaves a corrupt
    file at the final path)."""
    if not path.endswith(".npz"):
        path += ".npz"   # np.savez would have appended it anyway
    _write_npz_atomic(path, _model_flat(model))


def _check_mesh_meta(model, data, path: str, elastic: bool) -> None:
    """Reject-with-reason on a mesh mismatch (non-elastic restores)."""
    if "meta/num_devices" in data.files and model.mesh is not None:
        ck_ndev = int(data["meta/num_devices"])
        ck_axes = [int(x) for x in data["meta/mesh_axes"]] \
            if "meta/mesh_axes" in data.files else None
        cur_axes = [int(model.mesh.shape[a])
                    for a in model.mesh.axis_names]
        if not elastic and (ck_ndev != model.mesh.size
                            or (ck_axes is not None
                                and ck_axes != cur_axes)):
            raise ValueError(
                f"checkpoint {path} was written under a "
                f"{ck_ndev}-device mesh (axes {ck_axes}) but this model "
                f"is compiled for {model.mesh.size} devices (axes "
                f"{cur_axes}). Cross-mesh restore needs elastic mode: "
                f"set FFConfig.elastic='resume' (--elastic resume) or "
                f"pass restore_checkpoint(..., elastic=True) to reshard "
                f"the snapshot onto the current mesh.")


def _split_sections(data):
    """npz files -> the five per-section flat dicts."""
    params_flat, opt_flat, state_flat = {}, {}, {}
    host_flat, hostopt_flat = {}, {}
    for k in data.files:
        if k.startswith("params/"):
            params_flat[k[len("params/"):]] = data[k]
        elif k.startswith("opt/"):
            opt_flat[k[len("opt/"):]] = data[k]
        elif k.startswith("state/"):
            state_flat[k[len("state/"):]] = data[k]
        elif k.startswith("hostparams/"):
            host_flat[k[len("hostparams/"):]] = data[k]
        elif k.startswith("hostopt/"):
            hostopt_flat[k[len("hostopt/"):]] = data[k]
    return params_flat, opt_flat, state_flat, host_flat, hostopt_flat


def restore_checkpoint(model, path: str, elastic: Optional[bool] = None,
                       params_only: bool = False):
    """Restore into a compiled model, re-applying each parameter's GSPMD
    sharding.

    Snapshot arrays are host-gathered (full, unsharded), so the
    device_put below IS the reshard: loading a snapshot written under
    mesh A into a model compiled on mesh B re-splits every tensor per
    B's partition degrees (host-resident tables stay numpy and need no
    resharding at all). That cross-mesh load is only performed when
    `elastic` is True (default: ``model.config.elastic != "off"``);
    otherwise a mesh mismatch is rejected UP FRONT with the recorded
    topology in the message — never half-applied mid-load.

    ``params_only=True`` is the serving fast path: load params, host
    tables, and op state (inference needs e.g. batch-norm running
    stats) but SKIP the optimizer-state slabs — for big embedding
    models that halves the bytes read and device_put. The model's
    current opt_state is left untouched (resuming TRAINING from a
    params-only load silently reuses stale optimizer state — don't).
    All reject-with-reason checks (mesh above, per-op shape validation
    in the apply) run the same in both modes.
    """
    # the restore replaces host tables underneath any in-flight async
    # scatter / chained prefetch gather: land the scatter first, then
    # drop the (now stale) prefetched gather
    if hasattr(model, "_host_drain"):
        model._host_drain()
    if hasattr(model, "_host_prefetch_invalidate"):
        model._host_prefetch_invalidate()
    data = np.load(path if path.endswith(".npz") else path + ".npz")
    if elastic is None:
        elastic = getattr(getattr(model, "config", None), "elastic",
                          "off") != "off"
    _check_mesh_meta(model, data, path, elastic)
    (params_flat, opt_flat, state_flat,
     host_flat, hostopt_flat) = _split_sections(data)
    if params_only:
        opt_flat = hostopt_flat = None
    return _apply_flat_state(model, params_flat, opt_flat, state_flat,
                             host_flat, hostopt_flat,
                             int(data["meta/step"]), source=path)


def load_params_for_swap(model, path: str, elastic: bool = False):
    """Read a snapshot's inference state WITHOUT touching the model:
    validated + device_put against the model's compiled shardings, but
    returned instead of assigned. The serving hot-reload does the slow
    part (file read, validation, H2D) here — outside the engine's
    dispatch lock — then installs the result atomically between
    dispatches via ``FFModel.swap_params``. Optimizer state is never
    read (serving has none). Raises with a reason on mesh or per-op
    shape mismatch; the watcher logs it and keeps serving old weights.

    ``elastic=True`` permits a cross-mesh load — the snapshot's global
    arrays are resharded onto THIS model's compiled shardings. That is
    the serving-fleet topology (per-device replicas consuming a
    multi-device trainer's snapshots), so fleet replicas opt in via
    ``ServeConfig.reshard``; the default stays reject-with-reason.
    """
    data = np.load(path if path.endswith(".npz") else path + ".npz")
    _check_mesh_meta(model, data, path, elastic=elastic)
    params_flat, _, state_flat, host_flat, _ = _split_sections(data)
    params = _validated_params(model, params_flat, source=path)
    return {
        "params": params,
        "op_state": jax.tree.map(jax.device_put, _unflatten(state_flat)),
        "host_params": _unflatten(host_flat) if host_flat else None,
        "step": int(data["meta/step"]),
    }


def restore_from_flat(model, flat: Dict[str, np.ndarray],
                      source: str = "<memory>"):
    """Restore a `_model_flat` snapshot held in memory (no file round
    trip) — the elastic IN-PLACE reshard path: gather-to-host happened in
    `_model_flat`, the re-split onto the model's (new) mesh happens
    here via the per-parameter device_put."""
    parts = {"params/": {}, "opt/": {}, "state/": {},
             "hostparams/": {}, "hostopt/": {}}
    for k, v in flat.items():
        for prefix, d in parts.items():
            if k.startswith(prefix):
                d[k[len(prefix):]] = v
                break
    return _apply_flat_state(model, parts["params/"], parts["opt/"],
                             parts["state/"], parts["hostparams/"],
                             parts["hostopt/"],
                             int(flat["meta/step"]), source=source)


def _validated_params(model, params_flat, source: str):
    """Unflatten + validate + device_put a snapshot's params section
    against the model's compiled parameter spec, returning the sharded
    tree (nothing on the model is touched)."""
    params = _unflatten(params_flat)
    # validate against the model's parameter spec before overwriting
    # anything: a mismatch (e.g. a checkpoint from a per-table or
    # pre-lane-packing embedding layout) must fail HERE with a clear
    # message, not as an opaque shape error inside the next train step
    if model.params is not None:
        for opname, pdict in params.items():
            cur = model.params.get(opname)
            if cur is None:
                raise ValueError(
                    f"checkpoint has parameters for op {opname!r} which "
                    f"does not exist in this model (built with different "
                    f"fuse_embeddings / graph options?)")
            for n, v in pdict.items():
                if n in cur and tuple(cur[n].shape) != tuple(v.shape):
                    raise ValueError(
                        f"checkpoint param {opname}/{n} has shape "
                        f"{tuple(v.shape)} but the model expects "
                        f"{tuple(cur[n].shape)}. Embedding tables are "
                        f"stored lane-packed — rebuild the model with the "
                        f"options used when the checkpoint was written, "
                        f"or convert via the op's unpack_kernel/"
                        f"pack_kernel helpers.")
        # the inverse mismatch must be LOUD too: ops present in the model
        # but absent from the checkpoint keep their current (e.g. freshly
        # initialized) values — silent partial restores corrupt resumes
        missing = sorted(set(model.params) - set(params))
        if missing:
            log_ckpt.warning(
                "checkpoint %s has no parameters for %d model op(s) %s — "
                "these keep their CURRENT in-memory values (checkpoint "
                "written by a smaller/different graph?)",
                source, len(missing), missing)
    # re-shard parameters per compile-time shardings
    for opname, pdict in params.items():
        shards = model._param_sharding.get(opname, {})
        params[opname] = {
            n: jax.device_put(v, shards.get(n)) if shards.get(n) else
            jax.device_put(v)
            for n, v in pdict.items()}
    return params


def _apply_flat_state(model, params_flat, opt_flat, state_flat, host_flat,
                      hostopt_flat, step: int, source: str):
    """Install snapshot sections on the model. ``opt_flat`` /
    ``hostopt_flat`` of None mean "leave the model's current value
    untouched" (the params_only serving fast path)."""
    model.params = _validated_params(model, params_flat, source)
    if opt_flat is not None:
        model.opt_state = jax.tree.map(jax.device_put,
                                       _unflatten(opt_flat))
    model.op_state = jax.tree.map(jax.device_put, _unflatten(state_flat))
    if host_flat:
        # host-resident tables stay numpy on the host — no device_put
        model.host_params = _unflatten(host_flat)
    if hostopt_flat:
        model.host_opt_state = _unflatten(hostopt_flat)
    model._step = int(step)
    # the jitted step threads a device-resident step counter and metric
    # sums; drop them so the next step re-seeds from the restored _step
    # (a rollback that re-winds _step would otherwise keep training from
    # the stale device counter)
    model._step_dev = None
    model._msums = None
    return model


# ---------------------------------------------------------------------
# rolling checkpoints
# ---------------------------------------------------------------------
class CheckpointManager:
    """Atomic rolling checkpoints in a directory, with manifest + resume.

    Layout::

        <dir>/ckpt-00000042.npz     keep-last-K snapshot files
        <dir>/manifest.json         entries newest-last (atomic writes)

    `save`/`save_async` snapshot the model (device→host gather inline,
    host tables deep-copied), then write + rename + update the manifest —
    on a background thread for `save_async`, so training never blocks on
    file I/O. `restore_latest` walks entries newest-first and restores the
    first one whose file exists, passes its CRC-32, and matches the
    model's fingerprint — a run SIGKILLed mid-write (or a torn file
    injected by `utils.faults`) falls back to the previous snapshot.
    """

    MANIFEST = "manifest.json"
    # conventional home of the persistent warm caches (plan + compile,
    # utils/warmcache.py) — a SUBDIRECTORY next to the manifest, so the
    # snapshot and the executables/plans that can serve it travel
    # together, and the manager's tmp sweep / file GC (which only touch
    # top-level files) never race a cache writer
    CACHE_DIR = "cache"

    def __init__(self, directory: str, keep_last: int = 3):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1, got {keep_last}")
        self.directory = os.path.abspath(directory)
        self.keep_last = keep_last
        os.makedirs(self.directory, exist_ok=True)
        self._thread: Optional[threading.Thread] = None
        self._thread_exc: Optional[BaseException] = None
        from ..analysis.sanitizer import make_lock
        self._manifest_lock = make_lock("CheckpointManager._manifest_lock")
        self._sweep_orphan_tmps()

    @property
    def cache_dir(self) -> str:
        """Where ``--compile-cache-dir auto`` puts the warm caches for
        this checkpoint directory (the directory itself is created by
        the caches on first use, not here)."""
        return os.path.join(self.directory, self.CACHE_DIR)

    # --- manifest ------------------------------------------------------
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, self.MANIFEST)

    def _read_manifest(self) -> Dict[str, Any]:
        try:
            with open(self._manifest_path()) as f:
                m = json.load(f)
            if isinstance(m, dict) and isinstance(m.get("entries"), list):
                return m
        except FileNotFoundError:
            pass
        except (json.JSONDecodeError, OSError) as e:
            # a torn manifest must not kill resume: fall back to empty
            # (snapshot FILES stay on disk for manual recovery via
            # restore_checkpoint)
            log_ckpt.warning("unreadable manifest %s (%s); treating as "
                             "empty", self._manifest_path(), e)
        return {"version": 1, "entries": []}

    def _write_manifest(self, manifest: Dict[str, Any]) -> None:
        path = self._manifest_path()
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _sweep_orphan_tmps(self) -> None:
        for name in os.listdir(self.directory):
            if ".tmp-" in name:
                try:
                    os.unlink(os.path.join(self.directory, name))
                    log_ckpt.info("removed orphan temp file %s (crashed "
                                  "writer)", name)
                except OSError:
                    pass

    # --- save ----------------------------------------------------------
    def save(self, model, loader_state: Optional[Dict[str, Any]] = None):
        """Blocking snapshot of the model's current state."""
        self.wait()
        step = int(model._step)
        flat = _model_flat(model, copy_host=True)
        self._write_snapshot(flat, step, config_fingerprint(model),
                             dict(loader_state or {}), mesh_meta(model))

    def save_async(self, model,
                   loader_state: Optional[Dict[str, Any]] = None):
        """Snapshot now (device→host gather inline, for consistency),
        write on a background thread. Joins any previous in-flight save
        first — at most one writer; its errors re-raise here or at
        wait()."""
        self.wait()
        step = int(model._step)
        flat = _model_flat(model, copy_host=True)
        fp = config_fingerprint(model)
        state = dict(loader_state or {})
        mmeta = mesh_meta(model)

        def work():
            try:
                self._write_snapshot(flat, step, fp, state, mmeta)
            except BaseException as e:   # surfaced at wait()/next save
                self._thread_exc = e

        self._thread = threading.Thread(target=work, daemon=True,
                                        name="ff-ckpt-writer")
        self._thread.start()

    def wait(self) -> None:
        """Join the in-flight async save and re-raise its error, if any."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        exc = self._thread_exc
        if exc is not None:
            self._thread_exc = None
            raise exc

    def _write_snapshot(self, flat, step: int, fingerprint: str,
                        loader_state: Dict[str, Any],
                        mesh: Optional[Dict[str, Any]] = None
                        ) -> Dict[str, Any]:
        fname = f"ckpt-{step:08d}.npz"
        path = os.path.join(self.directory, fname)
        t0 = time.time()
        crc = _write_npz_atomic(path, flat)
        entry = {"file": fname, "step": step, "crc32": crc,
                 "fingerprint": fingerprint, "time": time.time(),
                 "loader_state": loader_state}
        if mesh:
            # mesh shape / device count / per-op partition degrees the
            # snapshot was written under — elastic recovery reads these
            # to decide whether a restore needs resharding, and the
            # non-elastic path uses them to reject-with-reason
            entry["mesh"] = mesh
        with self._manifest_lock:
            manifest = self._read_manifest()
            manifest["entries"] = [e for e in manifest["entries"]
                                   if e.get("file") != fname] + [entry]
            self._gc(manifest)
            self._write_manifest(manifest)
        log_ckpt.info("saved checkpoint %s (step %d, %.0f ms)",
                      fname, step, 1e3 * (time.time() - t0))
        return entry

    def _gc(self, manifest: Dict[str, Any]) -> None:
        """Keep the newest `keep_last` entries; delete the rest's files.
        Called under the manifest lock, BEFORE the manifest write — a
        crash between unlink and manifest write only loses already-
        superseded snapshots (the entry scan skips missing files).

        A snapshot a LIVE delta chain still references as its base is
        retained beyond keep_last — deleting it would strand every
        watcher that has not caught up past the base (the chain's
        incremental loads and its full-reload fallback both die with
        it). It falls out of the manifest on the next chain reset."""
        entries = manifest["entries"]
        entries.sort(key=lambda e: e.get("step", -1))
        drop, keep = entries[:-self.keep_last], entries[-self.keep_last:]
        chained = {d.get("base_file") for d in manifest.get("deltas", [])}
        chained.discard(None)
        spared = [e for e in drop if e.get("file") in chained]
        drop = [e for e in drop if e.get("file") not in chained]
        for e in drop:
            try:
                os.unlink(os.path.join(self.directory, e["file"]))
            except OSError:
                pass
        manifest["entries"] = sorted(spared + keep,
                                     key=lambda e: e.get("step", -1))

    def set_manifest_extra(self, key: str, value: Any) -> None:
        """Set one top-level manifest key (atomic read-modify-replace
        under the manifest lock) — sidecar pointers like the id-
        frequency histogram ride the manifest without touching the
        entries/deltas machinery. Reserved keys are refused."""
        if key in ("entries", "deltas"):
            raise ValueError(f"manifest key {key!r} is reserved")
        with self._manifest_lock:
            manifest = self._read_manifest()
            manifest[key] = value
            self._write_manifest(manifest)

    # --- delta chain (utils/delta.py DeltaPublisher) -------------------
    def delta_entries(self) -> List[Dict[str, Any]]:
        with self._manifest_lock:
            return list(self._read_manifest().get("deltas", []))

    def append_delta_entry(self, entry: Dict[str, Any]) -> None:
        """Append one delta entry to the chain manifest (atomic
        read-modify-replace under the manifest lock). The delta FILE
        must already be on disk — a crash between the two leaves an
        unlisted file, never a listed-but-missing one."""
        with self._manifest_lock:
            manifest = self._read_manifest()
            deltas = manifest.setdefault("deltas", [])
            manifest["deltas"] = [e for e in deltas
                                  if e.get("file") != entry.get("file")] \
                + [entry]
            self._write_manifest(manifest)

    def reset_deltas(self) -> int:
        """Retire the delta chain: drop every delta entry from the
        manifest, then delete the files (in that order — a crash in
        between leaves harmless orphan files, never dangling entries).
        Returns how many entries were retired."""
        with self._manifest_lock:
            manifest = self._read_manifest()
            retired = list(manifest.get("deltas", []))
            if retired:
                manifest["deltas"] = []
                self._write_manifest(manifest)
        for e in retired:
            try:
                os.unlink(os.path.join(self.directory, e.get("file", "")))
            except OSError:
                pass
        return len(retired)

    # --- restore -------------------------------------------------------
    def entries(self) -> List[Dict[str, Any]]:
        with self._manifest_lock:
            return list(self._read_manifest()["entries"])

    def _entry_valid(self, entry: Dict[str, Any],
                     fingerprint: Optional[str]) -> bool:
        path = os.path.join(self.directory, entry.get("file", ""))
        if not os.path.isfile(path):
            log_ckpt.warning("checkpoint %s listed in manifest but "
                             "missing on disk; skipping", entry.get("file"))
            return False
        if (fingerprint is not None
                and entry.get("fingerprint") not in (None, fingerprint)):
            log_ckpt.warning(
                "checkpoint %s was written by a differently-built model "
                "(fingerprint %s != %s); skipping", entry["file"],
                entry.get("fingerprint"), fingerprint)
            return False
        crc = entry.get("crc32")
        if crc is not None and _file_crc32(path) != crc:
            log_ckpt.warning("checkpoint %s fails its checksum (torn "
                             "write / corruption); skipping", entry["file"])
            return False
        return True

    def latest_valid(self, fingerprint: Optional[str] = None
                     ) -> Optional[Dict[str, Any]]:
        """Newest manifest entry that exists, checksums clean, and (when
        given) matches `fingerprint`; None when no snapshot survives."""
        for entry in reversed(self.entries()):
            if self._entry_valid(entry, fingerprint):
                return entry
        return None

    def restore_latest(self, model) -> Optional[Dict[str, Any]]:
        """Restore the newest valid snapshot into `model`; returns its
        manifest entry (step, loader_state, ...) or None when the
        directory holds nothing restorable."""
        fp = config_fingerprint(model)
        for entry in reversed(self.entries()):
            if not self._entry_valid(entry, fp):
                continue
            path = os.path.join(self.directory, entry["file"])
            try:
                restore_checkpoint(model, path)
            except (ValueError, KeyError, OSError, zlib.error) as e:
                # checksum passed but the content disagrees with this
                # model (or the zip is unreadable) — keep walking back
                log_ckpt.warning("checkpoint %s did not restore (%s); "
                                 "trying an older snapshot",
                                 entry["file"], e)
                continue
            log_ckpt.info("resumed from %s (step %d)", entry["file"],
                          entry["step"])
            return entry
        return None


def get_weights(model, op_name: str):
    """Parameter::get_weights parity (reference model.cu:300-334)."""
    return {k: np.asarray(v) for k, v in model.params[op_name].items()}


def set_weights(model, op_name: str, weights):
    """Parameter::set_weights parity (reference model.cu:260-298): host
    buffers -> sharded device arrays."""
    shards = model._param_sharding.get(op_name, {})
    cur = model.params[op_name]
    for k, v in weights.items():
        if k not in cur:
            raise KeyError(f"{op_name} has no parameter {k}")
        if tuple(v.shape) != tuple(cur[k].shape):
            raise ValueError(f"{op_name}.{k}: shape {v.shape} != "
                             f"{tuple(cur[k].shape)}")
        model.params[op_name][k] = jax.device_put(
            np.asarray(v, dtype=np.asarray(cur[k]).dtype), shards.get(k))
