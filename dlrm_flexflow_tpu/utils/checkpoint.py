"""Checkpoint / resume.

The reference has NO checkpointing — only Parameter::set_weights/get_weights
host copies (reference: src/runtime/model.cu:260-334, exposed via
flexflow_c.h / flexflow_cbinding.py); strategy files are the only persisted
artifact. Per SURVEY.md §5.4 this module is a strict superset: full params +
optimizer state + step counter, saved as a single .npz (portable; arrays are
gathered to host, so checkpoints are host-memory-bound — for truly sharded
async multi-host snapshots wire `model.params` into orbax yourself; this
module deliberately has no orbax dependency).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

import jax


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten(flat):
    tree = {}
    for k, v in flat.items():
        parts = k.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def save_checkpoint(model, path: str):
    """Save params + optimizer state + step to `path` (.npz)."""
    if hasattr(model, "_host_drain"):
        model._host_drain()   # land any in-flight async host scatter
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    flat = {}
    flat.update({f"params/{k}": v
                 for k, v in _flatten(model.params).items()})
    flat.update({f"opt/{k}": v
                 for k, v in _flatten(model.opt_state).items()})
    flat.update({f"state/{k}": v
                 for k, v in _flatten(model.op_state).items()})
    flat.update({f"hostparams/{k}": v
                 for k, v in _flatten(
                     getattr(model, "host_params", {}) or {}).items()})
    flat.update({f"hostopt/{k}": v
                 for k, v in _flatten(
                     getattr(model, "host_opt_state", {}) or {}).items()})
    flat["meta/step"] = np.asarray(model._step)
    np.savez(path, **flat)


def restore_checkpoint(model, path: str):
    """Restore into a compiled model, re-applying each parameter's GSPMD
    sharding."""
    data = np.load(path if path.endswith(".npz") else path + ".npz")
    params_flat, opt_flat, state_flat = {}, {}, {}
    host_flat, hostopt_flat = {}, {}
    for k in data.files:
        if k.startswith("params/"):
            params_flat[k[len("params/"):]] = data[k]
        elif k.startswith("opt/"):
            opt_flat[k[len("opt/"):]] = data[k]
        elif k.startswith("state/"):
            state_flat[k[len("state/"):]] = data[k]
        elif k.startswith("hostparams/"):
            host_flat[k[len("hostparams/"):]] = data[k]
        elif k.startswith("hostopt/"):
            hostopt_flat[k[len("hostopt/"):]] = data[k]
    params = _unflatten(params_flat)
    # validate against the model's parameter spec before overwriting
    # anything: a mismatch (e.g. a checkpoint from a per-table or
    # pre-lane-packing embedding layout) must fail HERE with a clear
    # message, not as an opaque shape error inside the next train step
    if model.params is not None:
        for opname, pdict in params.items():
            cur = model.params.get(opname)
            if cur is None:
                raise ValueError(
                    f"checkpoint has parameters for op {opname!r} which "
                    f"does not exist in this model (built with different "
                    f"fuse_embeddings / graph options?)")
            for n, v in pdict.items():
                if n in cur and tuple(cur[n].shape) != tuple(v.shape):
                    raise ValueError(
                        f"checkpoint param {opname}/{n} has shape "
                        f"{tuple(v.shape)} but the model expects "
                        f"{tuple(cur[n].shape)}. Embedding tables are "
                        f"stored lane-packed — rebuild the model with the "
                        f"options used when the checkpoint was written, "
                        f"or convert via the op's unpack_kernel/"
                        f"pack_kernel helpers.")
    # re-shard parameters per compile-time shardings
    for opname, pdict in params.items():
        shards = model._param_sharding.get(opname, {})
        params[opname] = {
            n: jax.device_put(v, shards.get(n)) if shards.get(n) else
            jax.device_put(v)
            for n, v in pdict.items()}
    model.params = params
    model.opt_state = jax.tree.map(jax.device_put, _unflatten(opt_flat))
    model.op_state = jax.tree.map(jax.device_put, _unflatten(state_flat))
    if host_flat:
        # host-resident tables stay numpy on the host — no device_put
        model.host_params = _unflatten(host_flat)
    if hostopt_flat:
        model.host_opt_state = _unflatten(hostopt_flat)
    model._step = int(data["meta/step"])
    return model


def get_weights(model, op_name: str):
    """Parameter::get_weights parity (reference model.cu:300-334)."""
    return {k: np.asarray(v) for k, v in model.params[op_name].items()}


def set_weights(model, op_name: str, weights):
    """Parameter::set_weights parity (reference model.cu:260-298): host
    buffers -> sharded device arrays."""
    shards = model._param_sharding.get(op_name, {})
    cur = model.params[op_name]
    for k, v in weights.items():
        if k not in cur:
            raise KeyError(f"{op_name} has no parameter {k}")
        if tuple(v.shape) != tuple(cur[k].shape):
            raise ValueError(f"{op_name}.{k}: shape {v.shape} != "
                             f"{tuple(cur[k].shape)}")
        model.params[op_name][k] = jax.device_put(
            np.asarray(v, dtype=np.asarray(cur[k]).dtype), shards.get(k))
