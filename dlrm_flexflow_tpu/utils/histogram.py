"""Id-frequency sketches for skew-aware embedding placement.

Real recommendation traffic is zipfian — a handful of hot ids dominate
lookups (FAE, Adnan et al. 2021; Neo/ZionEX, Mudigere et al. 2022). The
strategy search can only exploit that structurally (dedup-before-
exchange, hot/cold hybrid placement — parallel/alltoall.py) if it knows
the distribution, so a lightweight :class:`IdFrequencySketch` is
collected per embedding op at STAGING time (next to PR 10's touched-row
tracking: the observe() runs on the prefetch/staging thread, cheap
numpy, never in the jitted step) and flows to three consumers:

- the cost model: ``expected_distinct(n)`` prices the dedup'd exchange
  (bytes scale with distinct ids, not batch size) and ``hot_mass(H)``
  prices the hybrid placement's hot-hit rate;
- the checkpoint manifest: ``save_histograms`` persists a sidecar
  ``id_histogram.npz`` next to the snapshots so a later search (or a
  serving fleet) can reuse the observed distribution;
- serving: ``EmbeddingCache`` pre-warms from the persisted sketch
  (``--serve-cache-warm``), so a fresh replica starts with the hot
  working set already cached.

The sketch is exact counts over the op's FLAT lookup-id space (table
offset + row, the same space ``op.flat_lookup_ids`` maps batches into)
up to ``max_buckets`` rows; larger id spaces fold modulo the bucket
count — an approximation that preserves the head of a zipfian
distribution (hot ids are the low-numbered ones after the standard
frequency-ordered renumbering) while bounding memory.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

# beyond this many distinct ids the sketch folds (keeps memory ~8 MB per
# million tracked rows; DLRM-Terabyte's 40M-row tables fold 40x)
DEFAULT_MAX_BUCKETS = 1 << 20


class IdFrequencySketch:
    """Bounded exact-count histogram over one op's flat lookup-id space.

    NOT thread-safe by itself; the collector (``TouchedRowTracker``)
    serializes observe() on its own lock.
    """

    def __init__(self, rows: int, max_buckets: int = DEFAULT_MAX_BUCKETS,
                 counts: Optional[np.ndarray] = None, total: int = 0):
        self.rows = int(rows)
        self.buckets = min(self.rows, int(max_buckets))
        if self.buckets < 1:
            raise ValueError(f"sketch needs >= 1 row, got {rows}")
        self.counts = (np.zeros(self.buckets, np.int64) if counts is None
                       else np.asarray(counts, np.int64))
        if self.counts.shape != (self.buckets,):
            raise ValueError(
                f"counts shape {self.counts.shape} != ({self.buckets},)")
        self.total = int(total)

    @property
    def folded(self) -> bool:
        return self.buckets < self.rows

    def observe(self, flat_ids: np.ndarray) -> None:
        """Count one batch's flat lookup ids (any shape, wraps mod rows)."""
        f = np.asarray(flat_ids).reshape(-1).astype(np.int64) % self.rows
        if self.folded:
            f = f % self.buckets
        self.counts += np.bincount(f, minlength=self.buckets)
        self.total += int(f.size)

    def merge(self, other: "IdFrequencySketch") -> None:
        if (other.rows, other.buckets) != (self.rows, self.buckets):
            raise ValueError(
                f"cannot merge sketch over {other.rows}/{other.buckets} "
                f"into {self.rows}/{self.buckets}")
        self.counts += other.counts
        self.total += other.total

    def copy(self) -> "IdFrequencySketch":
        """Deep copy (the re-placement controller snapshots live
        sketches as the new search baseline at swap time)."""
        return IdFrequencySketch(self.rows, max_buckets=self.buckets,
                                 counts=self.counts.copy(),
                                 total=self.total)

    def reset(self) -> None:
        """Zero the observations in place (the live sketch rebases after
        an online re-placement so the drift gauge measures divergence
        from the NEW placement's baseline, not history)."""
        self.counts[:] = 0
        self.total = 0

    def _folded_probs(self, buckets: int) -> np.ndarray:
        """probs() folded down to ``buckets`` entries (mod fold, the
        same aliasing observe() applies) so two sketches over the same
        row space but different bucket budgets stay comparable."""
        p = self.probs()
        if p.size == buckets:
            return p
        if p.size < buckets or buckets < 1:
            raise ValueError(
                f"cannot fold {p.size} buckets down to {buckets}")
        idx = np.arange(p.size, dtype=np.int64) % buckets
        return np.bincount(idx, weights=p, minlength=buckets)

    def divergence(self, other: "IdFrequencySketch") -> float:
        """Total-variation distance between the two empirical
        distributions, in [0, 1] — THE online re-placement trigger: the
        live sketch diverging from the histogram the placement was
        searched with means the hot set moved. Zero while either side is
        unobserved (no evidence of drift is not drift: an empty live
        sketch reads uniform, and uniform-vs-zipf would otherwise fire
        the trigger before the first batch lands). Mismatched bucket
        budgets compare at the coarser fold; mismatched row spaces are
        structurally different ops and refuse."""
        if self.rows != other.rows:
            raise ValueError(
                f"cannot compare sketch over {self.rows} rows with one "
                f"over {other.rows}")
        if self.total <= 0 or other.total <= 0:
            return 0.0
        m = min(self.buckets, other.buckets)
        p = self._folded_probs(m)
        q = other._folded_probs(m)
        return float(0.5 * np.abs(p - q).sum())

    # --- the two quantities the cost model consumes --------------------
    def probs(self) -> np.ndarray:
        """Per-bucket empirical probabilities (uniform when unobserved —
        the structural default under which dedup ~= dense and hybrid
        never looks attractive, exactly right for unknown traffic)."""
        if self.total <= 0:
            return np.full(self.buckets, 1.0 / self.rows)
        return self.counts / float(self.total)

    def _hot_mask(self, hot_rows_per_table: int,
                  rows_per_table: Optional[int]) -> Optional[np.ndarray]:
        """Bucket mask of the hybrid placement's HOT set (within-table
        row < hot_rows_per_table), or None when no hot set applies."""
        h = int(hot_rows_per_table)
        if h <= 0:
            return None
        rpt = int(rows_per_table or self.rows)
        ids = np.arange(self.buckets, dtype=np.int64)
        return (ids % min(rpt, self.buckets)) < h

    def expected_distinct(self, n_draws: float,
                          hot_rows_per_table: int = 0,
                          rows_per_table: Optional[int] = None) -> float:
        """E[# distinct COLD ids among n iid draws] =
        sum_{i cold} 1 - (1 - p_i)^n.

        THE dedup quantity: the routed exchange carries one slot per
        distinct id, so its expected bytes scale with this, not with n.
        `hot_rows_per_table` excludes the hybrid placement's replicated
        head (those lookups never route at all). Computed with
        log1p/expm1 so million-row tails stay stable. Folded sketches
        under-count distinct ids (aliased rows merge) — the
        conservative direction would overprice dedup's win, so the
        estimate is clamped to at most n."""
        n = float(n_draws)
        if n <= 0:
            return 0.0
        hot = self._hot_mask(hot_rows_per_table, rows_per_table)
        if self.total <= 0:
            # uniform closed form over the true row count
            cold = self.rows
            if hot is not None:
                rpt = int(rows_per_table or self.rows)
                tables = max(self.rows // max(rpt, 1), 1)
                cold = self.rows - tables * int(hot_rows_per_table)
            per = -np.expm1(n * np.log1p(-1.0 / self.rows))
            return float(min(max(cold, 0) * per, n))
        p = self.probs()
        if hot is not None:
            p = np.where(hot, 0.0, p)
        nz = p[p > 0]
        e = float(np.sum(-np.expm1(n * np.log1p(-np.minimum(nz,
                                                            1.0 - 1e-12)))))
        return min(e, n)

    def hot_mass(self, hot_rows_per_table: int, rows_per_table: int,
                 tables: int = 1) -> float:
        """Probability mass of the HOT set: flat ids whose within-table
        row (id % rows_per_table) falls below ``hot_rows_per_table`` —
        the rows the hybrid placement actually replicates (the
        low-numbered ids; zipf generators and frequency-ordered
        preprocessed datasets put the hot ids there)."""
        h = int(hot_rows_per_table)
        if h <= 0:
            return 0.0
        if h >= rows_per_table:
            return 1.0
        ids = np.arange(self.buckets, dtype=np.int64)
        hot = (ids % rows_per_table) < h
        if self.total <= 0:
            return float(h) / float(rows_per_table)
        if self.folded:
            # folding aliases within-table positions only when the
            # bucket count is not a multiple of rows_per_table; the mask
            # over folded ids is the best available estimate
            hot = (ids % min(rows_per_table, self.buckets)) < h
        return float(self.counts[hot].sum()) / float(self.total)

    # --- serving / tests -----------------------------------------------
    def sample_range(self, rng: np.random.RandomState,
                     lo: int, hi: int, size) -> np.ndarray:
        """Draw table-LOCAL row ids in [0, hi-lo) from the observed
        distribution of the flat-id slice [lo, hi) — one table's range
        (the serving cache pre-warm builds likely request index tuples
        from these). Folded sketches whose fold cuts through the slice
        (and unobserved sketches) draw uniform."""
        lo, hi = int(lo), int(hi)
        span = max(hi - lo, 1)
        n = int(np.prod(size))
        c = None
        if self.total > 0 and hi <= self.buckets:
            c = self.counts[lo:hi].astype(np.float64)
            if c.sum() <= 0:
                c = None
        if c is None:
            return rng.randint(0, span, size=size).astype(np.int64)
        cdf = np.cumsum(c)
        cdf /= cdf[-1]
        out = np.searchsorted(cdf, rng.random_sample(n), side="right")
        return out.reshape(size).astype(np.int64)

    def sample(self, rng: np.random.RandomState, size) -> np.ndarray:
        """Draw flat ids from the empirical distribution (inverse CDF) —
        the serving cache pre-warm and the calibration harness use this.
        Unobserved sketches draw uniform."""
        n = int(np.prod(size))
        if self.total <= 0:
            out = rng.randint(0, self.rows, size=n)
        else:
            cdf = np.cumsum(self.counts.astype(np.float64))
            cdf /= cdf[-1]
            out = np.searchsorted(cdf, rng.random_sample(n), side="right")
        return out.reshape(size).astype(np.int64)


# --- persistence (the checkpoint-manifest sidecar) ------------------------

HISTOGRAM_FILE = "id_histogram.npz"


def save_histograms(path: str, sketches: Dict[str, IdFrequencySketch]
                    ) -> None:
    """Atomic npz of {op name -> sketch} (same temp+os.replace
    discipline as every other published artifact)."""
    import os
    flat: Dict[str, np.ndarray] = {}
    for name, sk in sketches.items():
        flat[f"{name}/counts"] = sk.counts
        flat[f"{name}/meta"] = np.asarray([sk.rows, sk.buckets, sk.total],
                                          np.int64)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def sketch_signature(sketches: Optional[Dict[str, IdFrequencySketch]]
                     ) -> str:
    """Short stable digest of a {op -> sketch} mapping, for plan-cache
    keys: a placement searched against drifted traffic must not collide
    with the pre-drift entry (same graph, topology, budget, and
    warm-start — only the observed distribution moved)."""
    import zlib
    if not sketches:
        return "none"
    crc = 0
    for name in sorted(sketches):
        sk = sketches[name]
        head = np.asarray([sk.rows, sk.buckets, sk.total], np.int64)
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(head.tobytes(), crc)
        crc = zlib.crc32(np.ascontiguousarray(sk.counts).tobytes(), crc)
    return f"{crc:08x}"


def load_histograms(path: str) -> Dict[str, IdFrequencySketch]:
    out: Dict[str, IdFrequencySketch] = {}
    with np.load(path) as data:
        for key in data.files:
            if not key.endswith("/meta"):
                continue
            name = key[:-len("/meta")]
            rows, buckets, total = (int(x) for x in data[key])
            out[name] = IdFrequencySketch(
                rows, max_buckets=buckets,
                counts=data[f"{name}/counts"], total=total)
    return out
