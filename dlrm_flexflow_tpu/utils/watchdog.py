"""Worker liveness watchdogs: structured stall detection for background
threads.

The training stack runs three kinds of background workers — the prefetch
ring's staging thread (``ff-prefetch-N``), the async host-table scatter
worker (``ff-scatter``), and the checkpoint writer (``ff-ckpt-writer``).
A wedged worker (device hang, filesystem stall, a stuck collective inside
a staged ``device_put``) previously surfaced as a silent hang: the
consumer blocked forever in ``Condition.wait``/``Thread.join``.

This module gives every wait a deadline and a typed failure:

- :class:`StallReport` — structured description of WHICH worker stalled,
  what the consumer was waiting for, and for how long (the README's
  troubleshooting table is keyed off these fields);
- :class:`WorkerStalled` — the typed error carrying the report. The
  elastic recovery layer (``parallel/elastic.py`` + ``fit(--elastic)``)
  catches it and recovers (abandon the wedged worker, restore the last
  good snapshot, rebuild the pipeline) instead of hanging.

Deadlines come from ``FFConfig.worker_deadline_s`` (``--worker-deadline``,
0 disables — blocking waits, the pre-elastic behavior).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class StallReport:
    """What a watchdog saw when its deadline expired."""

    worker: str          # thread name: ff-prefetch-0, ff-scatter, ...
    waiting_for: str     # what the consumer needed from it
    waited_s: float      # how long the consumer actually waited
    deadline_s: float    # the configured liveness deadline
    detail: str = ""     # worker-specific context (ring depth, step, ...)
    alive: bool = True   # False = the thread died rather than wedged

    def __str__(self) -> str:
        state = "alive but unresponsive" if self.alive else "dead"
        s = (f"worker {self.worker!r} ({state}) missed its "
             f"{self.deadline_s:.3g}s liveness deadline: waited "
             f"{self.waited_s:.3g}s for {self.waiting_for}")
        if self.detail:
            s += f" [{self.detail}]"
        return s


class WorkerStalled(RuntimeError):
    """A background worker missed its liveness deadline.

    Raised at the consumer's wait site (never from the worker thread), so
    the training loop sees it at a step boundary where recovery is
    possible. ``report`` carries the structured :class:`StallReport`.

    Construction also lands the report in the observability layer (a
    trace instant + an ``ff_stalls_total`` counter labeled by worker) —
    deliberately HERE, at the one choke point every stall passes
    through, so a wedged subsystem is visible in the trace ring and the
    scrape even when the thread that would have reported it never runs
    again. No-ops when ``--obs off``.
    """

    def __init__(self, report: StallReport):
        super().__init__(str(report))
        self.report = report
        from ..obs import metrics as _obsm
        from ..obs import trace as _obstrace
        _obsm.counter(
            "ff_stalls_total",
            "worker stalls / missed deadlines by worker name",
            labelnames=("worker",)).inc(worker=report.worker)
        _obstrace.instant("stall", cat="watchdog",
                          worker=report.worker,
                          waiting_for=report.waiting_for,
                          waited_s=round(report.waited_s, 4),
                          alive=report.alive)


class Heartbeat:
    """Last-sign-of-life timestamp for a long-lived worker.

    The worker calls :meth:`beat` each time around its loop; a monitor
    on another thread reads :meth:`age` and, past a deadline, builds the
    same structured :class:`StallReport` the join watchdogs raise. Used
    by the serving fleet: each engine's batcher beats per iteration, and
    the router's health thread ejects a replica whose heartbeat goes
    stale (a wedged dispatch — device hang, runaway host gather) even
    when no request has errored yet. A bare float store/load is atomic
    under the GIL, so neither side takes a lock.
    """

    __slots__ = ("name", "_t")

    def __init__(self, name: str):
        self.name = name
        self._t = time.monotonic()

    def beat(self) -> None:
        self._t = time.monotonic()

    def age(self) -> float:
        """Seconds since the last beat."""
        return time.monotonic() - self._t

    def report(self, deadline_s: float, waiting_for: str,
               detail: str = "", alive: bool = True) -> StallReport:
        """StallReport for a monitor that found this heartbeat stale."""
        return StallReport(worker=self.name, waiting_for=waiting_for,
                          waited_s=self.age(), deadline_s=deadline_s,
                          detail=detail, alive=alive)


class Sustained:
    """Consecutive-observation debouncer for policy loops.

    An SLO breach (or an idle fleet) must persist for N consecutive
    evaluation periods before a scaling action fires — one slow dispatch
    or one quiet tick must not flap the fleet. ``observe(breach)``
    returns True once the condition has held for ``periods``
    observations in a row; any non-breach observation resets the count.
    Used by the serving autoscaler (``serve/autoscale.py``) for both its
    grow and shrink triggers. Single-threaded by design (one policy
    loop owns each instance)."""

    __slots__ = ("periods", "count")

    def __init__(self, periods: int):
        if periods < 1:
            raise ValueError(f"periods must be >= 1, got {periods}")
        self.periods = int(periods)
        self.count = 0

    def observe(self, breach: bool) -> bool:
        self.count = self.count + 1 if breach else 0
        return self.count >= self.periods

    def reset(self) -> None:
        self.count = 0


@dataclass
class Deadline:
    """A wall-clock budget shared by the serving path's per-request
    timeouts and the worker-join watchdogs: construct when the wait
    begins, poll :meth:`expired`, and hand :meth:`report` the structured
    description the typed error carries.

    ``seconds <= 0`` means no deadline (never expires) — the same
    convention as ``FFConfig.worker_deadline_s``.
    """

    seconds: float
    t0: float = field(default_factory=time.monotonic)

    def elapsed(self) -> float:
        return time.monotonic() - self.t0

    def remaining(self) -> float:
        """Seconds left (``inf`` when no deadline is configured)."""
        if self.seconds <= 0:
            return float("inf")
        return self.seconds - self.elapsed()

    def expired(self) -> bool:
        return self.seconds > 0 and self.remaining() <= 0

    def report(self, worker: str, waiting_for: str, detail: str = "",
               alive: bool = True) -> StallReport:
        """StallReport snapshot of this deadline's state."""
        return StallReport(worker=worker, waiting_for=waiting_for,
                          waited_s=self.elapsed(),
                          deadline_s=self.seconds, detail=detail,
                          alive=alive)
