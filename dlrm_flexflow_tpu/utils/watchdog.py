"""Worker liveness watchdogs: structured stall detection for background
threads.

The training stack runs three kinds of background workers — the prefetch
ring's staging thread (``ff-prefetch-N``), the async host-table scatter
worker (``ff-scatter``), and the checkpoint writer (``ff-ckpt-writer``).
A wedged worker (device hang, filesystem stall, a stuck collective inside
a staged ``device_put``) previously surfaced as a silent hang: the
consumer blocked forever in ``Condition.wait``/``Thread.join``.

This module gives every wait a deadline and a typed failure:

- :class:`StallReport` — structured description of WHICH worker stalled,
  what the consumer was waiting for, and for how long (the README's
  troubleshooting table is keyed off these fields);
- :class:`WorkerStalled` — the typed error carrying the report. The
  elastic recovery layer (``parallel/elastic.py`` + ``fit(--elastic)``)
  catches it and recovers (abandon the wedged worker, restore the last
  good snapshot, rebuild the pipeline) instead of hanging.

Deadlines come from ``FFConfig.worker_deadline_s`` (``--worker-deadline``,
0 disables — blocking waits, the pre-elastic behavior).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class StallReport:
    """What a watchdog saw when its deadline expired."""

    worker: str          # thread name: ff-prefetch-0, ff-scatter, ...
    waiting_for: str     # what the consumer needed from it
    waited_s: float      # how long the consumer actually waited
    deadline_s: float    # the configured liveness deadline
    detail: str = ""     # worker-specific context (ring depth, step, ...)
    alive: bool = True   # False = the thread died rather than wedged

    def __str__(self) -> str:
        state = "alive but unresponsive" if self.alive else "dead"
        s = (f"worker {self.worker!r} ({state}) missed its "
             f"{self.deadline_s:.3g}s liveness deadline: waited "
             f"{self.waited_s:.3g}s for {self.waiting_for}")
        if self.detail:
            s += f" [{self.detail}]"
        return s


class WorkerStalled(RuntimeError):
    """A background worker missed its liveness deadline.

    Raised at the consumer's wait site (never from the worker thread), so
    the training loop sees it at a step boundary where recovery is
    possible. ``report`` carries the structured :class:`StallReport`.
    """

    def __init__(self, report: StallReport):
        super().__init__(str(report))
        self.report = report
