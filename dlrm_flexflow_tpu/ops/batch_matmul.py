"""BatchMatmul (3-D) operator — the DLRM "dot" feature-interaction workhorse.

Parity with the reference BatchMatmul (reference: src/ops/batch_matmul.cu,
544 LoC — `cublasSgemmStridedBatched` forward and both gradients,
batch_matmul.cu:199,349-355). The reference's default contraction computes
C = A^T * B with layouts (d,k,m) × (d,k,n) → (d,m,n) (model.h:1350).

TPU-native: one `lax.dot_general` with batch dims — lands directly on the
MXU as a batched matmul; both grads come from jax.grad as dot_generals too.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from ..core.op import Op
from ..parallel.pconfig import ParallelConfig


class BatchMatmul(Op):
    type_name = "BatchMatmul"

    def __init__(self, model, a, b, trans_a: bool = True,
                 trans_b: bool = False, name: Optional[str] = None):
        """Default (trans_a=True, trans_b=False) reproduces the reference
        semantics: a (d,k,m), b (d,k,n) -> out (d,m,n)."""
        super().__init__(model, [a, b], name)
        if a.num_dims != 3 or b.num_dims != 3:
            raise ValueError("BatchMatmul expects rank-3 inputs")
        if a.shape[0] != b.shape[0]:
            raise ValueError("batch dim mismatch")
        self.trans_a, self.trans_b = bool(trans_a), bool(trans_b)
        d = a.shape[0]
        m = a.shape[2] if trans_a else a.shape[1]
        ka = a.shape[1] if trans_a else a.shape[2]
        kb = b.shape[2] if trans_b else b.shape[1]
        n = b.shape[1] if trans_b else b.shape[2]
        if ka != kb:
            raise ValueError(f"contraction dim mismatch {ka} vs {kb}")
        self.m, self.n, self.k = m, n, ka
        self.outputs = [self._make_output((d, m, n))]

    def apply(self, params, xs, *, training=False, rng=None):
        a, b = xs
        cdt = self.model.compute_dtype
        ca = 1 if self.trans_a else 2   # contraction dim of a
        cb = 2 if self.trans_b else 1   # contraction dim of b
        out = lax.dot_general(
            a.astype(cdt), b.astype(cdt),
            dimension_numbers=(((ca,), (cb,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        return [out.astype(a.dtype)]

    def candidate_parallel_configs(self, num_devices, feasible_degrees):
        # batch-dim parallel only, like the reference DLRM strategies
        out = []
        for d in feasible_degrees:
            if d <= num_devices:
                out.append(ParallelConfig((d, 1, 1)))
        return out

    def flops_per_sample(self) -> float:
        # per batch element of dim 0
        return 2.0 * self.m * self.n * self.k
